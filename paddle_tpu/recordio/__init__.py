"""RecordIO: chunked record container (writer + fault-tolerant scanner).

Parity: reference paddle/fluid/recordio/ (C++ chunk/header/writer/scanner)
and its recordio Python bindings.  The hot path is the C++ implementation
(recordio.cc, built lazily with g++ and loaded over ctypes); a pure-Python
codec of the SAME on-disk format is the fallback and the cross-check —
files written by either implementation are readable by both.

Format (little-endian; see recordio.cc header comment):
  chunk  := magic:u32 compressor:u32 num_records:u32
            uncompressed_len:u32 stored_len:u32 crc32:u32 payload
  payload (zlib per chunk by default) := { len:u32 bytes } * num_records
Corrupt or truncated chunks are skipped on read (the reference's
fault-tolerant scanner behavior, recordio/README.md).
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import zlib

__all__ = ["Writer", "Scanner", "write_records", "read_records",
           "native_available"]

MAGIC = 0x54505231
NO_COMPRESS = 0
ZLIB = 2

_HEADER = struct.Struct("<6I")

_lib = None
_lib_tried = False


def _load_native():
    """Build (once) and load librecordio.so; None if no toolchain."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "recordio.cc")
    so = os.path.join(here, "librecordio.so")
    try:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", so + ".tmp", src,
                 "-lz"], check=True, capture_output=True)
            os.replace(so + ".tmp", so)
        lib = ctypes.CDLL(so)
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                        ctypes.c_uint32]
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32]
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_next.restype = ctypes.c_int64
        lib.rio_next.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_char_p)]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def native_available():
    return _load_native() is not None


class Writer:
    """Append records to a recordio file; chunks flush every
    ``max_chunk_records`` records (or ~1MB) and on close."""

    def __init__(self, path, compressor=ZLIB, max_chunk_records=1000,
                 use_native=True):
        self._native = _load_native() if use_native else None
        self._path = path
        self._compressor = compressor
        self._max = max_chunk_records
        if self._native is not None:
            self._h = self._native.rio_writer_open(
                os.fsencode(path), compressor, max_chunk_records)
            if not self._h:
                raise IOError("cannot open %s for writing" % path)
        else:
            self._f = open(path, "wb")
            self._buf = []
            self._buf_bytes = 0

    def write(self, record):
        if not isinstance(record, (bytes, bytearray)):
            raise TypeError("record must be bytes, got %s" % type(record))
        if self._native is not None:
            self._native.rio_write(self._h, bytes(record), len(record))
            return
        self._buf.append(bytes(record))
        self._buf_bytes += len(record) + 4
        if len(self._buf) >= self._max or self._buf_bytes >= (1 << 20):
            self._flush()

    def _flush(self):
        if not self._buf:
            return
        raw = b"".join(struct.pack("<I", len(r)) + r for r in self._buf)
        stored = zlib.compress(raw) if self._compressor == ZLIB else raw
        crc = zlib.crc32(stored) & 0xFFFFFFFF
        self._f.write(_HEADER.pack(MAGIC, self._compressor, len(self._buf),
                                   len(raw), len(stored), crc))
        self._f.write(stored)
        self._buf = []
        self._buf_bytes = 0

    def close(self):
        if self._native is not None:
            if self._h:
                self._native.rio_writer_close(self._h)
                self._h = None
        else:
            self._flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Scanner:
    """Iterate records; corrupt/truncated chunks are skipped."""

    def __init__(self, path, use_native=True):
        self._native = _load_native() if use_native else None
        if self._native is not None:
            self._h = self._native.rio_scanner_open(os.fsencode(path))
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "rb")
            self._chunk_iter = None

    def __iter__(self):
        if self._native is not None:
            out = ctypes.c_char_p()
            while True:
                n = self._native.rio_next(self._h, ctypes.byref(out))
                if n < 0:
                    return
                yield ctypes.string_at(out, n)
        else:
            while True:
                head = self._f.read(_HEADER.size)
                if len(head) < _HEADER.size:
                    return
                magic, comp, nrec, raw_len, stored_len, crc = \
                    _HEADER.unpack(head)
                if magic != MAGIC:
                    return  # out of sync: stop
                stored = self._f.read(stored_len)
                if len(stored) < stored_len:
                    return  # truncated tail
                if (zlib.crc32(stored) & 0xFFFFFFFF) != crc:
                    continue  # corrupt chunk: skip
                raw = zlib.decompress(stored) if comp == ZLIB else stored
                pos = 0
                for _ in range(nrec):
                    if pos + 4 > len(raw):
                        break
                    (ln,) = struct.unpack_from("<I", raw, pos)
                    pos += 4
                    yield raw[pos:pos + ln]
                    pos += ln

    def close(self):
        if self._native is not None:
            if self._h:
                self._native.rio_scanner_close(self._h)
                self._h = None
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path, records, **kwargs):
    with Writer(path, **kwargs) as w:
        for r in records:
            w.write(r)


def read_records(path, **kwargs):
    with Scanner(path, **kwargs) as s:
        for r in s:
            yield r
