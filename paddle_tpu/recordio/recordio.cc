// RecordIO: chunked record container with per-chunk CRC + compression.
//
// Role parity: reference paddle/fluid/recordio/{header,chunk,writer,
// scanner}.{h,cc} — re-designed, not ported: one flat C API (consumed from
// Python over ctypes instead of pybind), zlib instead of snappy (always
// present next to a C++ toolchain), and corrupt/truncated tail chunks are
// skipped on read exactly like the reference's fault-tolerant scanner.
//
// On-disk layout, little-endian:
//   chunk := header payload
//   header := magic:u32 compressor:u32 num_records:u32
//             uncompressed_len:u32 stored_len:u32 crc32:u32
//   payload (after optional zlib) := { len:u32 bytes[len] } * num_records
//
// crc32 is over the STORED (possibly compressed) payload bytes, so a
// truncated write is detected without decompressing.
//
// Build: g++ -O2 -shared -fPIC -o librecordio.so recordio.cc -lz

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x54505231;  // "TPR1"

enum Compressor : uint32_t {
  kNoCompress = 0,
  kZlib = 2,  // value matches the reference's kGzip slot
};

struct Writer {
  FILE* f = nullptr;
  uint32_t compressor = kZlib;
  size_t max_records = 1000;
  size_t max_bytes = 1 << 20;
  std::string buf;          // concatenated {len,bytes} records
  uint32_t num_records = 0;

  void flush_chunk() {
    if (num_records == 0) return;
    std::string stored;
    if (compressor == kZlib) {
      uLongf cap = compressBound(buf.size());
      stored.resize(cap);
      if (compress2(reinterpret_cast<Bytef*>(&stored[0]), &cap,
                    reinterpret_cast<const Bytef*>(buf.data()), buf.size(),
                    Z_DEFAULT_COMPRESSION) != Z_OK) {
        stored = buf;  // fall back to raw on any zlib failure
      } else {
        stored.resize(cap);
      }
    } else {
      stored = buf;
    }
    uint32_t crc =
        crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
              stored.size());
    uint32_t header[6] = {kMagic,
                          compressor,
                          num_records,
                          static_cast<uint32_t>(buf.size()),
                          static_cast<uint32_t>(stored.size()),
                          crc};
    fwrite(header, sizeof(header), 1, f);
    fwrite(stored.data(), 1, stored.size(), f);
    buf.clear();
    num_records = 0;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::string chunk;        // decompressed current chunk payload
  size_t pos = 0;           // cursor within chunk
  uint32_t remaining = 0;   // records left in current chunk
  std::string record;       // last record handed out

  bool load_next_chunk() {
    for (;;) {
      uint32_t header[6];
      if (fread(header, sizeof(header), 1, f) != 1) return false;  // EOF
      if (header[0] != kMagic) return false;  // stream out of sync: stop
      uint32_t compressor = header[1];
      uint32_t nrec = header[2];
      uint32_t raw_len = header[3];
      uint32_t stored_len = header[4];
      uint32_t crc = header[5];
      std::string stored(stored_len, '\0');
      if (stored_len > 0 &&
          fread(&stored[0], 1, stored_len, f) != stored_len)
        return false;  // truncated tail chunk: skip (fault tolerance)
      if (crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
                stored.size()) != crc)
        continue;  // corrupt chunk: skip to the next one
      if (compressor == kZlib) {
        chunk.resize(raw_len);
        uLongf out_len = raw_len;
        if (uncompress(reinterpret_cast<Bytef*>(&chunk[0]), &out_len,
                       reinterpret_cast<const Bytef*>(stored.data()),
                       stored.size()) != Z_OK)
          continue;
        chunk.resize(out_len);
      } else {
        chunk = std::move(stored);
      }
      pos = 0;
      remaining = nrec;
      if (remaining > 0) return true;
    }
  }

  // returns length or -1 at EOF; record bytes stay valid until next call
  int64_t next() {
    while (remaining == 0) {
      if (!load_next_chunk()) return -1;
    }
    if (pos + 4 > chunk.size()) return -1;  // malformed: stop
    uint32_t len;
    memcpy(&len, chunk.data() + pos, 4);
    pos += 4;
    if (pos + len > chunk.size()) return -1;
    record.assign(chunk, pos, len);
    pos += len;
    remaining--;
    return static_cast<int64_t>(len);
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, uint32_t compressor,
                      uint32_t max_records) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  if (max_records > 0) w->max_records = max_records;
  return w;
}

int rio_write(void* h, const char* buf, uint32_t len) {
  Writer* w = static_cast<Writer*>(h);
  uint32_t le_len = len;
  w->buf.append(reinterpret_cast<const char*>(&le_len), 4);
  w->buf.append(buf, len);
  w->num_records++;
  if (w->num_records >= w->max_records || w->buf.size() >= w->max_bytes)
    w->flush_chunk();
  return 0;
}

void rio_writer_close(void* h) {
  Writer* w = static_cast<Writer*>(h);
  w->flush_chunk();
  fclose(w->f);
  delete w;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// returns record length, or -1 at EOF.  *out points at internal storage
// valid until the next call.
int64_t rio_next(void* h, const char** out) {
  Scanner* s = static_cast<Scanner*>(h);
  int64_t len = s->next();
  *out = (len >= 0) ? s->record.data() : nullptr;
  return len;
}

void rio_scanner_close(void* h) {
  Scanner* s = static_cast<Scanner*>(h);
  fclose(s->f);
  delete s;
}

}  // extern "C"
