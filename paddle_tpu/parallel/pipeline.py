"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

The reference's only model-parallel mechanism is the legacy per-layer
device assignment (--parallel_nn, gserver/gradientmachines/
ParallelNeuralNetwork.cpp) which pipelines layers across GPUs with
host-side threads.  TPU-native version: stage parameters are sharded over
the ``pp`` axis, microbatches stream through a shard_map loop and
activations hop stage-to-stage with ppermute over ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import pcast_varying, shard_map

__all__ = ["pipeline_apply"]


def _pipeline_shard(stage_params, x, axis_name, stage_fn):
    """Per-device body.  stage_params: [1, ...] (this stage's slice of the
    leading stage axis); x: [M, mb, ...] microbatches (replicated)."""
    p = lax.psum(1, axis_name)
    i = lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    m = x.shape[0]
    ev = jax.eval_shape(stage_fn, params, x[0])
    # carries start as constants; mark them device-varying for the scan
    state = pcast_varying(jnp.zeros(ev.shape, ev.dtype), axis_name)
    out = pcast_varying(jnp.zeros((m,) + ev.shape, ev.dtype), axis_name)
    perm = [(s, (s + 1) % p) for s in range(p)]

    def tick(carry, t):
        state, out = carry
        inp = jnp.where(i == 0,
                        x[jnp.clip(t, 0, m - 1)].astype(state.dtype), state)
        y = stage_fn(params, inp)
        done_idx = t - (p - 1)  # microbatch finishing at the last stage
        write = (i == p - 1) & (done_idx >= 0) & (done_idx < m)
        upd = lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(done_idx, 0, m - 1), 0)
        out = jnp.where(write, upd, out)
        state = lax.ppermute(y, axis_name, perm)
        return (state, out), None

    (state, out), _ = lax.scan(tick, (state, out), jnp.arange(m + p - 1))
    # all stages return the same result: broadcast last stage's buffer
    out = lax.psum(jnp.where(i == p - 1, out, jnp.zeros_like(out)),
                   axis_name)
    return out


def pipeline_apply(stage_params, microbatches, mesh, stage_fn,
                   axis_name="pp", batch_axis=None):
    """Run ``stage_fn(params_of_stage, x) -> y`` as a P-stage pipeline.

    stage_params: pytree whose leaves have leading dim P (one slice per
    stage), sharded over ``axis_name``.  microbatches: [M, mb, ...]
    replicated.  Returns [M, mb, ...] outputs (replicated).  All stages
    must map activations to the same shape/dtype.

    ``batch_axis``: optional second mesh axis carrying data parallelism
    — the microbatch dim (dim 1) shards over it, each dp slice runs its
    own pipeline over the shared (replicated-over-dp) stage weights,
    and the weight-gradient psum over dp is inserted by the shard_map
    transpose automatically.  The dp x pp composition the 8-device
    dryrun exercises (MESH_PROFILE r6)."""
    def leaf_spec(a):
        return P(axis_name, *([None] * (a.ndim - 1)))

    data_spec = P(None, batch_axis) if batch_axis else P()
    in_specs = (jax.tree.map(leaf_spec, stage_params), data_spec)
    fn = functools.partial(_pipeline_shard, axis_name=axis_name,
                           stage_fn=stage_fn)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=data_spec)(stage_params, microbatches)
