"""Sharding annotations on fluid programs."""
from __future__ import annotations

from paddle_tpu.fluid.layer_helper import LayerHelper

__all__ = ["shard_var", "sharding_constraint"]


def shard_var(var, spec):
    """Pin a variable's dims to mesh axes, e.g. shard_var(w, (None, "tp"))."""
    return var.set_sharding(spec)


def sharding_constraint(x, spec, name=None):
    """In-graph activation sharding constraint (the GSPMD escape hatch;
    becomes jax.lax.with_sharding_constraint under a Mesh, identity
    otherwise)."""
    helper = LayerHelper("sharding_constraint", **locals())
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sharding_constraint", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"spec": [a if a else "" for a in spec]})
    return out
