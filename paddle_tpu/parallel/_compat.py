"""jax version shims for the shard_map strategies.

The repo meets several jax versions: newer ones expose
``jax.shard_map`` with varying-manual-axes (vma) typing (``lax.pcast``,
``check_vma``); older ones only have
``jax.experimental.shard_map.shard_map`` with the replication-rule
checker (``check_rep``).  Resolve once here so ring/pipeline/moe code
stays version-agnostic.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map", "pcast_varying"]


def shard_map(fn, mesh, in_specs, out_specs, check=False):
    """``check=False`` disables whichever replication/vma checker this
    jax ships — the strategies' collectives (masked psum broadcasts,
    reverse all_to_all reconstructions) are replication-correct by
    construction but not inferable by either type system."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check else {"check_vma": False}
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
        except TypeError:
            pass   # jax with jax.shard_map but no check_vma kwarg
    from jax.experimental.shard_map import shard_map as esm

    kw = {} if check else {"check_rep": False}
    try:
        return esm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, **kw)
    except TypeError:
        return esm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def pcast_varying(x, axis_name):
    """Mark a constant as device-varying for the vma type system; a
    no-op on jax versions without lax.pcast (their shard_map has no vma
    typing to satisfy)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")
