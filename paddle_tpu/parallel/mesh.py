"""Device-mesh construction.

Replaces the reference's flat rank map (platform/nccl_helper.h:81
NCCLContextMap: rank = dev_id + trainer_id * ngpus) with a named,
multi-axis jax.sharding.Mesh over which all collectives are expressed.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "auto_mesh_axes"]


def make_mesh(axes, devices=None):
    """axes: dict axis-name -> size (insertion order = mesh order).
    devices: flat device list (default: all; CPU fallback when the default
    platform has too few)."""
    sizes = list(axes.values())
    n = int(np.prod(sizes))
    if devices is None:
        devices = jax.devices()
        if len(devices) < n:
            cpus = jax.devices("cpu")
            if len(cpus) >= n and devices and devices[0].platform != "cpu":
                import warnings
                warnings.warn(
                    "mesh %r needs %d devices but the default platform (%s) "
                    "has %d — falling back to %d host-CPU devices; the SPMD "
                    "program will run on CPU" % (axes, n, devices[0].platform,
                                                 len(devices), len(cpus)))
            devices = cpus
    if len(devices) < n:
        raise ValueError("mesh %r needs %d devices, have %d"
                         % (axes, n, len(devices)))
    arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def auto_mesh_axes(n_devices, prefer=("dp", "tp", "sp", "pp")):
    """Factor n_devices over the preferred axes, largest-first: spread
    factors of 2 across as many axes as possible so every strategy gets a
    non-trivial extent when the device count allows."""
    axes = {a: 1 for a in prefer}
    remaining = n_devices
    i = 0
    order = list(prefer)
    while remaining > 1:
        f = _smallest_prime_factor(remaining)
        axes[order[i % len(order)]] *= f
        remaining //= f
        i += 1
    return {a: s for a, s in axes.items()}


def _smallest_prime_factor(n):
    for p in (2, 3, 5, 7):
        if n % p == 0:
            return p
    d = 11
    while d * d <= n:
        if n % d == 0:
            return d
        d += 2
    return n
