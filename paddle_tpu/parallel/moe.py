"""Expert parallelism: top-1 routed mixture-of-experts FFN over an ``ep``
mesh axis.

The reference's sparse-scaling analog is the distributed lookup table
(transpiler/distribute_transpiler.py:611: rows sharded over pservers,
fetched via prefetch RPC).  TPU-native: experts are sharded over ``ep``;
tokens are dispatched to their expert's device with all_to_all over ICI,
transformed, and combined back — no parameter server in the hot path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["moe_ffn", "emit_router_stats"]


def _metrics_on():
    from paddle_tpu.core.flags import FLAGS

    return bool(FLAGS.moe_metrics)


def _note_stats(tokens, idx, load, dropped, entropy):
    """Host side of the routing-stats callback (ISSUE 15 MoE rider):
    feed the always-on metrics registry.  Only ring/ep position 0
    reports — under a pure ep mesh every shard routes the SAME
    replicated tokens, so emitting from all of them would multiply
    the counts (dp shards each carry idx 0 for their ep row and land
    as independent samples, which is what we want)."""
    if int(np.asarray(idx)) != 0:
        return
    from paddle_tpu.observability import metrics

    load = np.asarray(load)
    hist = metrics.histogram(
        "moe_expert_load_tokens",
        "tokens routed to one expert in one step (pre-capacity): the "
        "per-expert load distribution — a balanced router keeps the "
        "spread tight")
    for c in load:
        hist.observe(float(c))
    dropped = float(np.asarray(dropped))
    metrics.gauge("moe_dropped_token_frac",
                  "fraction of tokens dropped by expert capacity in "
                  "the latest routed step").set(dropped)
    metrics.gauge("moe_router_entropy",
                  "mean per-token entropy of the router softmax in "
                  "the latest routed step (nats; ln(E) = uniform)"
                  ).set(float(np.asarray(entropy)))
    metrics.counter("moe_tokens_total",
                    "tokens routed through moe_ffn").inc(tokens)
    metrics.counter("moe_dropped_tokens_total",
                    "tokens dropped by expert capacity").inc(
                        int(round(dropped * tokens)))
    metrics.counter("moe_router_steps_total",
                    "moe_ffn routed steps observed").inc(1)


def emit_router_stats(gates, expert, keep, shard_idx=0):
    """Emit capacity-factor routing stats from inside a traced
    computation: per-expert load, dropped-token fraction, router
    entropy -> the always-on metrics registry (jax.debug.callback, one
    [E]+2-scalar transfer per step; FLAGS_moe_metrics gates the
    callback out of the program entirely).  ``gates`` [T, E] softmax
    output, ``expert`` [T] argmax routing, ``keep`` [T] bool kept
    mask, ``shard_idx`` the ep ring position (only 0 reports)."""
    if not _metrics_on():
        return
    e = gates.shape[-1]
    load = jnp.sum(jax.nn.one_hot(expert, e, dtype=jnp.int32), axis=0)
    dropped = 1.0 - keep.astype(jnp.float32).mean()
    entropy = -(gates * jnp.log(jnp.clip(gates, 1e-20, None))
                ).sum(-1).mean()
    jax.debug.callback(
        functools.partial(_note_stats, int(gates.shape[0])),
        shard_idx, load, dropped, entropy)


def _moe_shard(x, wg, w1, w2, axis_name, capacity_factor):
    """x: [T_local, D] tokens; wg: [D, E] router; w1: [E_local, D, F],
    w2: [E_local, F, D] expert weights (E = E_local * ep_size)."""
    p = lax.psum(1, axis_name)
    t, d = x.shape
    e_local = w1.shape[0]
    e = e_local * p

    gates = jax.nn.softmax(x @ wg, axis=-1)           # [T, E]
    expert = jnp.argmax(gates, axis=-1)               # [T]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]

    cap = max(1, int(capacity_factor * t / e))        # tokens/expert/device
    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.int32)       # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1             # [T, E]
    pos_tok = jnp.max(pos, axis=1)                            # [T]
    keep = (pos_tok >= 0) & (pos_tok < cap)
    emit_router_stats(gates, expert, keep,
                      shard_idx=lax.axis_index(axis_name))
    # dispatch buffer [E, cap, D]
    disp = jnp.zeros((e, cap, d), x.dtype)
    disp = disp.at[expert, jnp.clip(pos_tok, 0, cap - 1)].add(
        jnp.where(keep[:, None], x, 0.0))
    # [E, cap, D] -> [p, E_local, cap, D] -> all_to_all over ep
    disp = disp.reshape(p, e_local, cap, d)
    recv = lax.all_to_all(disp, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)                # [p, E_local, cap, D]
    recv = jnp.swapaxes(recv, 0, 1).reshape(e_local, p * cap, d)
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", recv, w1))
    y = jnp.einsum("ecf,efd->ecd", h, w2)             # [E_local, p*cap, D]
    y = jnp.swapaxes(y.reshape(e_local, p, cap, d), 0, 1)  # [p,E_local,cap,D]
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)                # [p, E_local, cap, D]
    back = back.reshape(e, cap, d)
    out = back[expert, jnp.clip(pos_tok, 0, cap - 1)]  # [T, D]
    return jnp.where(keep[:, None], out * gate[:, None], 0.0)


def moe_ffn(x, router_w, w1, w2, mesh, axis_name="ep", dp_axis=None,
            capacity_factor=2.0):
    """Top-1 MoE FFN.  x: [T, D] (T sharded over dp_axis if given);
    router_w: [D, E] replicated; w1: [E, D, F], w2: [E, F, D] sharded on
    the expert dim over ``axis_name``.  Returns [T, D] like x."""
    xspec = P(dp_axis, None)
    espec = P(axis_name, None, None)
    fn = functools.partial(_moe_shard, axis_name=axis_name,
                           capacity_factor=capacity_factor)
    # When tokens are replicated over the ep axis (dp_axis=None), every
    # shard reconstructs the full [T, D] output after the reverse
    # all_to_all, so the result is replicated — but the vma type system
    # cannot infer that through the collectives; the check is disabled.
    from ._compat import shard_map
    return shard_map(
        fn, mesh=mesh,
        in_specs=(xspec, P(None, None), espec, espec),
        out_specs=xspec)(x, router_w, w1, w2)
