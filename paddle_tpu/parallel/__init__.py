"""Parallelism package — the TPU-native counterpart of the reference's
multi-device machinery (framework/details/ SSA graph + NCCL op handles,
transpiler/distribute_transpiler.py).

Three levels:

- ``mesh``   : device-mesh construction helpers (axes: dp/tp/pp/sp/ep).
- ``api``    : sharding annotations on fluid programs (parameters via
               ParamAttr(sharding=...) / Variable.set_sharding, activations
               via sharding_constraint op) — compiled by GSPMD, which
               inserts the collectives the reference implemented as
               AllReduce/Broadcast/Gather op handles.
- ``ring`` / ``pipeline`` / ``moe``: explicit shard_map strategies for the
  parts GSPMD cannot express alone — ring attention (sequence/context
  parallelism), GPipe-style pipeline parallelism, expert parallelism.
- ``spmd``  : the elastic SPMD runtime (ISSUE 20) that unifies the
  above: ShardingPass assigns/propagates per-VarDesc annotations, a
  measured-cost search (auto_shard) picks the placement, and reshard()
  re-lowers the same program for a grown/shrunk mesh mid-job.
"""
from .mesh import make_mesh, auto_mesh_axes  # noqa: F401
from .api import shard_var, sharding_constraint  # noqa: F401
from .ring import (ring_attention, ring_attention_fwd_lse,  # noqa: F401
                   ring_attention_bwd, causal_step_counts)
from .pipeline import pipeline_apply  # noqa: F401
from .moe import moe_ffn, emit_router_stats  # noqa: F401
from .spmd import (ShardingPass, CostModel, Placement,  # noqa: F401
                   auto_shard, apply_placement, annotate_program,
                   placement_for, enumerate_strategies, strategy_name,
                   infer_mesh_axes, assign_pipeline_stages,
                   check_reshard_pair, reshard)

__all__ = ["make_mesh", "auto_mesh_axes", "shard_var",
           "sharding_constraint", "ring_attention",
           "ring_attention_fwd_lse", "ring_attention_bwd",
           "causal_step_counts", "pipeline_apply", "moe_ffn",
           "emit_router_stats", "ShardingPass", "CostModel",
           "Placement", "auto_shard", "apply_placement",
           "annotate_program", "placement_for",
           "enumerate_strategies", "strategy_name",
           "infer_mesh_axes", "assign_pipeline_stages",
           "check_reshard_pair", "reshard"]
