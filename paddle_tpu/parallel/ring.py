"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context story (SURVEY §5.7: the reference's is LoDTensor ragged
batching — it predates sequence parallelism; this is the first-class
TPU-native mechanism).  Q/K/V live sharded on the sequence dim over the
``sp`` axis; each device folds one K/V block at a time into a flash
online-softmax carry while blocks rotate around the ring via ppermute
over ICI (Liu et al., "Ring Attention with Blockwise Transformers").

ISSUE 15 rebuilt the hot path on kernels/flash_attention.py's
chunk-carry form:

- **Tiled inner compute.**  Each ring step is ONE
  ``flash_attention_chunk`` call — the (m, l, acc) online-softmax carry
  threads across steps and no dense [Sq_local, Sk_local] score block
  ever materializes in HBM (the blockwise XLA fallback is
  memory-bounded too, so CPU parity transfers).
- **Double-buffered rotation.**  The ``ppermute`` for block j+1 is
  issued BEFORE block j's compute; the collective has no data
  dependency on the running chunk so the latency-hiding scheduler
  overlaps it (FLAGS_xla_latency_hiding_scheduler; the
  tools/longctx_bench.py HLO inventory verifies the structure).
- **Causal block skipping.**  The ring loop is Python-unrolled (p is
  static): step 0 is the diagonal chunk (causal mask, always live) and
  every later step is a ``lax.cond`` on the ring-position predicate —
  a K/V block entirely in this shard's future skips its FLOPs at
  runtime, not just its probability mass (~(p+1)/2p of the dense step
  count at causal; ``causal_step_counts`` is the measured evidence).
- **A real backward.**  ``ring_attention`` carries a custom_vjp: the
  forward saves the per-shard log-sum-exp, and the backward runs a
  REVERSE-direction ring — the (q, dO, lse, delta) package rotates
  while K/V and their gradient accumulators stay device-resident, P is
  rebuilt per chunk from the saved lse (no forward recompute), and the
  travelling dQ returns home after a full cycle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.kernels.flash_attention import (
    NEG_INF, chunk_finalize, flash_attention_chunk,
    flash_attention_chunk_bwd)
from paddle_tpu.observability.trace import traced as _traced

__all__ = ["ring_attention", "ring_attention_fwd_lse",
           "ring_attention_bwd", "causal_step_counts"]


def _step_live(j, my, p, causal, direction):
    """Liveness of ring step ``j`` on the device at ring position
    ``my`` — (static_live, traced_pred).  Static True for the diagonal
    step and every non-causal step; otherwise the block-index
    predicate that drives causal skipping.

    forward: after j forward rotations the local K/V block came from
    shard (my - j) mod p; it is entirely in the past iff j <= my.
    backward: after j reverse rotations the visiting Q package came
    from shard (my + j) mod p; it is at-or-after the local K/V block
    iff j < p - my.
    """
    if j == 0 or not causal:
        return True, None
    if direction == "fwd":
        return False, j <= my
    return False, j < p - my


def _ring_fwd_shard(q, k, v, *, axis_name, causal, scale, block_q,
                    block_k, force_xla, interpret):
    """Per-shard forward under shard_map.  q,k,v: [B, H, S_local, D];
    returns (out [B,H,S,D], lse [B,H,S] f32)."""
    p = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]
    chunk = functools.partial(flash_attention_chunk, scale=scale,
                              block_q=block_q, block_k=block_k,
                              force_xla=force_xla, interpret=interpret)
    k_cur, v_cur = k, v
    for j in range(p):
        if j + 1 < p:
            # double-buffer: the rotation feeding step j+1 is issued
            # BEFORE step j's compute — no data dependency between
            # them, so the collective hides under the chunk
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        live, pred = _step_live(j, my, p, causal, "fwd")
        if live:
            m, l, acc = chunk(q, k_cur, v_cur, m, l, acc,
                              causal=(causal and j == 0))
        else:
            # causal block skipping: the whole K/V block is in this
            # shard's future — skip its FLOPs, not just its mass
            m, l, acc = lax.cond(
                pred,
                lambda mla, _k=k_cur, _v=v_cur:
                    chunk(q, _k, _v, *mla, causal=False),
                lambda mla: mla,
                (m, l, acc))
        if j + 1 < p:
            k_cur, v_cur = k_nxt, v_nxt
    return chunk_finalize(m, l, acc, q.dtype)


def _ring_bwd_shard(q, k, v, out, lse, do, *, axis_name, causal, scale,
                    block_q, block_k, force_xla, interpret):
    """Per-shard backward: reverse-direction ring over the saved lse.
    K/V and their gradient accumulators stay home; the (q, dO, lse,
    delta, dQ) package rotates.  No forward recompute anywhere."""
    p = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dq = jnp.zeros(q.shape, jnp.float32)
    rev = [(i, (i - 1) % p) for i in range(p)]
    chunk_bwd = functools.partial(flash_attention_chunk_bwd, scale=scale,
                                  block_q=block_q, block_k=block_k,
                                  force_xla=force_xla,
                                  interpret=interpret)
    q_cur, do_cur, lse_cur, delta_cur = q, do, lse, delta
    for j in range(p):
        if j + 1 < p:
            # prefetch the next Q package (not dq — THIS step's compute
            # still contributes to it before it moves on)
            q_nxt = lax.ppermute(q_cur, axis_name, rev)
            do_nxt = lax.ppermute(do_cur, axis_name, rev)
            lse_nxt = lax.ppermute(lse_cur, axis_name, rev)
            delta_nxt = lax.ppermute(delta_cur, axis_name, rev)

        def upd(args, _q=q_cur, _do=do_cur, _lse=lse_cur,
                _delta=delta_cur, _j=j):
            dq_a, dk_a, dv_a = args
            dqj, dkj, dvj = chunk_bwd(_q, k, v, _do, _lse, _delta,
                                      causal=(causal and _j == 0))
            return (dq_a + dqj.astype(jnp.float32),
                    dk_a + dkj.astype(jnp.float32),
                    dv_a + dvj.astype(jnp.float32))

        live, pred = _step_live(j, my, p, causal, "bwd")
        if live:
            dq, dk, dv = upd((dq, dk, dv))
        else:
            dq, dk, dv = lax.cond(pred, upd, lambda args: args,
                                  (dq, dk, dv))
        # the travelling dQ rotates AFTER every step (including the
        # last: p reverse rotations bring each shard's dQ home)
        dq = lax.ppermute(dq, axis_name, rev)
        if j + 1 < p:
            q_cur, do_cur = q_nxt, do_nxt
            lse_cur, delta_cur = lse_nxt, delta_nxt
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _specs(batch_axis, head_axis, axis_name):
    qspec = P(batch_axis, head_axis, axis_name, None)
    rspec = P(batch_axis, head_axis, axis_name)
    return qspec, rspec


def _shard_fns(mesh, axis_name, causal, scale, batch_axis, head_axis,
               block_q, block_k, force_xla, interpret):
    from ._compat import shard_map

    qspec, rspec = _specs(batch_axis, head_axis, axis_name)
    fwd = functools.partial(_ring_fwd_shard, axis_name=axis_name,
                            causal=causal, scale=scale, block_q=block_q,
                            block_k=block_k, force_xla=force_xla,
                            interpret=interpret)
    bwd = functools.partial(_ring_bwd_shard, axis_name=axis_name,
                            causal=causal, scale=scale, block_q=block_q,
                            block_k=block_k, force_xla=force_xla,
                            interpret=interpret)
    fwd_sm = shard_map(fwd, mesh=mesh, in_specs=(qspec, qspec, qspec),
                       out_specs=(qspec, rspec))
    bwd_sm = shard_map(bwd, mesh=mesh,
                       in_specs=(qspec, qspec, qspec, qspec, rspec,
                                 qspec),
                       out_specs=(qspec, qspec, qspec))
    return fwd_sm, bwd_sm


@_traced("pallas.ring_attention",
         lambda q, *a, **kw: {"q": str(q.shape)})
def ring_attention_fwd_lse(q, k, v, mesh, axis_name="sp", causal=True,
                           scale=None, batch_axis=None, head_axis=None,
                           block_q=None, block_k=None, force_xla=False,
                           interpret=False):
    """Forward returning ``(out, lse)`` — the op-level residual form.

    ``lse`` is the REAL per-position log-sum-exp ([B, H, S] f32, S
    sharded like q): with it saved as an op output the grad op runs
    ``ring_attention_bwd`` directly instead of re-executing the forward
    inside a generic vjp (MIGRATION.md "Ring attention" note)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    fwd_sm, _ = _shard_fns(mesh, axis_name, causal, scale, batch_axis,
                           head_axis, block_q, block_k, force_xla,
                           interpret)
    return fwd_sm(q, k, v)


@_traced("pallas.ring_attention_bwd",
         lambda q, *a, **kw: {"q": str(q.shape)})
def ring_attention_bwd(q, k, v, out, lse, do, mesh, axis_name="sp",
                       causal=True, scale=None, batch_axis=None,
                       head_axis=None, block_q=None, block_k=None,
                       force_xla=False, interpret=False):
    """Backward from op-level residuals: (dq, dk, dv) via the
    reverse-direction ring over the saved lse.  No forward
    re-execution."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    _, bwd_sm = _shard_fns(mesh, axis_name, causal, scale, batch_axis,
                           head_axis, block_q, block_k, force_xla,
                           interpret)
    return bwd_sm(q, k, v, out, lse, do)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=True, scale=None,
                   batch_axis=None, head_axis=None, block_q=None,
                   block_k=None, force_xla=False, interpret=False):
    """q,k,v: [B, H, S, D] global; S sharded over ``axis_name`` (B over
    ``batch_axis``, H over ``head_axis`` — tensor parallelism composes
    for free since heads are independent).  Returns [B, H, S, D] with
    the same sharding.  Differentiable: the custom_vjp replays the
    saved-lse reverse ring (no forward recompute, no [S, S] block)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    fwd_sm, bwd_sm = _shard_fns(mesh, axis_name, causal, scale,
                                batch_axis, head_axis, block_q, block_k,
                                force_xla, interpret)

    @jax.custom_vjp
    def _ring(q, k, v):
        out, _ = fwd_sm(q, k, v)
        return out

    def _fwd(q, k, v):
        out, lse = fwd_sm(q, k, v)
        return out, (q, k, v, out, lse)

    def _bwd(res, g):
        q, k, v, out, lse = res
        return bwd_sm(q, k, v, out, lse, g)

    _ring.defvjp(_fwd, _bwd)
    return _ring(q, k, v)


def causal_step_counts(mesh, axis_name="sp", causal=True,
                       direction="fwd"):
    """Executed-chunk count per ring position ([p] int32) — the causal
    block-skipping evidence, from the SAME liveness predicate the real
    loops branch on (``_step_live``).  Causal at p devices sums to
    p*(p+1)/2 executed chunks vs p*p dense — ~2x fewer at p=8."""
    from ._compat import shard_map

    p = dict(mesh.shape)[axis_name]

    def body(x):
        my = lax.axis_index(axis_name)
        c = jnp.zeros((1,), jnp.int32)
        for j in range(p):
            live, pred = _step_live(j, my, p, causal, direction)
            if live:
                c = c + 1
            else:
                c = lax.cond(pred, lambda c: c + 1, lambda c: c, c)
        return c

    counts = shard_map(body, mesh=mesh, in_specs=(P(axis_name),),
                       out_specs=P(axis_name))(
                           jnp.zeros((p,), jnp.float32))
    return counts
