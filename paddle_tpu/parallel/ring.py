"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context story (SURVEY §5.7: the reference's is LoDTensor ragged
batching — it predates sequence parallelism; this is the first-class
TPU-native mechanism).  Q/K/V live sharded on the sequence dim over the
``sp`` axis; each device computes attention of its Q shard against one K/V
shard at a time with an online-softmax accumulator while K/V blocks rotate
around the ring via ppermute over ICI — compute overlaps the collective
and the full S×S score matrix never materializes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention"]


def _ring_attention_shard(q, k, v, axis_name, causal, scale):
    """Per-shard body under shard_map.  q,k,v: [B, H, S_local, D]."""
    p = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    sq = q.shape[2]
    sk = k.shape[2]
    qpos = my * sq + jnp.arange(sq)  # global positions of local queries

    def step(carry, j):
        k_blk, v_blk, m, num, den = carry
        src = (my - j) % p  # which shard this K/V block came from
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            kpos = src * sk + jnp.arange(sk)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        # new_m can stay -inf for fully-masked rows; keep exp() finite
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m) - safe_m)
        e = jnp.exp(s - safe_m)
        num = num * corr + jnp.einsum("bhqk,bhkd->bhqd", e, v_blk)
        den = den * corr + jnp.sum(e, axis=-1, keepdims=True)
        perm = [(i, (i + 1) % p) for i in range(p)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, new_m, num, den), None

    # derive inits from q so their varying-axes match the step outputs
    # regardless of which mesh axes q is sharded over
    m0 = jnp.full_like(q[..., :1], -jnp.inf)
    num0 = jnp.zeros_like(q)
    den0 = jnp.zeros_like(q[..., :1])
    (k, v, m, num, den), _ = lax.scan(step, (k, v, m0, num0, den0),
                                      jnp.arange(p))
    return num / jnp.maximum(den, 1e-20)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=True, scale=None,
                   batch_axis=None, head_axis=None):
    """q,k,v: [B, H, S, D] global; S sharded over ``axis_name`` (B over
    ``batch_axis``, H over ``head_axis`` — tensor parallelism composes for
    free since heads are independent).  Returns [B, H, S, D] with the same
    sharding.  Differentiable (jax re-derives the reverse ring through the
    scan)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, head_axis, axis_name, None)
    fn = functools.partial(_ring_attention_shard, axis_name=axis_name,
                           causal=causal, scale=scale)
    from ._compat import shard_map
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
