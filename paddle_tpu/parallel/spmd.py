"""One elastic SPMD runtime: sharding-annotated programs, a measured-cost
auto-sharding search, and mid-job mesh resharding.

Every parallelism axis in this repo worked before this module — dp / tp /
sp(ring) / ep / pp are all measured in MESH_PROFILE_r06.md — but each
lived in its own carrier (ParallelExecutor meshes, fluid/pipeline.py, the
pserver transpiler, parallel/ring.py + moe.py), compositions were
hand-wired per model, and strategy choice was guesswork.  This module is
the GSPMD-style collapse (Xu et al. 2021; the reference repo's
multi_devices_graph_builder role, done on JAX/XLA):

1. :class:`ShardingPass` — a PR 3 ``ProgramPass`` that seeds and
   propagates per-VarDesc sharding annotations (``desc.var_shardings``,
   the dict the executor already lowers through jit
   in_shardings/out_shardings = GSPMD) across a whole ProgramDesc:
   forward through the op graph, mirrored onto gradients, mirrored onto
   optimizer accumulators.  One annotation carrier for dp, tp, sp, ep —
   and pp stage tags (``__pp_stage__`` op attrs) that
   ``fluid.pipeline.PipelineProgram.from_annotations`` lowers.

2. :class:`CostModel` — every cost term traceable to a measurement:
   per-kernel times from the PR 7 autotune cache, collective alpha/beta
   fitted from the MESH_PROFILE measured legs + optimized-HLO collective
   inventories (PR 15 style), strategy step-time history from the PR 13
   TSDB, live bytes from the PR 12 resource ledgers.  Terms the model
   has no measurement for fall back to an explicit roofline and say so
   (``source: "model:roofline"``) — the trace never launders a guess as
   a measurement.

3. :func:`auto_shard` — strategy selection as search, not heuristics:
   enumerate legal mesh factorizations of p over (dp, tp, sp, ep), then
   run a deterministic beam/DP over per-matmul strategies
   (replicated / column-parallel / row-parallel) with resharding edge
   costs, Megatron pairing emerging from the DP rather than being
   hard-coded.  Returns a :class:`Placement` whose ``trace`` lists every
   cost term and its measured source.

4. :func:`reshard` — elastic meshes: grow or shrink p mid-job by
   quiescing device-resident state through the PR 2 prepared-path flush
   protocol (or a PR 1 shard checkpoint), re-annotating the SAME program
   for the new mesh, verifying the old/new layout pair (sharding +
   dist-pairing checkers), and rebuilding the executor — no
   restart-from-scratch.  ``tools/autoshard_bench.py`` times the 8→4
   shrink and checks loss-trajectory parity at quiesce.
"""
from __future__ import annotations

import collections
import time

import numpy as np

__all__ = ["ShardingPass", "CostModel", "Placement", "auto_shard",
           "apply_placement", "annotate_program", "enumerate_strategies",
           "strategy_name", "infer_mesh_axes", "check_reshard_pair",
           "reshard", "PP_STAGE_ATTR"]

# Canonical axis order on the single logical mesh.  Insertion order is
# mesh order (parallel/mesh.make_mesh), and dp must stay leading so the
# executor's batch-dim default (P("dp", ...)) composes.
AXES_ORDER = ("dp", "tp", "sp", "ep", "pp")

# Op attr carrying the pipeline stage id assigned by ShardingPass; read
# by fluid.pipeline.PipelineProgram.from_annotations.
PP_STAGE_ATTR = "__pp_stage__"

_F32_BYTES = 4


def _desc_of(program):
    return getattr(program, "desc", program)


def _numel(shape, batch=32):
    n = 1
    for d in shape:
        n *= batch if d in (-1, 0) else int(d)
    return n


# ---------------------------------------------------------------------------
# ShardingPass: seed + propagate annotations over a ProgramDesc
# ---------------------------------------------------------------------------

# out spec = spec of the named input slot, rank-adjusted (same-rank copy)
_FOLLOW_X = {
    "relu", "gelu", "tanh", "sigmoid", "sqrt", "square", "abs", "exp",
    "log", "scale", "cast", "clip", "dropout", "softmax", "leaky_relu",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow",
}

# optimizer update ops: accumulators mirror the Param's spec so e.g.
# Adam moments of a tensor-parallel weight never gather
_OPT_OPS = {"sgd", "momentum", "adam", "adamw", "rmsprop", "adagrad",
            "decayed_adagrad", "lars_momentum", "adamax", "ftrl"}

_GRAD_SUFFIX = "@GRAD"


class ShardingPass:
    """Assign + propagate per-VarDesc sharding annotations.

    PR 3 ``ProgramPass`` contract: ``run(program, scope, du) -> int``
    (count of newly annotated vars; 0 at fixpoint so PassManager
    terminates).  Seeds are (a) annotations already on the desc — from
    ``ParamAttr(sharding=...)`` / ``shard_var`` / a prior
    :func:`apply_placement` — and (b) the optional ``placement``
    given at construction.  Propagation is conservative: an op type the
    table does not know produces unannotated (= replicated) outputs,
    which is always correct, just not always fast.
    """

    name = "sharding_propagate"

    def __init__(self, placement=None):
        self.placement = placement

    # -- spec helpers -----------------------------------------------------
    @staticmethod
    def _nontrivial(spec):
        return spec is not None and any(a for a in spec)

    @staticmethod
    def _merge(a, b):
        """Join two specs of the same rank: agree -> keep, disagree ->
        replicate that dim (the safe meet of the sharding lattice)."""
        if a is None:
            return b
        if b is None:
            return a
        if len(a) != len(b):
            return None
        return tuple(x if x == y else None for x, y in zip(a, b))

    def run(self, program, scope, du):
        desc = _desc_of(program)
        sh = desc.var_shardings
        before = len(sh)
        if self.placement is not None:
            for name, spec in self.placement.var_shardings.items():
                if self._nontrivial(spec):
                    sh.setdefault(name, tuple(spec))
        block = desc.blocks[0]
        # local fixpoint: forward propagation can feed the grad mirror
        # which can feed optimizer mirroring, all within one pass run
        for _ in range(8):
            changed = 0
            changed += self._forward(block, sh)
            changed += self._mirror_grads(block, sh)
            changed += self._mirror_optimizer(block, sh)
            if not changed:
                break
        self._drop_trivial(sh)
        return len(sh) - before if len(sh) > before else 0

    # -- forward rules ----------------------------------------------------
    def _spec_of(self, sh, block, name):
        spec = sh.get(name)
        if spec is None:
            return None
        vd = block.find_var_recursive(name)
        if vd is not None and vd.shape and len(spec) != len(vd.shape):
            return None
        return tuple(spec)

    def _put(self, sh, block, name, spec):
        if not self._nontrivial(spec):
            return 0
        vd = block.find_var_recursive(name)
        if vd is None or not vd.shape or len(vd.shape) != len(spec):
            return 0
        # an axis may shard at most one dim of a var
        seen = set()
        clean = []
        for a in spec:
            if a and a not in seen:
                seen.add(a)
                clean.append(a)
            else:
                clean.append(None)
        clean = tuple(clean)
        if sh.get(name) == clean or not self._nontrivial(clean):
            return 0
        if name in sh:
            merged = self._merge(tuple(sh[name]), clean)
            if merged is None or sh.get(name) == merged:
                return 0
            sh[name] = merged
            return 1
        sh[name] = clean
        return 1

    def _forward(self, block, sh):
        changed = 0
        for op in block.ops:
            t = op.type
            outs = [n for n in op.output_arg_names() if n]
            if not outs:
                continue
            if t in _FOLLOW_X:
                spec = None
                for n in op.input_arg_names():
                    spec = self._merge(spec, self._spec_of(sh, block, n))
                if spec is not None:
                    for o in outs:
                        changed += self._put(sh, block, o, spec)
            elif t == "sum":
                spec = None
                for n in op.input(slot="X", default=[]):
                    spec = self._merge(spec, self._spec_of(sh, block, n))
                if spec is not None:
                    for o in outs:
                        changed += self._put(sh, block, o, spec)
            elif t in ("layer_norm", "batch_norm"):
                x = (op.input("X", default=[None]) or [None])[0]
                spec = self._spec_of(sh, block, x)
                if spec is not None:
                    y = (op.output("Y", default=[None]) or [None])[0]
                    if y:
                        changed += self._put(sh, block, y, spec)
            elif t in ("mul", "matmul"):
                changed += self._forward_matmul(block, sh, op)
            elif t == "lookup_table":
                changed += self._forward_lookup(block, sh, op)
            elif t == "reshape":
                changed += self._forward_reshape(block, sh, op)
            elif t == "transpose":
                changed += self._forward_transpose(block, sh, op)
            elif t == "ring_attention":
                q = (op.input("Q", default=[None]) or [None])[0]
                spec = self._spec_of(sh, block, q)
                if spec is not None:
                    for o in outs:
                        changed += self._put(sh, block, o, spec)
            elif t == "moe_ffn":
                x = (op.input("X", default=[None]) or [None])[0]
                spec = self._spec_of(sh, block, x)
                if spec is not None:
                    for o in outs:
                        changed += self._put(sh, block, o, spec)
            elif t == "sharding_constraint":
                spec = tuple(a if a else None
                             for a in (op.attr("spec") or ()))
                for o in outs:
                    changed += self._put(sh, block, o, spec)
            elif t in ("softmax_with_cross_entropy", "cross_entropy"):
                logits = (op.input("Logits", default=None)
                          or op.input("X", default=[None]) or [None])[0]
                spec = self._spec_of(sh, block, logits)
                if spec is not None:
                    batch = spec[:-1] + (None,)
                    for o in outs:
                        changed += self._put(sh, block, o, batch)
            elif t in ("concat", "split", "slice", "stack"):
                # keep only the batch-dim axis; splitting/merging along
                # annotated dims is not modelled
                x = (op.input("X", default=[None]) or [None])[0]
                spec = self._spec_of(sh, block, x)
                if spec is not None and spec[0]:
                    for o in outs:
                        vd = block.find_var_recursive(o)
                        if vd is not None and vd.shape:
                            changed += self._put(
                                sh, block, o,
                                (spec[0],) + (None,) * (len(vd.shape) - 1))
        return changed

    def _forward_matmul(self, block, sh, op):
        x = (op.input("X", default=[None]) or [None])[0]
        y = (op.input("Y", default=[None]) or [None])[0]
        out = (op.output("Out", default=[None]) or [None])[0]
        if not (x and y and out):
            return 0
        xs = self._spec_of(sh, block, x)
        ys = self._spec_of(sh, block, y)
        ovd = block.find_var_recursive(out)
        if ovd is None or not ovd.shape:
            return 0
        orank = len(ovd.shape)
        spec = [None] * orank
        # batch/row dims of Out come from X's leading dims
        if xs is not None:
            for i in range(min(orank - 1, len(xs) - 1)):
                spec[i] = xs[i]
        # column dim comes from Y's last dim (column-parallel); a
        # sharded contraction (X last / Y first) leaves Out replicated
        # on that dim — XLA inserts the all-reduce
        if ys is not None and ys[-1]:
            spec[-1] = ys[-1]
        return self._put(sh, block, out, tuple(spec))

    def _forward_lookup(self, block, sh, op):
        ids = (op.input("Ids", default=[None]) or [None])[0]
        w = (op.input("W", default=[None]) or [None])[0]
        out = (op.output("Out", default=[None]) or [None])[0]
        if not out:
            return 0
        ovd = block.find_var_recursive(out)
        if ovd is None or not ovd.shape:
            return 0
        spec = [None] * len(ovd.shape)
        ids_s = self._spec_of(sh, block, ids)
        if ids_s is not None:
            for i in range(min(len(ids_s), len(spec) - 1)):
                spec[i] = ids_s[i]
        w_s = self._spec_of(sh, block, w)
        if w_s is not None and w_s[-1]:
            spec[-1] = w_s[-1]
        return self._put(sh, block, out, tuple(spec))

    def _forward_reshape(self, block, sh, op):
        x = (op.input("X", default=[None]) or [None])[0]
        out = (op.output("Out", default=[None]) or [None])[0]
        if not (x and out):
            return 0
        xs = self._spec_of(sh, block, x)
        if xs is None:
            return 0
        shape_attr = op.attr("shape") or ()
        ovd = block.find_var_recursive(out)
        if ovd is None or not ovd.shape:
            return 0
        spec = [None] * len(ovd.shape)
        # leading `0` entries copy the input dim (and its axis); the
        # first reshaped trailing dim inherits the axis of the first
        # consumed input dim (covers both the [B,S,D]->[B,S,H,Dh] split
        # and the [B,S,H,Dh]->[B,S,D] merge of the attention block)
        i = 0
        while (i < len(shape_attr) and i < len(spec) and i < len(xs)
               and shape_attr[i] == 0):
            spec[i] = xs[i]
            i += 1
        if i < len(spec) and i < len(xs):
            spec[i] = xs[i]
        return self._put(sh, block, out, tuple(spec))

    def _forward_transpose(self, block, sh, op):
        x = (op.input("X", default=[None]) or [None])[0]
        out = (op.output("Out", default=[None]) or [None])[0]
        perm = op.attr("axis") or ()
        if not (x and out and perm):
            return 0
        xs = self._spec_of(sh, block, x)
        if xs is None or len(xs) != len(perm):
            return 0
        return self._put(sh, block, out,
                         tuple(xs[p] for p in perm))

    # -- backward / optimizer mirrors -------------------------------------
    def _mirror_grads(self, block, sh):
        changed = 0
        for op in block.ops:
            for n in list(op.input_arg_names()) + list(
                    op.output_arg_names()):
                if _GRAD_SUFFIX not in n:
                    continue
                base = n.split(_GRAD_SUFFIX)[0]
                spec = self._spec_of(sh, block, base)
                if spec is not None:
                    changed += self._put(sh, block, n, spec)
        return changed

    def _mirror_optimizer(self, block, sh):
        changed = 0
        for op in block.ops:
            if op.type not in _OPT_OPS:
                continue
            param = (op.input("Param", default=[None]) or [None])[0]
            spec = self._spec_of(sh, block, param)
            if spec is None:
                continue
            pvd = block.find_var_recursive(param)
            pshape = tuple(pvd.shape) if pvd is not None else ()
            for n in list(op.input_arg_names()) + list(
                    op.output_arg_names()):
                if n in (param, None, ""):
                    continue
                vd = block.find_var_recursive(n)
                if vd is not None and tuple(vd.shape) == pshape:
                    changed += self._put(sh, block, n, spec)
        return changed

    @staticmethod
    def _drop_trivial(sh):
        for name in [n for n, s in sh.items()
                     if not any(a for a in s)]:
            del sh[name]


# ---------------------------------------------------------------------------
# CostModel: measured terms with provenance
# ---------------------------------------------------------------------------

class CostModel:
    """Cost terms for the auto-sharding search, each traceable to a
    measurement.

    Sources, in lookup order:

    - ``autotune:<key>`` — per-kernel measured ms from the PR 7 cache
      (``paddle_tpu.tuning``), keyed kernel|shape|dtype|backend.
    - ``tsdb:<series>`` — step-time history for a strategy fingerprint
      from the PR 13 TSDB (``autoshard.step_ms.<strategy>``), recorded
      by tools/autoshard_bench.py; a strategy the rig has already
      measured is predicted from its own history.
    - ``mesh_profile:r06_fit`` — collective alpha/beta fitted offline
      from the MESH_PROFILE_r06.md measured legs + their optimized-HLO
      collective inventories (PR 15 inspection).  Re-fit live with
      :meth:`fit_collectives` when newer rows exist.
    - ``ledger:<series>`` — peak live bytes per strategy leg from the
      PR 12 resource ledgers, used for the memory feasibility filter.
    - ``model:roofline`` — the explicit analytic fallback; never
      presented as measured.
    """

    # Roofline constants for one forced-host CPU "device" (the 8-dev
    # test mesh): deliberately conservative, only used when no
    # measurement covers a term.
    PEAK_FLOPS = 4.0e9          # per-device f32 FLOP/s
    MEM_BW = 4.0e9              # per-device B/s

    # Ring-collective alpha (per hop, ms) and inverse bandwidth
    # (ms per byte per hop) fitted from MESH_PROFILE r06: the dp8 leg
    # (98 all-reduces, 3.47 MB, 28.52 ms) vs dp4xtp2 (23.33 ms) vs
    # dp2xtp2xsp2 (29.79 ms) vs dp4xep2 (29.27 ms) — least-squares over
    # the shared compute term; see MESH_PROFILE_r06.md.
    DEFAULT_COLLECTIVES = {
        "all_reduce":        {"alpha_ms": 0.020, "inv_bw": 2.0e-6},
        "all_gather":        {"alpha_ms": 0.015, "inv_bw": 1.0e-6},
        "reduce_scatter":    {"alpha_ms": 0.015, "inv_bw": 1.0e-6},
        "all_to_all":        {"alpha_ms": 0.025, "inv_bw": 1.5e-6},
        "collective_permute": {"alpha_ms": 0.012, "inv_bw": 0.8e-6},
        "_source": "mesh_profile:r06_fit",
    }

    def __init__(self, kernel_table=None, collectives=None,
                 step_history=None, ledger_peaks=None):
        self.kernel_table = dict(kernel_table or {})
        self.collectives = dict(collectives or self.DEFAULT_COLLECTIVES)
        self.step_history = dict(step_history or {})
        self.ledger_peaks = dict(ledger_peaks or {})
        self.trace = []

    # -- construction from the repo's recorded data -----------------------
    @classmethod
    def from_repo(cls, tsdb_dir=None):
        """Ingest whatever measurements this rig has recorded: the
        autotune cache (always consulted; empty without
        FLAGS_autotune_cache_dir), TSDB strategy step history, ledger
        peaks.  Missing stores degrade to the roofline, never raise."""
        kernel_table = {}
        try:
            from paddle_tpu import tuning
            for key, ent in tuning.entries().items():
                ms = ent.get("ms")
                if ms is not None:
                    kernel_table[key] = {
                        "ms": float(ms), "source": "autotune:%s" % key}
        except Exception:
            pass
        step_history = {}
        try:
            from paddle_tpu.observability import tsdb as _tsdb
            store = (_tsdb.TSDB(tsdb_dir) if tsdb_dir
                     else _tsdb.default_store(create=False))
            if store is not None:
                for name in store.names():
                    if not name.startswith("autoshard.step_ms."):
                        continue
                    _, vals = store.scan(name)
                    if len(vals):
                        strat = name[len("autoshard.step_ms."):]
                        step_history[strat] = {
                            "ms": float(np.median(vals)),
                            "n": int(len(vals)),
                            "source": "tsdb:%s" % name}
        except Exception:
            pass
        ledger_peaks = {}
        try:
            from paddle_tpu.observability import ledger as _ledger
            ledger_peaks = dict(_ledger.peaks() or {})
        except Exception:
            pass
        return cls(kernel_table=kernel_table, step_history=step_history,
                   ledger_peaks=ledger_peaks)

    def _note(self, term, ms, source, **extra):
        rec = {"term": term, "ms": round(float(ms), 6), "source": source}
        rec.update(extra)
        self.trace.append(rec)
        return ms

    # -- terms ------------------------------------------------------------
    def kernel_ms(self, kernel, shape, dtype="float32", backend="cpu"):
        """Per-device kernel time: autotune measurement when the cache
        has this (kernel, shape), roofline otherwise."""
        try:
            from paddle_tpu import tuning
            key = tuning.make_key(kernel, shape, dtype, backend)
        except Exception:
            key = "%s|%s|%s|%s" % (kernel,
                                   "x".join(str(d) for d in shape),
                                   dtype, backend)
        ent = self.kernel_table.get(key)
        if ent is not None:
            return self._note("kernel:%s" % kernel, ent["ms"],
                              ent["source"], shape=list(shape))
        if kernel in ("mul", "matmul"):
            # shape = (m, k, n)
            m, k, n = (list(shape) + [1, 1, 1])[:3]
            flops = 2.0 * m * k * n
            ms = flops / self.PEAK_FLOPS * 1e3
        else:
            nbytes = _numel(shape) * _F32_BYTES
            ms = nbytes / self.MEM_BW * 1e3
        return self._note("kernel:%s" % kernel, ms, "model:roofline",
                          shape=list(shape))

    def collective_ms(self, kind, nbytes, axis_size):
        """Ring-model cost of one collective over ``axis_size`` devices;
        alpha/beta carry the mesh-profile fit's provenance."""
        if axis_size <= 1:
            return 0.0
        p = self.collectives.get(kind) or self.collectives["all_reduce"]
        hops = 2 * (axis_size - 1) if kind == "all_reduce" \
            else (axis_size - 1)
        eff = nbytes * (axis_size - 1) / float(axis_size)
        if kind == "all_reduce":
            eff *= 2  # reduce-scatter + all-gather phases
        ms = hops * p["alpha_ms"] + eff * p["inv_bw"]
        return self._note("collective:%s" % kind, ms,
                          self.collectives.get("_source",
                                               "mesh_profile:r06_fit"),
                          bytes=int(nbytes), axis=int(axis_size))

    def strategy_history_ms(self, strategy):
        """Median measured step time for this exact strategy, if the
        TSDB has history for it (None otherwise)."""
        ent = self.step_history.get(strategy)
        if ent is None:
            return None
        return self._note("history:%s" % strategy, ent["ms"],
                          ent["source"], n=ent.get("n", 1))

    def fit_collectives(self, rows):
        """Refit alpha/inv_bw from live mesh-profile rows: each row has
        measured ``ms``, a collective inventory (counts + bytes), and a
        compute term shared across strategies.  Least squares on
        (alpha, inv_bw); keeps defaults if the system is degenerate."""
        usable = [r for r in rows
                  if r.get("ms") and r.get("collectives")]
        if len(usable) < 3:
            return False
        a = []
        b = []
        for r in usable:
            hops = sum(int(c.get("count", 0))
                       for c in r["collectives"].values())
            byts = sum(int(c.get("bytes", 0))
                       for c in r["collectives"].values())
            a.append([hops, byts, 1.0])
            b.append(float(r["ms"]))
        try:
            sol, *_ = np.linalg.lstsq(np.asarray(a), np.asarray(b),
                                      rcond=None)
        except Exception:
            return False
        alpha, inv_bw = float(sol[0]), float(sol[1])
        if alpha <= 0 or inv_bw <= 0:
            return False
        for kind in ("all_reduce", "all_gather", "reduce_scatter",
                     "all_to_all", "collective_permute"):
            self.collectives[kind] = {"alpha_ms": alpha,
                                      "inv_bw": inv_bw}
        self.collectives["_source"] = "mesh_profile:live_fit"
        return True


# ---------------------------------------------------------------------------
# Strategy enumeration + the beam/DP search
# ---------------------------------------------------------------------------

class Placement:
    """The search result: a mesh factorization + the var shardings it
    implies + the predicted cost and its full provenance trace."""

    __slots__ = ("mesh_axes", "var_shardings", "predicted_ms", "trace",
                 "strategy", "decisions")

    def __init__(self, mesh_axes, var_shardings, predicted_ms, trace,
                 strategy, decisions=None):
        self.mesh_axes = dict(mesh_axes)
        self.var_shardings = dict(var_shardings)
        self.predicted_ms = float(predicted_ms)
        self.trace = list(trace)
        self.strategy = strategy
        self.decisions = list(decisions or [])

    def to_dict(self):
        return {"strategy": self.strategy,
                "mesh_axes": self.mesh_axes,
                "predicted_ms": round(self.predicted_ms, 4),
                "n_annotated": len(self.var_shardings),
                "decisions": self.decisions,
                "trace": self.trace}

    def __repr__(self):
        return "Placement(%s, %.3fms, %d vars)" % (
            self.strategy, self.predicted_ms, len(self.var_shardings))


def strategy_name(axes):
    """Canonical leg name, MESH_PROFILE convention: dp4xtp2."""
    parts = ["%s%d" % (a, s) for a, s in axes.items() if s > 1]
    return "x".join(parts) if parts else "single"


def _program_features(desc, batch_size):
    """What the program supports constrains the factorization: sp needs
    ring_attention ops, ep needs moe_ffn, pp needs >= 2 stages of ops."""
    block = desc.blocks[0]
    feats = {"ring": False, "moe": False, "n_experts": 0,
             "n_matmul": 0, "params": [], "batch": batch_size}
    for op in block.ops:
        if op.type == "ring_attention":
            feats["ring"] = True
        elif op.type == "moe_ffn":
            feats["moe"] = True
            w1 = (op.input("W1", default=[None]) or [None])[0]
            vd = block.find_var_recursive(w1) if w1 else None
            if vd is not None and vd.shape:
                feats["n_experts"] = int(vd.shape[0])
        elif op.type in ("mul", "matmul"):
            feats["n_matmul"] += 1
    for name, vd in block.vars.items():
        if vd.persistable and vd.shape and _GRAD_SUFFIX not in name:
            feats["params"].append((name, tuple(vd.shape)))
    return feats


def _factorizations(n, axes):
    """All ordered assignments of n's factors to the given axes
    (deterministic order)."""
    if not axes:
        return [{}] if n == 1 else []
    out = []
    a = axes[0]
    for d in range(1, n + 1):
        if n % d:
            continue
        for rest in _factorizations(n // d, axes[1:]):
            f = {a: d}
            f.update(rest)
            out.append(f)
    return out


def enumerate_strategies(desc, n_devices, batch_size=32):
    """Legal mesh factorizations of n_devices over (dp, tp, sp, ep) for
    THIS program: tp needs matmuls, sp needs ring_attention, ep needs
    moe_ffn and must divide the expert count, dp must divide the batch.
    Deterministic, sorted by canonical name."""
    feats = _program_features(desc, batch_size)
    cands = []
    seen = set()
    for f in _factorizations(n_devices, ["dp", "tp", "sp", "ep"]):
        axes = {a: s for a, s in f.items() if s > 1}
        if not axes:
            axes = {"dp": 1}
        key = tuple(sorted(axes.items()))
        if key in seen:
            continue
        seen.add(key)
        dp = f.get("dp", 1)
        tp = f.get("tp", 1)
        sp = f.get("sp", 1)
        ep = f.get("ep", 1)
        if dp > 1 and batch_size % dp:
            continue
        if tp > 1 and not feats["n_matmul"]:
            continue
        if sp > 1 and not feats["ring"]:
            continue
        if ep > 1 and (not feats["moe"]
                       or (feats["n_experts"] or 0) % ep):
            continue
        if tp > 8 or sp > 8:
            continue
        ordered = collections.OrderedDict(
            (a, f.get(a, 1)) for a in AXES_ORDER
            if f.get(a, 1) > 1 or a == "dp")
        cands.append(ordered)
    cands.sort(key=lambda ax: strategy_name(ax))
    return cands


def _matmul_ops(desc):
    """(op, x, w, out, m, k, n) for every mul/matmul whose Y is a 2-D
    persistable — the decision points of the per-op DP."""
    block = desc.blocks[0]
    out = []
    for op in block.ops:
        if op.type not in ("mul", "matmul"):
            continue
        x = (op.input("X", default=[None]) or [None])[0]
        y = (op.input("Y", default=[None]) or [None])[0]
        o = (op.output("Out", default=[None]) or [None])[0]
        if not (x and y and o):
            continue
        yvd = block.find_var_recursive(y)
        if yvd is None or not yvd.persistable or len(yvd.shape) != 2:
            continue
        xvd = block.find_var_recursive(x)
        xshape = tuple(xvd.shape) if xvd is not None else ()
        k, n = int(yvd.shape[0]), int(yvd.shape[1])
        m = 1
        for d in xshape[:-1]:
            m *= 32 if d in (-1, 0) else int(d)
        out.append({"op": op, "x": x, "w": y, "out": o,
                    "m": m, "k": k, "n": n})
    return out


def _dp_over_matmuls(desc, axes, cost, batch_size):
    """Deterministic beam/DP over per-matmul strategies.

    State: is the activation's hidden dim currently sharded over tp
    ('tp') or replicated ('rep').  Options per matmul: keep the weight
    replicated, column-parallel (None, tp), or row-parallel (tp, None).
    Transition costs are the resharding collectives the choice implies —
    the Megatron column→row pairing falls out of the DP, it is not
    hard-coded.  Returns (weight specs, compute+collective ms,
    decisions)."""
    tp = axes.get("tp", 1)
    dp = axes.get("dp", 1)
    mats = _matmul_ops(desc)
    # states: hidden replicated / hidden tp-sharded
    INF = float("inf")
    best = {"rep": (0.0, {}, [])}
    for mm in mats:
        m_dev = max(1, mm["m"] // max(1, dp))
        nxt = {}
        for state, (acc, specs, decs) in sorted(best.items()):
            opts = [("repl", "rep")]
            if tp > 1 and mm["n"] % tp == 0:
                opts.append(("col", "tp"))
            if tp > 1 and mm["k"] % tp == 0:
                opts.append(("row", "rep"))
            for choice, out_state in opts:
                cost.trace, saved = [], cost.trace
                ms = 0.0
                if choice == "repl":
                    if state == "tp":  # gather hidden back first
                        ms += cost.collective_ms(
                            "all_gather",
                            m_dev * mm["k"] * _F32_BYTES * (tp - 1) // tp,
                            tp)
                    ms += cost.kernel_ms("mul", (m_dev, mm["k"], mm["n"]))
                elif choice == "col":
                    if state == "tp":
                        ms += cost.collective_ms(
                            "all_gather",
                            m_dev * mm["k"] * _F32_BYTES * (tp - 1) // tp,
                            tp)
                    ms += cost.kernel_ms(
                        "mul", (m_dev, mm["k"], mm["n"] // tp))
                else:  # row
                    if state == "rep":
                        # slicing a replicated activation is free; the
                        # cost is the output all-reduce
                        pass
                    ms += cost.kernel_ms(
                        "mul", (m_dev, mm["k"] // tp, mm["n"]))
                    ms += cost.collective_ms(
                        "all_reduce", m_dev * mm["n"] * _F32_BYTES, tp)
                terms = cost.trace
                cost.trace = saved
                tot = acc + ms
                prev = nxt.get(out_state, (INF,))[0]
                if tot < prev - 1e-12:
                    s2 = dict(specs)
                    if choice == "col":
                        s2[mm["w"]] = (None, "tp")
                    elif choice == "row":
                        s2[mm["w"]] = ("tp", None)
                    d2 = decs + [{"op": "mul", "w": mm["w"],
                                  "choice": choice,
                                  "ms": round(ms, 5),
                                  "terms": terms}]
                    nxt[out_state] = (tot, s2, d2)
        best = nxt or best
    # leave the last activation replicated (the loss is host-consumed)
    endc = {}
    for state, (acc, specs, decs) in best.items():
        extra = 0.0
        if state == "tp" and mats:
            cost.trace, saved = [], cost.trace
            last = mats[-1]
            m_dev = max(1, last["m"] // max(1, dp))
            extra = cost.collective_ms(
                "all_gather", m_dev * last["n"] * _F32_BYTES, tp)
            cost.trace = saved
        endc[state] = (acc + extra, specs, decs)
    state = min(sorted(endc), key=lambda s: endc[s][0])
    return endc[state]


def _strategy_cost(desc, axes, cost, batch_size):
    """Predicted step ms for one factorization: measured history when
    the TSDB has this exact strategy, else matmul DP + per-step grad
    all-reduce + the axis-specific extras."""
    name = strategy_name(axes)
    hist = cost.strategy_history_ms(name)
    ms, specs, decisions = _dp_over_matmuls(desc, axes, cost, batch_size)
    dp = axes.get("dp", 1)
    tp = axes.get("tp", 1)
    sp = axes.get("sp", 1)
    ep = axes.get("ep", 1)
    feats = _program_features(desc, batch_size)
    # dp gradient all-reduce: every trainable param's grad, sized by its
    # tp/ep shard (annotated grads never gather)
    grad_bytes = 0
    for pname, shape in feats["params"]:
        nb = _numel(shape, batch_size) * _F32_BYTES
        spec = specs.get(pname)
        if spec and "tp" in spec:
            nb //= tp
        if len(shape) == 3 and ep > 1:  # expert weights shard over ep
            nb //= ep
        grad_bytes += nb
    if dp > 1 and grad_bytes:
        ms += cost.collective_ms("all_reduce", grad_bytes, dp)
    if sp > 1:
        # ring attention: (sp-1) K/V permutes per attention op
        act = batch_size // max(1, dp) * 64 * 64 * _F32_BYTES // sp
        for _ in range(max(1, feats["n_matmul"] // 6)):
            ms += cost.collective_ms("collective_permute",
                                     2 * act * (sp - 1), sp)
    if ep > 1:
        act = batch_size // max(1, dp) * 64 * 64 * _F32_BYTES
        ms += cost.collective_ms("all_to_all", 2 * act, ep)
    predicted = hist if hist is not None else ms
    return predicted, ms, hist, specs, decisions


def auto_shard(program, n_devices, cost_model=None, batch_size=32,
               keep_existing=True):
    """Search the factorization lattice x per-matmul strategies and
    return the cheapest :class:`Placement` (deterministic: sorted
    enumeration, stable tie-break on canonical name).

    Strategies the rig has measured (TSDB step history) are predicted
    from their own history.  When at least one candidate is
    history-backed, model-only candidates are charged the WORST
    observed measured/model ratio ("pessimistic calibration"): the
    analytic roofline assumes per-device compute shrinks with the
    mesh, which real rigs — above all the forced-host CPU mesh, where
    every "device" shares the same cores — routinely violate, and an
    optimistic unmeasured estimate must not outrank a measurement.

    The placement is NOT applied; call :func:`apply_placement` (or
    :func:`annotate_program`) to write it onto the desc."""
    desc = _desc_of(program)
    cost = cost_model or CostModel.from_repo()
    rows = []
    for axes in enumerate_strategies(desc, n_devices, batch_size):
        cost.trace = []
        predicted, model_ms, hist, specs, decisions = _strategy_cost(
            desc, axes, cost, batch_size)
        rows.append({"predicted": predicted, "model_ms": model_ms,
                     "hist": hist, "name": strategy_name(axes),
                     "axes": axes, "specs": specs,
                     "decisions": decisions, "trace": list(cost.trace)})
    if not rows:
        raise ValueError("no legal strategy for %d devices" % n_devices)
    ratios = [r["hist"] / r["model_ms"] for r in rows
              if r["hist"] is not None and r["model_ms"] > 0]
    if ratios and any(r["hist"] is None for r in rows):
        scale = max(ratios)
        for r in rows:
            if r["hist"] is None:
                r["predicted"] = r["model_ms"] * scale
                r["trace"].append({
                    "term": "calibration:model_x%.3f" % scale,
                    "ms": round(r["predicted"], 4),
                    "source": "tsdb:calibration",
                    "scale": round(scale, 4)})
    results = [(r["predicted"], r["name"], Placement(
        r["axes"], r["specs"], r["predicted"], r["trace"], r["name"],
        r["decisions"])) for r in rows]
    results.sort(key=lambda r: (r[0], r[1]))
    best = results[0][2]
    best.trace = list(best.trace) + [
        {"term": "considered:%s" % name, "ms": round(pred, 4),
         "source": "search"} for pred, name, _ in results[1:]]
    return best


def apply_placement(program, placement, scope=None):
    """Write a placement's annotations onto the program via
    :class:`ShardingPass` (so seeds propagate to grads/accumulators),
    stash the mesh extents on the desc for the executor route, and bump
    the version so every compile/verify cache misses."""
    desc = _desc_of(program)
    from paddle_tpu.fluid.transpiler.pass_framework import PassManager
    PassManager([ShardingPass(placement)]).run(
        program if hasattr(program, "desc") else _FluidShim(desc),
        scope)
    desc.mesh_axes = dict(placement.mesh_axes)
    desc.bump_version()
    return desc.var_shardings


def annotate_program(program, n_devices, cost_model=None, batch_size=32,
                     scope=None):
    """auto_shard + apply_placement in one step; returns the
    Placement."""
    placement = auto_shard(program, n_devices, cost_model=cost_model,
                           batch_size=batch_size)
    apply_placement(program, placement, scope=scope)
    return placement


def placement_for(program, axes, cost_model=None, batch_size=32):
    """A Placement for a FIXED factorization — no search: the same
    per-matmul dynamic program the search runs, pinned to ``axes``.
    This is how a hand-picked MESH_PROFILE strategy lowers through the
    annotated route instead of the legacy carrier wiring."""
    desc = _desc_of(program)
    cost = cost_model or CostModel()
    cost.trace = []
    predicted, _model_ms, _hist, specs, decisions = _strategy_cost(
        desc, dict(axes), cost, batch_size)
    return Placement(dict(axes), specs, predicted, list(cost.trace),
                     strategy_name(axes), decisions)


class _FluidShim:
    """Minimal Program-shaped wrapper so PassManager/DefUse accept a
    bare ProgramDesc."""

    def __init__(self, desc):
        self.desc = desc


def infer_mesh_axes(program, n_devices=None):
    """Mesh extents for an annotated program: the stash
    ``apply_placement`` left on the desc when present; otherwise the
    annotation axis NAMES with extents solved from n_devices (single
    unknown axis gets the remainder; ambiguous splits fall back to
    auto_mesh_axes order)."""
    desc = _desc_of(program)
    stashed = getattr(desc, "mesh_axes", None)
    if stashed:
        return collections.OrderedDict(
            (a, int(s)) for a, s in stashed.items())
    names = []
    for spec in desc.var_shardings.values():
        for a in spec:
            if a and a not in names:
                names.append(a)
    names.sort(key=lambda a: AXES_ORDER.index(a)
               if a in AXES_ORDER else len(AXES_ORDER))
    if not names:
        return None
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    axes = collections.OrderedDict()
    rem = n_devices
    for a in names[:-1]:
        axes[a] = 2 if rem % 2 == 0 and rem > 1 else 1
        rem //= axes[a]
    axes[names[-1]] = max(1, rem)
    return axes


# ---------------------------------------------------------------------------
# Pipeline stage assignment (the pp axis on the same annotation carrier)
# ---------------------------------------------------------------------------

def assign_pipeline_stages(program, n_stages):
    """Tag every block-0 op with a ``__pp_stage__`` attr: contiguous
    stages, boundaries chosen where exactly ONE live activation crosses
    (the GPipe cut contract), balanced by matmul count.  Returns the
    cut-variable names; ``PipelineProgram.from_annotations`` lowers the
    tagged program.  Raises when the program has no n_stages-1 legal
    single-crossing cuts (e.g. a one-matmul net)."""
    desc = _desc_of(program)
    block = desc.blocks[0]
    ops = block.ops
    if n_stages < 2:
        for op in ops:
            op.set_attr(PP_STAGE_ATTR, 0)
        return []
    persist = {n for n, vd in block.vars.items() if vd.persistable}
    # candidate cut AFTER op i: vars defined at <=i and read at >i,
    # excluding persistables (params live with their stage)
    last_read = {}
    for i, op in enumerate(ops):
        for n in op.input_arg_names():
            if n:
                last_read[n] = i
    defined_at = {}
    for i, op in enumerate(ops):
        for n in op.output_arg_names():
            if n and n not in defined_at:
                defined_at[n] = i
    candidates = []
    for i in range(len(ops) - 1):
        crossing = [n for n, d in defined_at.items()
                    if d <= i and last_read.get(n, -1) > i
                    and n not in persist]
        if len(crossing) == 1:
            candidates.append((i, crossing[0]))
    weights = [1 + (4 if op.type in ("mul", "matmul", "ring_attention",
                                     "moe_ffn") else 0)
               for op in ops]
    total = float(sum(weights))
    cuts = []
    acc = 0.0
    want = 1
    for i, (idx, var) in enumerate(sorted(candidates)):
        acc = sum(weights[:idx + 1])
        if acc >= total * want / n_stages and len(cuts) < n_stages - 1:
            cuts.append((idx, var))
            want += 1
    if len(cuts) < n_stages - 1:
        raise ValueError(
            "program has %d single-crossing cut points, need %d for "
            "%d stages" % (len(candidates), n_stages - 1, n_stages))
    bounds = [c[0] for c in cuts]
    for i, op in enumerate(ops):
        stage = sum(1 for b in bounds if i > b)
        op.set_attr(PP_STAGE_ATTR, stage)
    desc.bump_version()
    return [c[1] for c in cuts]


# ---------------------------------------------------------------------------
# Elastic resharding
# ---------------------------------------------------------------------------

def check_reshard_pair(desc, old_shardings, old_axes, new_shardings,
                       new_axes):
    """Diagnostics for an old/new layout pair of the SAME program:
    annotated persistables must stay annotated (or knowingly dropped to
    replicated), every spec must be valid on its mesh, and sharded dims
    must divide by their axis extent on BOTH layouts — the invariants
    redistribution relies on."""
    from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
    diags = []
    block = desc.blocks[0]
    for name, spec in sorted(old_shardings.items()):
        vd = block.find_var_recursive(name)
        if vd is None or not vd.persistable:
            continue
        new_spec = new_shardings.get(name)
        if new_spec is None and any(a for a in spec):
            diags.append(Diagnostic(
                "reshard-pair", Severity.WARNING,
                "persistable sharded on the old mesh (%s) is "
                "unannotated on the new one — it will gather to "
                "replicated during redistribution" % (spec,),
                var=name,
                suggestion="carry the annotation through "
                           "apply_placement on the new mesh"))
    for which, shardings, axes in (("old", old_shardings, old_axes),
                                   ("new", new_shardings, new_axes)):
        axes = axes or {}
        for name, spec in sorted(shardings.items()):
            vd = block.find_var_recursive(name)
            if vd is None or not vd.shape:
                continue
            for dim, a in enumerate(spec):
                if not a:
                    continue
                ext = axes.get(a)
                if ext is None:
                    diags.append(Diagnostic(
                        "reshard-pair", Severity.ERROR,
                        "%s layout shards dim %d over axis %r which "
                        "the %s mesh %r does not have"
                        % (which, dim, a, which, dict(axes)), var=name,
                        suggestion="add the axis to the mesh or drop "
                                   "the annotation"))
                elif (dim < len(vd.shape) and vd.shape[dim] > 0
                      and vd.shape[dim] % ext):
                    diags.append(Diagnostic(
                        "reshard-pair", Severity.ERROR,
                        "%s layout: dim %d (size %d) of %r does not "
                        "divide by %s=%d"
                        % (which, dim, vd.shape[dim], name, a, ext),
                        var=name,
                        suggestion="pick an extent that divides the "
                                   "dim, or leave it replicated"))
    return diags


def reshard(program, scope, n_devices, cost_model=None, batch_size=32,
            checkpoint_dir=None, verify=True, flight_reason="mesh_reshard",
            exec_strategy=None, build_strategy=None):
    """Grow or shrink the mesh mid-job without restart-from-scratch.

    Quiesce: flush every prepared attachment's device-resident state
    back through the scope (the PR 2 ``sync_scope`` protocol) so host
    state is authoritative.  Re-lower: run :func:`auto_shard` for the
    new device count on the SAME program, verify the old/new layout
    pair plus the full checker pipeline (sharding + dist-pairing), and
    build a fresh ParallelExecutor over the new mesh — the first run's
    ``in_shardings`` redistribute the quiesced state.  When
    ``checkpoint_dir`` is given the PR 1 shard checkpoint is loaded
    instead of trusting device-resident state (the crash-recovery arm
    of the fault drill).

    Returns ``(executor, report)``; the report times each step and a
    flight artifact records the transition for post-mortems."""
    desc = _desc_of(program)
    report = {"from_axes": dict(getattr(desc, "mesh_axes", {}) or {}),
              "to_devices": int(n_devices)}
    old_shardings = dict(desc.var_shardings)
    old_axes = dict(getattr(desc, "mesh_axes", {}) or {})

    t0 = time.perf_counter()
    try:
        scope.flush_prepared()
    except Exception:
        pass
    report["quiesce_ms"] = (time.perf_counter() - t0) * 1e3

    if checkpoint_dir is not None:
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import io as fio
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            serial = fio.load_checkpoint(exe, checkpoint_dir,
                                         main_program=program)
        report["checkpoint_serial"] = serial

    t0 = time.perf_counter()
    placement = auto_shard(program, n_devices, cost_model=cost_model,
                           batch_size=batch_size)
    apply_placement(program, placement, scope=scope)
    report["relower_ms"] = (time.perf_counter() - t0) * 1e3
    report["strategy"] = placement.strategy
    report["mesh_axes"] = dict(placement.mesh_axes)

    if verify:
        from paddle_tpu import analysis
        diags = check_reshard_pair(desc, old_shardings, old_axes,
                                   desc.var_shardings,
                                   placement.mesh_axes)
        diags += [d for d in analysis.verify_program(desc)
                  if d.is_error]
        errors = [d for d in diags if d.is_error]
        report["verify_errors"] = len(errors)
        if errors:
            raise analysis.ProgramVerificationError(
                analysis.format_diagnostics(errors))

    from paddle_tpu.fluid.parallel_executor import ParallelExecutor
    t0 = time.perf_counter()
    pe = ParallelExecutor(use_cuda=False, main_program=program,
                          scope=scope,
                          mesh_axes=dict(placement.mesh_axes),
                          num_devices=n_devices,
                          exec_strategy=exec_strategy,
                          build_strategy=build_strategy)
    report["rebuild_ms"] = (time.perf_counter() - t0) * 1e3

    try:
        from paddle_tpu.observability import flight
        path = flight.dump(flight_reason, sections={
            "reshard": {k: v for k, v in report.items()
                        if not isinstance(v, Exception)}})
        report["flight_artifact"] = path
    except Exception:
        report["flight_artifact"] = None
    return pe, report
