"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: kexinzhao/Paddle), built on JAX/XLA.

Layout:
  core/      IR descriptors, scope, op registry, block->XLA lowering, executor
  ops/       operator library (JAX lowerings, vjp-derived grads)
  fluid/     user API mirroring python/paddle/fluid
  parallel/  SPMD mesh utilities, distributed transpiler
  models/    benchmark/fluid model configs
  reader/    reader creators/decorators + double-buffered DeviceLoader
  dataset/   dataset adapters (real-format parsers, synthetic fallback)
  recordio/  chunked record container (C++ core + Python codec)
  utils/     serialization helpers
"""
__version__ = "0.1.0"

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import recordio  # noqa: F401


def batch(reader_fn, batch_size, drop_last=True):
    """paddle.batch parity (reference python/paddle/batch.py)."""
    from .reader.device_loader import batch as _b
    return _b(reader_fn, batch_size, drop_last)
