"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: kexinzhao/Paddle), built on JAX/XLA.

Layout:
  core/      IR descriptors, scope, op registry, block->XLA lowering, executor
  ops/       operator library (JAX lowerings, vjp-derived grads)
  fluid/     user API mirroring python/paddle/fluid
  parallel/  SPMD mesh utilities, distributed transpiler
  models/    benchmark/fluid model configs
  utils/     readers, datasets, serialization
  native/    C++ runtime components (recordio, ...)
"""
__version__ = "0.1.0"

from . import fluid  # noqa: F401
