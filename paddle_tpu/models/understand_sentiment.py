"""Sentiment-classification book models (parity:
python/paddle/fluid/tests/book/notest_understand_sentiment.py — the
convolution net, the hand-built DynamicRNN LSTM, and the stacked-LSTM
variant lives in models/stacked_dynamic_lstm.py).

Text towers are ragged (lod_level=1) batches; sequence_conv_pool and the
DynamicRNN front-end both lower to masked static-shape XLA programs.
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["convolution_net", "dyn_rnn_lstm", "get_model"]


def convolution_net(data, input_dim, class_dim=2, emb_dim=32, hid_dim=32):
    """Two context-window conv towers (filter 3 and 4) with sqrt pooling
    (reference notest_understand_sentiment.py:27)."""
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim],
                                 is_sparse=True)
    conv_3 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=3, act="tanh",
                                           pool_type="sqrt")
    conv_4 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=4, act="tanh",
                                           pool_type="sqrt")
    return fluid.layers.fc(input=[conv_3, conv_4], size=class_dim,
                           act="softmax")


def dyn_rnn_lstm(data, input_dim, class_dim=2, emb_dim=32, lstm_size=128):
    """An LSTM cell written out gate-by-gate inside a DynamicRNN block
    (reference notest_understand_sentiment.py:52) — exercises the
    control-flow front-end rather than the fused lstm op."""
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim],
                                 is_sparse=True)
    sentence = fluid.layers.fc(input=emb, size=lstm_size, act="tanh")

    rnn = fluid.layers.DynamicRNN()
    with rnn.block():
        word = rnn.step_input(sentence)
        prev_hidden = rnn.memory(value=0.0, shape=[lstm_size])
        prev_cell = rnn.memory(value=0.0, shape=[lstm_size])

        def gate(ipt, hidden):
            g0 = fluid.layers.fc(input=ipt, size=lstm_size, bias_attr=True)
            g1 = fluid.layers.fc(input=hidden, size=lstm_size,
                                 bias_attr=False)
            return g0 + g1

        forget_g = fluid.layers.sigmoid(gate(word, prev_hidden))
        input_g = fluid.layers.sigmoid(gate(word, prev_hidden))
        output_g = fluid.layers.sigmoid(gate(word, prev_hidden))
        cell_g = fluid.layers.tanh(gate(word, prev_hidden))

        cell = forget_g * prev_cell + input_g * cell_g
        hidden = output_g * fluid.layers.tanh(cell)
        rnn.update_memory(prev_cell, cell)
        rnn.update_memory(prev_hidden, hidden)
        rnn.output(hidden)

    last = fluid.layers.sequence_last_step(rnn())
    return fluid.layers.fc(input=last, size=class_dim, act="softmax")


def get_model(dict_dim, net="conv", class_dim=2, emb_dim=32, hid_dim=32,
              learning_rate=0.002):
    """(avg_cost, [data, label], [accuracy]) in the current program."""
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if net == "conv":
        prediction = convolution_net(data, dict_dim, class_dim, emb_dim,
                                     hid_dim)
    elif net == "dyn_rnn":
        prediction = dyn_rnn_lstm(data, dict_dim, class_dim, emb_dim,
                                  lstm_size=hid_dim)
    else:
        raise ValueError("net must be conv|dyn_rnn, got %r" % net)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    accuracy = fluid.layers.accuracy(input=prediction, label=label)
    fluid.optimizer.Adagrad(learning_rate=learning_rate).minimize(avg_cost)
    return avg_cost, [data, label], [accuracy]
