"""GoogLeNet / Inception-v1 (parity: the legacy benchmark's googlenet
workload — benchmark/README.md publishes its K40m ms/batch numbers;
standard 9-inception-module config, main head only)."""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["googlenet", "get_model"]


def _conv(input, num_filters, filter_size, stride=1, padding=0):
    return fluid.layers.conv2d(input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=padding, act="relu")


def _inception(x, c1, c3r, c3, c5r, c5, proj):
    """One inception module: 1x1 / 3x3 / 5x5 towers + pooled projection,
    channel-concatenated."""
    t1 = _conv(x, c1, 1)
    t3 = _conv(_conv(x, c3r, 1), c3, 3, padding=1)
    t5 = _conv(_conv(x, c5r, 1), c5, 5, padding=2)
    tp = _conv(fluid.layers.pool2d(x, pool_size=3, pool_stride=1,
                                   pool_padding=1, pool_type="max"),
               proj, 1)
    return fluid.layers.concat([t1, t3, t5, tp], axis=1)


def googlenet(input, class_dim, is_test=False):
    x = _conv(input, 64, 7, stride=2, padding=3)
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2,
                            pool_padding=1, pool_type="max")
    x = fluid.layers.lrn(x, n=5)
    x = _conv(_conv(x, 64, 1), 192, 3, padding=1)
    x = fluid.layers.lrn(x, n=5)
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2,
                            pool_padding=1, pool_type="max")

    x = _inception(x, 64, 96, 128, 16, 32, 32)     # 3a
    x = _inception(x, 128, 128, 192, 32, 96, 64)   # 3b
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2,
                            pool_padding=1, pool_type="max")
    x = _inception(x, 192, 96, 208, 16, 48, 64)    # 4a
    x = _inception(x, 160, 112, 224, 24, 64, 64)   # 4b
    x = _inception(x, 128, 128, 256, 24, 64, 64)   # 4c
    x = _inception(x, 112, 144, 288, 32, 64, 64)   # 4d
    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 4e
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2,
                            pool_padding=1, pool_type="max")
    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 5a
    x = _inception(x, 384, 192, 384, 48, 128, 128)  # 5b

    x = fluid.layers.pool2d(x, pool_size=7, pool_stride=1,
                            pool_type="avg")
    x = fluid.layers.dropout(x, dropout_prob=0.4, is_test=is_test)
    return fluid.layers.fc(x, size=class_dim, act="softmax")


def get_model(class_dim=102, learning_rate=0.01, is_test=False):
    """(avg_cost, [image, label], [batch_acc]) at ImageNet shapes."""
    images = fluid.layers.data(name="data", shape=[3, 224, 224],
                               dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = googlenet(images, class_dim, is_test=is_test)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    if not is_test:
        fluid.optimizer.Momentum(learning_rate=learning_rate,
                                 momentum=0.9).minimize(avg_cost)
    return avg_cost, [images, label], [batch_acc]
