"""Model zoo: the fluid-benchmark model families.

Parity: reference benchmark/fluid/models/{mnist,resnet,vgg,
stacked_dynamic_lstm,machine_translation}.py — each module exposes the
network builder(s) plus a ``get_model(...)`` returning
(loss, feeds, extra_fetches) built into the current default program.
"""
from . import (mnist, resnet, vgg, transformer,  # noqa: F401
               stacked_dynamic_lstm, machine_translation,
               understand_sentiment, recommender, label_semantic_roles,
               word2vec, alexnet, googlenet)

__all__ = ["mnist", "resnet", "vgg", "transformer",
           "stacked_dynamic_lstm", "machine_translation",
           "understand_sentiment", "recommender", "label_semantic_roles",
           "word2vec", "alexnet", "googlenet"]
