"""N-gram word2vec book model (parity:
python/paddle/fluid/tests/book/test_word2vec.py — four context-word
embeddings sharing one 'shared_w' table (is_sparse: gradients flow as
SelectedRows), concat -> hidden fc -> softmax over the vocab).
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["inference_program", "get_model"]

EMBED_SIZE = 32
HIDDEN_SIZE = 256
N = 5  # 5-gram: 4 context words predict the 5th


def inference_program(words, dict_size, is_sparse=True,
                      embed_size=EMBED_SIZE, hidden_size=HIDDEN_SIZE):
    """``words`` = [first, second, third, forth] id tensors."""
    embs = [
        fluid.layers.embedding(
            input=w, size=[dict_size, embed_size], dtype="float32",
            is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name="shared_w"))
        for w in words]
    concat_embed = fluid.layers.concat(input=embs, axis=1)
    hidden1 = fluid.layers.fc(input=concat_embed, size=hidden_size,
                              act="sigmoid")
    return fluid.layers.fc(input=hidden1, size=dict_size, act="softmax")


def get_model(dict_size, is_sparse=True, embed_size=EMBED_SIZE,
              hidden_size=HIDDEN_SIZE, learning_rate=1e-3):
    """(avg_cost, feeds in imikolov 5-gram column order, [predict])."""
    first = fluid.layers.data(name="firstw", shape=[1], dtype="int64")
    second = fluid.layers.data(name="secondw", shape=[1], dtype="int64")
    third = fluid.layers.data(name="thirdw", shape=[1], dtype="int64")
    forth = fluid.layers.data(name="forthw", shape=[1], dtype="int64")
    next_word = fluid.layers.data(name="nextw", shape=[1], dtype="int64")

    predict_word = inference_program(
        [first, second, third, forth], dict_size, is_sparse,
        embed_size, hidden_size)
    cost = fluid.layers.cross_entropy(input=predict_word, label=next_word)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    return avg_cost, [first, second, third, forth, next_word], \
        [predict_word]
