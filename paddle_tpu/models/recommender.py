"""MovieLens recommender book model (parity:
python/paddle/fluid/tests/book/test_recommender_system.py — two feature
towers (user: id/gender/age/job embeddings; movie: id embedding +
category sum-pool + title conv-pool), cosine similarity scaled to the
rating range, square_error_cost regression).

All embedding lookups are is_sparse=True: gradients flow as
SelectedRows and apply as scatter-adds (core/selected_rows.py).
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid
from paddle_tpu import dataset

__all__ = ["get_usr_combined_features", "get_mov_combined_features",
           "get_model"]

IS_SPARSE = True


def get_usr_combined_features():
    usr_dict_size = dataset.movielens.max_user_id() + 1
    uid = fluid.layers.data(name="user_id", shape=[1], dtype="int64")
    usr_emb = fluid.layers.embedding(
        input=uid, size=[usr_dict_size, 32], dtype="float32",
        param_attr="user_table", is_sparse=IS_SPARSE)
    usr_fc = fluid.layers.fc(input=usr_emb, size=32)

    usr_gender_id = fluid.layers.data(name="gender_id", shape=[1],
                                      dtype="int64")
    usr_gender_emb = fluid.layers.embedding(
        input=usr_gender_id, size=[2, 16],
        param_attr="gender_table", is_sparse=IS_SPARSE)
    usr_gender_fc = fluid.layers.fc(input=usr_gender_emb, size=16)

    age_dict_size = len(dataset.movielens.age_table)
    usr_age_id = fluid.layers.data(name="age_id", shape=[1], dtype="int64")
    usr_age_emb = fluid.layers.embedding(
        input=usr_age_id, size=[age_dict_size, 16],
        param_attr="age_table", is_sparse=IS_SPARSE)
    usr_age_fc = fluid.layers.fc(input=usr_age_emb, size=16)

    job_dict_size = dataset.movielens.max_job_id() + 1
    usr_job_id = fluid.layers.data(name="job_id", shape=[1], dtype="int64")
    usr_job_emb = fluid.layers.embedding(
        input=usr_job_id, size=[job_dict_size, 16],
        param_attr="job_table", is_sparse=IS_SPARSE)
    usr_job_fc = fluid.layers.fc(input=usr_job_emb, size=16)

    concat_embed = fluid.layers.concat(
        input=[usr_fc, usr_gender_fc, usr_age_fc, usr_job_fc], axis=1)
    return fluid.layers.fc(input=concat_embed, size=200, act="tanh")


def get_mov_combined_features():
    mov_dict_size = dataset.movielens.max_movie_id() + 1
    mov_id = fluid.layers.data(name="movie_id", shape=[1], dtype="int64")
    mov_emb = fluid.layers.embedding(
        input=mov_id, size=[mov_dict_size, 32], dtype="float32",
        param_attr="movie_table", is_sparse=IS_SPARSE)
    mov_fc = fluid.layers.fc(input=mov_emb, size=32)

    category_size = len(dataset.movielens.movie_categories())
    category_id = fluid.layers.data(name="category_id", shape=[1],
                                    dtype="int64", lod_level=1)
    mov_categories_emb = fluid.layers.embedding(
        input=category_id, size=[category_size, 32], is_sparse=IS_SPARSE)
    mov_categories_hidden = fluid.layers.sequence_pool(
        input=mov_categories_emb, pool_type="sum")

    title_size = len(dataset.movielens.get_movie_title_dict())
    mov_title_id = fluid.layers.data(name="movie_title", shape=[1],
                                     dtype="int64", lod_level=1)
    mov_title_emb = fluid.layers.embedding(
        input=mov_title_id, size=[title_size, 32], is_sparse=IS_SPARSE)
    mov_title_conv = fluid.nets.sequence_conv_pool(
        input=mov_title_emb, num_filters=32, filter_size=3, act="tanh",
        pool_type="sum")

    concat_embed = fluid.layers.concat(
        input=[mov_fc, mov_categories_hidden, mov_title_conv], axis=1)
    return fluid.layers.fc(input=concat_embed, size=200, act="tanh")


def get_model(learning_rate=0.2):
    """(avg_cost, feed vars in reader column order, [scaled predict])."""
    usr = get_usr_combined_features()
    mov = get_mov_combined_features()
    inference = fluid.layers.cos_sim(X=usr, Y=mov)
    scale_infer = fluid.layers.scale(x=inference, scale=5.0)

    label = fluid.layers.data(name="score", shape=[1], dtype="float32")
    square_cost = fluid.layers.square_error_cost(input=scale_infer,
                                                 label=label)
    avg_cost = fluid.layers.mean(square_cost)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)

    prog = fluid.default_main_program()
    feed_order = ["user_id", "gender_id", "age_id", "job_id", "movie_id",
                  "category_id", "movie_title", "score"]
    feeds = [prog.global_block().var(n) for n in feed_order]
    return avg_cost, feeds, [scale_infer]
