"""Transformer language model — the attention-era flagship.

Reference coverage: the machine_translation/seq2seq-attention configs
(benchmark/fluid/models/machine_translation.py, tests book
machine_translation) are RNN+attention; this model family is their
TPU-first successor, built so every parallel axis of the mesh is
exercised inside ONE fluid program:

- dp  : batch sharding of feeds (ParallelExecutor).
- tp  : column/row-parallel qkv/out/ffn weights via
        ParamAttr(sharding=...); heads stay independent.
- sp  : ring attention over the sequence axis (paddle_tpu.parallel.ring)
        through the ``ring_attention`` op.
- ep  : expert-parallel MoE FFN blocks through the ``moe_ffn`` op.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.param_attr import ParamAttr

__all__ = ["transformer_lm", "get_model"]


def _attn_block(x, d_model, n_head, tp, sp, prefix):
    ln = fluid.layers.layer_norm(x, begin_norm_axis=2)
    head_dim = d_model // n_head
    wattr = (lambda: ParamAttr(sharding=(None, "tp"))) if tp else \
        (lambda: None)
    qkv = []
    for nm in ("q", "k", "v"):
        h = fluid.layers.fc(ln, size=d_model, num_flatten_dims=2,
                            param_attr=wattr(), bias_attr=False,
                            name="%s_%s" % (prefix, nm))
        h = fluid.layers.reshape(h, [0, 0, n_head, head_dim])
        qkv.append(fluid.layers.transpose(h, [0, 2, 1, 3]))  # [B,H,S,Dh]
    q, k, v = qkv

    helper = fluid.layer_helper.LayerHelper(prefix + "_ring")
    att = helper.create_tmp_variable(x.dtype)
    # LSE output = the flash residual: the backward runs the two flash
    # kernels from it instead of re-executing the forward inside the
    # grad op's vjp (~2.5 ms/layer on the secondary bench)
    lse = helper.create_tmp_variable("float32")
    lse.stop_gradient = True
    helper.append_op(
        type="ring_attention", inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [att], "LSE": [lse]},
        attrs={"causal": True, "sp_axis": "sp" if sp else "",
               "batch_axis": "dp", "head_axis": "tp" if tp else ""})
    att = fluid.layers.transpose(att, [0, 2, 1, 3])
    att = fluid.layers.reshape(att, [0, 0, d_model])
    out = fluid.layers.fc(
        att, size=d_model, num_flatten_dims=2,
        param_attr=ParamAttr(sharding=("tp", None)) if tp else None,
        name=prefix + "_o")
    return fluid.layers.elementwise_add(x, out)


def _ffn_block(x, d_model, d_ff, tp, prefix):
    ln = fluid.layers.layer_norm(x, begin_norm_axis=2)
    h = fluid.layers.fc(
        ln, size=d_ff, num_flatten_dims=2, act="relu",
        param_attr=ParamAttr(sharding=(None, "tp")) if tp else None,
        name=prefix + "_fc1")
    h = fluid.layers.fc(
        h, size=d_model, num_flatten_dims=2,
        param_attr=ParamAttr(sharding=("tp", None)) if tp else None,
        name=prefix + "_fc2")
    return fluid.layers.elementwise_add(x, h)


def _moe_block(x, d_model, d_ff, n_experts, ep, prefix):
    ln = fluid.layers.layer_norm(x, begin_norm_axis=2)
    router = fluid.layers.create_parameter(
        [d_model, n_experts], "float32", name=prefix + "_router")
    eattr = (ParamAttr(sharding=("ep", None, None), name=prefix + "_w1")
             if ep else ParamAttr(name=prefix + "_w1"))
    e2attr = (ParamAttr(sharding=("ep", None, None), name=prefix + "_w2")
              if ep else ParamAttr(name=prefix + "_w2"))
    w1 = fluid.layers.create_parameter([n_experts, d_model, d_ff],
                                       "float32", attr=eattr)
    w2 = fluid.layers.create_parameter([n_experts, d_ff, d_model],
                                       "float32", attr=e2attr)
    helper = fluid.layer_helper.LayerHelper(prefix + "_moe")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(
        type="moe_ffn",
        inputs={"X": [ln], "RouterW": [router], "W1": [w1], "W2": [w2]},
        outputs={"Out": [out]},
        attrs={"ep_axis": "ep" if ep else "", "dp_axis": "dp",
               "capacity_factor": 2.0})
    return fluid.layers.elementwise_add(x, out)


def transformer_lm(src, vocab_size, max_len, d_model=256, n_head=8,
                   n_layers=4, d_ff=1024, tp=False, sp=False,
                   moe_experts=0, ep=False):
    """src: [B, S] int64 token ids -> logits [B, S, vocab_size]."""
    emb = fluid.layers.embedding(src, (vocab_size, d_model))
    pos = fluid.layers.create_parameter([max_len, d_model], "float32",
                                        name="pos_emb")
    x = fluid.layers.elementwise_add(emb, pos, axis=1)
    if sp:
        from paddle_tpu.parallel.api import sharding_constraint
        x = sharding_constraint(x, ("dp", "sp", None))
    for i in range(n_layers):
        x = _attn_block(x, d_model, n_head, tp, sp, "blk%d" % i)
        if moe_experts and i % 2 == 1:
            x = _moe_block(x, d_model, d_ff, moe_experts, ep,
                           "blk%d" % i)
        else:
            x = _ffn_block(x, d_model, d_ff, tp, "blk%d" % i)
    x = fluid.layers.layer_norm(x, begin_norm_axis=2)
    logits = fluid.layers.fc(x, size=vocab_size, num_flatten_dims=2,
                             name="lm_head")
    return logits


def get_model(vocab_size=1000, seq_len=64, batch_size=None, d_model=256,
              n_head=8, n_layers=4, d_ff=1024, learning_rate=1e-3,
              tp=False, sp=False, moe_experts=0, ep=False,
              fuse_transformer=None):
    """(avg_cost, [src, label], []) — next-token LM loss.

    ``fuse_transformer`` None → ``FLAGS.transformer_fuse``; True runs
    FuseTransformerBlockPass on the built graph BEFORE backward
    generation (fused QKV / matmul+bias+act / residual+LN ops backed by
    kernels/matmul_fused.py), so minimize differentiates the fused
    forward through the explicit saved-activation grad lowerings.  The
    unfused program stays the default for bisection, like conv_layout.
    """
    from paddle_tpu.core.flags import FLAGS

    if fuse_transformer is None:
        fuse_transformer = bool(FLAGS.transformer_fuse)

    src = fluid.layers.data(name="src", shape=[seq_len], dtype="int64")
    label = fluid.layers.data(name="label", shape=[seq_len, 1],
                              dtype="int64")
    logits = transformer_lm(src, vocab_size, seq_len, d_model, n_head,
                            n_layers, d_ff, tp=tp, sp=sp,
                            moe_experts=moe_experts, ep=ep)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_cost = fluid.layers.mean(loss)
    if fuse_transformer:
        from paddle_tpu.fluid.transpiler import TransformerFuseTranspiler
        TransformerFuseTranspiler().transpile(fluid.default_main_program())
    opt = fluid.optimizer.Adam(learning_rate=learning_rate)
    opt.minimize(avg_cost)
    return avg_cost, [src, label], []
