"""AlexNet (parity: the legacy benchmark's alexnet workload —
benchmark/README.md publishes its K40m ms/batch numbers; config is the
classic 5-conv/3-fc net with LRN and grouped convs)."""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["alexnet", "get_model"]


def alexnet(input, class_dim, is_test=False):
    conv1 = fluid.layers.conv2d(input, num_filters=96, filter_size=11,
                                stride=4, padding=2, act="relu")
    lrn1 = fluid.layers.lrn(conv1, n=5, alpha=1e-4, beta=0.75)
    pool1 = fluid.layers.pool2d(lrn1, pool_size=3, pool_stride=2,
                                pool_type="max")
    conv2 = fluid.layers.conv2d(pool1, num_filters=256, filter_size=5,
                                padding=2, groups=2, act="relu")
    lrn2 = fluid.layers.lrn(conv2, n=5, alpha=1e-4, beta=0.75)
    pool2 = fluid.layers.pool2d(lrn2, pool_size=3, pool_stride=2,
                                pool_type="max")
    conv3 = fluid.layers.conv2d(pool2, num_filters=384, filter_size=3,
                                padding=1, act="relu")
    conv4 = fluid.layers.conv2d(conv3, num_filters=384, filter_size=3,
                                padding=1, groups=2, act="relu")
    conv5 = fluid.layers.conv2d(conv4, num_filters=256, filter_size=3,
                                padding=1, groups=2, act="relu")
    pool5 = fluid.layers.pool2d(conv5, pool_size=3, pool_stride=2,
                                pool_type="max")
    fc6 = fluid.layers.fc(pool5, size=4096, act="relu")
    drop6 = fluid.layers.dropout(fc6, dropout_prob=0.5, is_test=is_test)
    fc7 = fluid.layers.fc(drop6, size=4096, act="relu")
    drop7 = fluid.layers.dropout(fc7, dropout_prob=0.5, is_test=is_test)
    return fluid.layers.fc(drop7, size=class_dim, act="softmax")


def get_model(class_dim=102, learning_rate=0.01, is_test=False):
    """(avg_cost, [image, label], [batch_acc]) at ImageNet shapes."""
    images = fluid.layers.data(name="data", shape=[3, 224, 224],
                               dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = alexnet(images, class_dim, is_test=is_test)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    if not is_test:
        fluid.optimizer.Momentum(learning_rate=learning_rate,
                                 momentum=0.9).minimize(avg_cost)
    return avg_cost, [images, label], [batch_acc]
