"""ResNet for cifar10 / imagenet (parity: benchmark/fluid/models/resnet.py:
conv_bn_layer:32, basicblock:53, bottleneck:60, resnet_imagenet:75,
resnet_cifar10:102).

TPU notes: convolutions and the residual adds all fuse under XLA; bf16
inputs keep the convs on the MXU.  NCHW builder shapes are kept for API
parity — XLA's layout assignment re-tiles for TPU internally.
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["resnet_imagenet", "resnet_cifar10", "get_model"]


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False):
    conv1 = fluid.layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv1, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_test=is_test)
    return input


def basicblock(input, ch_out, stride, is_test=False):
    short = shortcut(input, ch_out, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return fluid.layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False):
    short = shortcut(input, ch_out * 4, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test)
    return fluid.layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_test=False):
    res_out = block_func(input, ch_out, stride, is_test=is_test)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test=is_test)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, is_test=False):
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test)
    pool1 = fluid.layers.pool2d(input=conv1, pool_type="avg", pool_size=3,
                                pool_stride=2)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_test=is_test)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_test=is_test)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_test=is_test)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_test=is_test)
    pool2 = fluid.layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                                pool_stride=1, global_pooling=True)
    out = fluid.layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim, depth=32, is_test=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test=is_test)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test=is_test)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test=is_test)
    pool = fluid.layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                               pool_stride=1)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def get_model(data_set="flowers", depth=50, learning_rate=0.01,
              is_test=False, input_dtype="float32", data_format=None,
              fused_stages=None):
    """Build train graph; (avg_cost, [input, label], [batch_acc]).

    data_set 'cifar10' → 32×32/10-way resnet_cifar10; 'flowers'/'imagenet'
    → 224×224 resnet_imagenet (reference resnet.py get_model:119).

    input_dtype 'uint8': the data layer takes raw bytes and the graph
    casts + scales by 1/255 on device — the TPU-native input pipeline
    (the reference normalizes on host CPU before the feed,
    image/image.py; over a narrow host link shipping uint8 and
    normalizing on device is the same math at a quarter the traffic).

    data_format None → ``FLAGS.conv_layout``; 'NHWC' runs the
    LayoutTranspiler on the built graph BEFORE backward generation: NHWC
    pinned end-to-end, weights stored HWIO, and (fused_stages, default
    ``FLAGS.conv_fused_stages``) conv+BN+act stages fused into the
    Pallas conv-stage op.  The feed contract stays NCHW — one transpose
    bridges the feed into the pinned domain.
    """
    from paddle_tpu.core.flags import FLAGS

    if data_format is None:
        data_format = FLAGS.conv_layout or "NCHW"
    if fused_stages is None:
        fused_stages = bool(FLAGS.conv_fused_stages)

    if data_set == "cifar10":
        class_dim, dshape, model = 10, [3, 32, 32], resnet_cifar10
        kwargs = {"depth": 32 if depth == 50 else depth}
    else:
        class_dim = 102 if data_set == "flowers" else 1000
        dshape, model = [3, 224, 224], resnet_imagenet
        kwargs = {"depth": depth}

    input = fluid.layers.data(name="data", shape=dshape, dtype=input_dtype)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    x = input
    if input_dtype == "uint8":
        x = fluid.layers.scale(fluid.layers.cast(input, "float32"),
                               scale=1.0 / 255.0)
    predict = model(x, class_dim, is_test=is_test, **kwargs)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    if data_format == "NHWC":
        # before minimize: backward then differentiates the pinned
        # forward, so filter grads / optimizer state are HWIO too
        from paddle_tpu.fluid.transpiler import LayoutTranspiler
        LayoutTranspiler().transpile(
            fluid.default_main_program(),
            startup_program=fluid.default_startup_program(),
            data_format="NHWC", fuse_stages=fused_stages)
    if not is_test:
        opt = fluid.optimizer.Momentum(learning_rate=learning_rate,
                                       momentum=0.9)
        opt.minimize(avg_cost)
    return avg_cost, [input, label], [batch_acc]
