"""Stacked LSTM sentiment model on ragged batches (parity:
benchmark/fluid/models/stacked_dynamic_lstm.py — IMDB LSTM LM with
embedding -> fc -> recurrence -> last-pool -> softmax).

The reference builds the recurrence with DynamicRNN (while-op per step);
here the whole stacked recurrence is dynamic_lstm ops — masked lax.scan
loops that XLA compiles into one fused program (SURVEY §5.7).
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["stacked_lstm_net", "get_model"]


def stacked_lstm_net(words, dict_dim, class_dim=2, emb_dim=128,
                     hidden_dim=512, stacked_num=3):
    emb = fluid.layers.embedding(words, size=[dict_dim, emb_dim])
    fc1 = fluid.layers.fc(emb, size=hidden_dim, act="tanh")
    lstm1, _ = fluid.layers.dynamic_lstm(fc1, size=hidden_dim,
                                         use_peepholes=False)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(inputs[-1], size=hidden_dim, act="tanh")
        lstm, _ = fluid.layers.dynamic_lstm(fc, size=hidden_dim,
                                            use_peepholes=False,
                                            is_reverse=False)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(inputs[0], "max")
    lstm_last = fluid.layers.sequence_pool(inputs[1], "max")
    return fluid.layers.fc(input=[fc_last, lstm_last], size=class_dim,
                           act="softmax")


def get_model(dict_dim=5000, class_dim=2, emb_dim=128, hidden_dim=512,
              stacked_num=3, learning_rate=2e-3):
    """(avg_cost, [words, label], [batch_acc])."""
    words = fluid.layers.data(name="words", shape=[1], lod_level=1,
                              dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prediction = stacked_lstm_net(words, dict_dim, class_dim, emb_dim,
                                  hidden_dim, stacked_num)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=prediction, label=label)
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return avg_cost, [words, label], [batch_acc]
