"""MNIST ConvNet (parity: benchmark/fluid/models/mnist.py:36 cnn_model)."""
from __future__ import annotations

import numpy as np

import paddle_tpu.fluid as fluid

__all__ = ["cnn_model", "get_model"]


def cnn_model(data):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")

    size = 10
    input_shape = conv_pool_2.shape
    param_shape = [int(np.prod(input_shape[1:]))] + [size]
    scale = (2.0 / (param_shape[0] ** 2 * size)) ** 0.5
    predict = fluid.layers.fc(
        input=conv_pool_2, size=size, act="softmax",
        param_attr=fluid.param_attr.ParamAttr(
            initializer=fluid.initializer.NormalInitializer(
                loc=0.0, scale=scale)))
    return predict


def get_model(batch_size=64, learning_rate=0.001):
    """Build the train graph in the current default program.

    Returns (avg_cost, [img, label], [batch_acc]) like the reference
    harness's ``get_model`` (benchmark/fluid/models/mnist.py:69).
    """
    images = fluid.layers.data(name="pixel", shape=[1, 28, 28],
                               dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = cnn_model(images)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    opt = fluid.optimizer.Adam(learning_rate=learning_rate, beta1=0.9,
                               beta2=0.999)
    opt.minimize(avg_cost)
    return avg_cost, [images, label], [batch_acc]
