"""Seq2seq-attention NMT (parity: benchmark/fluid/models/
machine_translation.py + book test machine_translation — encoder-decoder
with attention, WMT-style vocab).

The reference runs Bahdanau attention step-by-step inside a DynamicRNN
(sequence_expand + sequence_softmax per decoder step); here the decoder
recurrence is a dynamic_lstm and the attention is one batched
seq_cross_attention op over all decoder steps — mathematically the
post-attention (Luong) formulation, compiled as a single masked einsum
chain on the MXU instead of T separate per-step graphs.
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["seq_to_seq_net", "get_model"]


def _encoder(src_word, src_dict_dim, emb_dim, hidden_dim):
    emb = fluid.layers.embedding(src_word, size=[src_dict_dim, emb_dim])
    proj = fluid.layers.fc(emb, size=hidden_dim * 4, act=None)
    fwd, _ = fluid.layers.dynamic_lstm(proj, size=hidden_dim * 4,
                                       use_peepholes=False)
    bproj = fluid.layers.fc(emb, size=hidden_dim * 4, act=None)
    bwd, _ = fluid.layers.dynamic_lstm(bproj, size=hidden_dim * 4,
                                       use_peepholes=False,
                                       is_reverse=True)
    return fluid.layers.concat([fwd, bwd], axis=-1)  # [N, Te, 2H]


def seq_to_seq_net(src_word, trg_word, src_dict_dim, trg_dict_dim,
                   emb_dim=512, hidden_dim=512):
    enc = _encoder(src_word, src_dict_dim, emb_dim, hidden_dim)
    enc_proj = fluid.layers.fc(enc, size=hidden_dim, act=None)

    trg_emb = fluid.layers.embedding(trg_word,
                                     size=[trg_dict_dim, emb_dim])
    dproj = fluid.layers.fc(trg_emb, size=hidden_dim * 4, act=None)
    dec, _ = fluid.layers.dynamic_lstm(dproj, size=hidden_dim * 4,
                                       use_peepholes=False)

    helper = fluid.layer_helper.LayerHelper("attention")
    ctxv = helper.create_tmp_variable(dec.dtype)
    helper.append_op(type="seq_cross_attention",
                     inputs={"Q": [dec], "K": [enc_proj],
                             "V": [enc_proj]},
                     outputs={"Out": [ctxv]})
    merged = fluid.layers.concat([dec, ctxv], axis=-1)
    att = fluid.layers.fc(merged, size=hidden_dim, act="tanh")
    logits = fluid.layers.fc(att, size=trg_dict_dim, act="softmax")
    return logits


def get_model(src_dict_dim=10000, trg_dict_dim=10000, emb_dim=256,
              hidden_dim=256, learning_rate=2e-3):
    """(avg_cost, [src_word, trg_word, trg_next], [])."""
    src_word = fluid.layers.data(name="source_sequence", shape=[1],
                                 lod_level=1, dtype="int64")
    trg_word = fluid.layers.data(name="target_sequence", shape=[1],
                                 lod_level=1, dtype="int64")
    label = fluid.layers.data(name="label_sequence", shape=[1],
                              lod_level=1, dtype="int64")
    prediction = seq_to_seq_net(src_word, trg_word, src_dict_dim,
                                trg_dict_dim, emb_dim, hidden_dim)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return avg_cost, [src_word, trg_word, label], []
