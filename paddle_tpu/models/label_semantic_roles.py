"""Semantic-role-labeling book model (parity:
python/paddle/fluid/tests/book/test_label_semantic_roles.py — the
8-feature db_lstm: per-feature embeddings (words share one frozen
table), a depth-8 alternating-direction LSTM stack with direct edges,
linear-chain CRF loss and crf_decoding inference).

The bidirectional-ish stack is eight masked lax.scan LSTMs (alternating
is_reverse) fused into one XLA program; the CRF is the exact
forward-algorithm lowering in ops/crf.py.
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["db_lstm", "get_model"]

WORD_DIM = 32
MARK_DIM = 5
MARK_DICT_LEN = 2
EMBEDDING_NAME = "emb"


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, label_dict_len, pred_dict_len,
            hidden_dim=512, depth=8, emb_lr=1.0, train_word_emb=False):
    predicate_embedding = fluid.layers.embedding(
        input=predicate, size=[pred_dict_len, WORD_DIM], dtype="float32",
        is_sparse=True, param_attr="vemb")
    mark_embedding = fluid.layers.embedding(
        input=mark, size=[MARK_DICT_LEN, MARK_DIM], dtype="float32",
        is_sparse=True)

    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    # the six word-context features share one embedding table; frozen by
    # default because the reference loads it from a pre-trained emb file
    # (load_parameter) — train it when no pre-trained table exists
    emb_layers = [
        fluid.layers.embedding(
            input=x, size=[word_dict_len, WORD_DIM],
            param_attr=fluid.ParamAttr(name=EMBEDDING_NAME,
                                       trainable=train_word_emb,
                                       learning_rate=emb_lr))
        for x in word_input]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0_layers = [fluid.layers.fc(input=emb, size=hidden_dim)
                       for emb in emb_layers]
    hidden_0 = fluid.layers.sums(input=hidden_0_layers)

    lstm_0, _ = fluid.layers.dynamic_lstm(
        input=hidden_0, size=hidden_dim, candidate_activation="relu",
        gate_activation="sigmoid", cell_activation="sigmoid")

    # stack L-LSTM and R-LSTM with direct edges
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=hidden_dim),
            fluid.layers.fc(input=input_tmp[1], size=hidden_dim)])
        lstm, _ = fluid.layers.dynamic_lstm(
            input=mix_hidden, size=hidden_dim,
            candidate_activation="relu", gate_activation="sigmoid",
            cell_activation="sigmoid", is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    return fluid.layers.sums(input=[
        fluid.layers.fc(input=input_tmp[0], size=label_dict_len,
                        act="tanh"),
        fluid.layers.fc(input=input_tmp[1], size=label_dict_len,
                        act="tanh")])


def get_model(word_dict_len, label_dict_len, pred_dict_len, hidden_dim=512,
              depth=8, mix_hidden_lr=1e-3, train_word_emb=False,
              learning_rate=0.01):
    """(avg_cost, feed vars in conll05 column order, [crf_decode])."""

    def seq_data(name):
        return fluid.layers.data(name=name, shape=[1], dtype="int64",
                                 lod_level=1)

    word = seq_data("word_data")
    predicate = seq_data("verb_data")
    ctx_n2 = seq_data("ctx_n2_data")
    ctx_n1 = seq_data("ctx_n1_data")
    ctx_0 = seq_data("ctx_0_data")
    ctx_p1 = seq_data("ctx_p1_data")
    ctx_p2 = seq_data("ctx_p2_data")
    mark = seq_data("mark_data")
    target = seq_data("target")

    feature_out = db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1,
                          ctx_p2, mark, word_dict_len, label_dict_len,
                          pred_dict_len, hidden_dim, depth,
                          train_word_emb=train_word_emb)

    crf_cost = fluid.layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name="crfw",
                                   learning_rate=mix_hidden_lr))
    avg_cost = fluid.layers.mean(crf_cost)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)

    crf_decode = fluid.layers.crf_decoding(
        input=feature_out, param_attr=fluid.ParamAttr(name="crfw"))

    feeds = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate, mark,
             target]
    return avg_cost, feeds, [crf_decode]
