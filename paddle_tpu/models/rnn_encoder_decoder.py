"""Seq2seq without attention, DynamicRNN decoder (parity: book test
python/paddle/fluid/tests/book/test_rnn_encoder_decoder.py — bi-LSTM
encoder + hand-built LSTM-cell DynamicRNN decoder).

Unlike models/machine_translation.py (which batches the decoder into one
dynamic_lstm + attention op chain), this model exercises the control-flow
front-end: the decoder is a ``fluid.layers.DynamicRNN`` whose per-step
sub-block (concat -> 4 fc gates -> cell update) is scanned over the target
sequence by the ``recurrent`` op (lax.scan), with per-row masking past
each sequence's length.
"""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["seq_to_seq_net", "get_model"]


def bi_lstm_encoder(input_seq, hidden_dim):
    """Forward+backward LSTM over the padded [N, T, D] source embedding
    (reference test_rnn_encoder_decoder.py:40-60)."""
    fwd_proj = fluid.layers.fc(input=input_seq, size=hidden_dim * 4,
                               bias_attr=False)
    forward, _ = fluid.layers.dynamic_lstm(fwd_proj, size=hidden_dim * 4,
                                           use_peepholes=False)
    bwd_proj = fluid.layers.fc(input=input_seq, size=hidden_dim * 4,
                               bias_attr=False)
    backward, _ = fluid.layers.dynamic_lstm(bwd_proj, size=hidden_dim * 4,
                                            use_peepholes=False,
                                            is_reverse=True)
    return forward, backward


def lstm_step(x_t, hidden_t_prev, cell_t_prev, size):
    """One LSTM cell from four fc gates (reference
    test_rnn_encoder_decoder.py:63-82)."""

    def linear(inputs):
        return fluid.layers.fc(input=inputs, size=size, bias_attr=True)

    forget_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    input_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    output_gate = fluid.layers.sigmoid(linear([hidden_t_prev, x_t]))
    cell_tilde = fluid.layers.tanh(linear([hidden_t_prev, x_t]))

    cell_t = fluid.layers.sums(input=[
        fluid.layers.elementwise_mul(x=forget_gate, y=cell_t_prev),
        fluid.layers.elementwise_mul(x=input_gate, y=cell_tilde)])
    hidden_t = fluid.layers.elementwise_mul(
        x=output_gate, y=fluid.layers.tanh(cell_t))
    return hidden_t, cell_t


def lstm_decoder_without_attention(target_embedding, decoder_boot, context,
                                   decoder_size, target_dict_dim):
    """DynamicRNN decoder (reference test_rnn_encoder_decoder.py:85-112)."""
    rnn = fluid.layers.DynamicRNN()

    cell_init = fluid.layers.fill_constant_batch_size_like(
        input=decoder_boot, shape=[1, decoder_size], dtype="float32",
        value=0.0)

    with rnn.block():
        current_word = rnn.step_input(target_embedding)
        context_ = rnn.static_input(context)
        hidden_mem = rnn.memory(init=decoder_boot, need_reorder=True)
        cell_mem = rnn.memory(init=cell_init)
        decoder_inputs = fluid.layers.concat(
            input=[context_, current_word], axis=1)
        h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem, decoder_size)
        rnn.update_memory(hidden_mem, h)
        rnn.update_memory(cell_mem, c)
        out = fluid.layers.fc(input=h, size=target_dict_dim,
                              act="softmax")
        rnn.output(out)
    return rnn()


def seq_to_seq_net(src_word, trg_word, src_dict_dim, trg_dict_dim,
                   emb_dim=32, encoder_size=32, decoder_size=32):
    src_embedding = fluid.layers.embedding(
        src_word, size=[src_dict_dim, emb_dim])
    src_forward, src_backward = bi_lstm_encoder(src_embedding, encoder_size)

    # context = last forward state + first backward state
    forward_last = fluid.layers.sequence_last_step(input=src_forward)
    backward_first = fluid.layers.sequence_first_step(input=src_backward)
    encoded_vector = fluid.layers.concat(
        input=[forward_last, backward_first], axis=1)
    decoder_boot = fluid.layers.fc(input=backward_first, size=decoder_size,
                                   act=None, bias_attr=False)

    trg_embedding = fluid.layers.embedding(
        trg_word, size=[trg_dict_dim, emb_dim])
    prediction = lstm_decoder_without_attention(
        trg_embedding, decoder_boot, encoded_vector, decoder_size,
        trg_dict_dim)
    return prediction


def get_model(src_dict_dim=60, trg_dict_dim=60, emb_dim=32, hidden_dim=32,
              learning_rate=2e-3):
    """(avg_cost, [src, trg, label], [])."""
    src_word = fluid.layers.data(name="source_sequence", shape=[1],
                                 lod_level=1, dtype="int64")
    trg_word = fluid.layers.data(name="target_sequence", shape=[1],
                                 lod_level=1, dtype="int64")
    label = fluid.layers.data(name="label_sequence", shape=[1],
                              lod_level=1, dtype="int64")
    prediction = seq_to_seq_net(src_word, trg_word, src_dict_dim,
                                trg_dict_dim, emb_dim, hidden_dim,
                                hidden_dim)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return avg_cost, [src_word, trg_word, label], []
