"""Host-side IO ops: feed / fetch / save / load / print.

Parity: reference operators/feed_op.cc, fetch_op.cc, save_op.cc, load_op.cc,
save_combine_op.cc, load_combine_op.cc, print_op.cc.  These run on the host
(the executor peels them off the compiled block — see executor_impl._segment).
"""
from __future__ import annotations

import os

import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.utils import serialization


def _host(name):
    def deco(impl):
        register_op(name, lower=impl, host_op=True, grad_maker=None)
        return impl

    return deco


@_host("feed")
def _feed(executor, op, scope, feed, env=None):
    out = op.output("Out")[0]
    val = feed.get(out)
    if val is not None:
        target = env if env is not None else scope
        if env is not None:
            env[out] = val
        else:
            scope.set(out, np.asarray(val))


@_host("fetch")
def _fetch(executor, op, scope, feed, env=None):
    # fetch handled by the executor's fetch_list; op kept for program parity
    pass


@_host("save")
def _save(executor, op, scope, feed, env=None):
    path = op.attr("file_path")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    name = op.input("X")[0]
    val = (env[name] if env is not None and name in env
           else scope.find_var(name))
    serialization.save_tensor(path, np.asarray(val))


@_host("load")
def _load(executor, op, scope, feed, env=None):
    path = op.attr("file_path")
    arr = serialization.load_tensor(path)
    name = op.output("Out")[0]
    if env is not None:
        env[name] = arr
    s = scope.find_scope_of(name) or scope
    s.set(name, arr)


@_host("save_combine")
def _save_combine(executor, op, scope, feed, env=None):
    path = op.attr("file_path")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    items = []
    for name in op.input("X"):
        val = (env[name] if env is not None and name in env
           else scope.find_var(name))
        items.append((name, np.asarray(val)))
    serialization.save_combined(path, items)


@_host("load_combine")
def _load_combine(executor, op, scope, feed, env=None):
    path = op.attr("file_path")
    loaded = dict(serialization.load_combined(path))
    for name in op.output("Out"):
        arr = loaded[name]
        if env is not None:
            env[name] = arr
        s = scope.find_scope_of(name) or scope
        s.set(name, arr)


@_host("print")
def _print(executor, op, scope, feed, env=None):
    name = op.input("In")[0]
    val = (env[name] if env is not None and name in env
           else scope.find_var(name))
    msg = op.attr("message", "")
    arr = np.asarray(val)
    parts = [msg or name]
    if op.attr("print_tensor_shape", True):
        parts.append("shape=%s" % (arr.shape,))
    if op.attr("print_tensor_type", True):
        parts.append("dtype=%s" % arr.dtype)
    if op.attr("summarize", -1) != 0:
        parts.append("data=%s" % np.array2string(arr, threshold=20))
    print("\t".join(parts))
    if env is not None and op.output("Out", []):
        env[op.output("Out")[0]] = val


@_host("delete_var")
def _delete_var(executor, op, scope, feed, env=None):
    scope.erase(op.input("X"))
