"""Math / elementwise / activation / reduce ops.

Parity: reference operators/elementwise_*_op.cc, activation_op.cc, mul_op.cc,
matmul_op.cc, scale_op.cc, sum_op.cc, mean_op.cc, reduce_op.cc, clip_op.cc,
compare_op.cc, logical_op.cc, cast_op.cc, cumsum_op.cc, sign_op.cc,
cos_sim_op.cc.  All lower to jnp/lax; gradients come from the generic vjp of
the lowering (XLA fuses them), so no hand-written grad kernels are needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.types import proto_to_np_dtype


# ---------------------------------------------------------------------------
# Elementwise binary ops with the reference's axis-broadcast rule
# (elementwise_op_function.h): y's dims align to x's starting at `axis`.
# ---------------------------------------------------------------------------

def broadcast_y_to_x(x, y, axis):
    if x.shape == y.shape or y.ndim == 0:
        return y
    if axis < 0:
        axis = x.ndim - y.ndim
    new_shape = [1] * axis + list(y.shape) + [1] * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _ew(name, fn):
    def lower(ctx, ins, attrs, op):
        x = ins["X"]
        y = broadcast_y_to_x(x, ins["Y"], attrs.get("axis", -1))
        return {"Out": fn(x, y)}

    register_op(name, lower=lower)


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod)
_ew("elementwise_floordiv", jnp.floor_divide)


# ---------------------------------------------------------------------------
# Activations (reference activation_op.cc registers ~20 of these).
# ---------------------------------------------------------------------------

def _act(name, fn, **reg_kwargs):
    def lower(ctx, ins, attrs, op):
        return {"Out": fn(ins["X"], attrs)}

    register_op(name, lower=lower, **reg_kwargs)


_act("relu", lambda x, a: jax.nn.relu(x))
_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_act("sqrt", lambda x, a: jnp.sqrt(x))
_act("abs", lambda x, a: jnp.abs(x))
_act("ceil", lambda x, a: jnp.ceil(x), grad_maker=None)
_act("floor", lambda x, a: jnp.floor(x), grad_maker=None)
_act("round", lambda x, a: jnp.round(x), grad_maker=None)
_act("cos", lambda x, a: jnp.cos(x))
_act("sin", lambda x, a: jnp.sin(x))
_act("exp", lambda x, a: jnp.exp(x))
_act("log", lambda x, a: jnp.log(x))
_act("square", lambda x, a: jnp.square(x))
_act("reciprocal", lambda x, a: 1.0 / x)
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_act("relu6", lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)))
_act("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_act("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
    a.get("scale_a", 2.0 / 3.0) * x))
_act("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_act("elu", lambda x, a: jnp.where(
    x > 0, x, a.get("alpha", 1.0) * (jnp.exp(jnp.minimum(x, 0.0)) - 1)))
_act("leaky_relu", lambda x, a: jnp.where(x > 0, x, a.get("alpha", 0.02) * x))
_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0),
                                    a.get("t_max", 24.0)))
_act("soft_relu", lambda x, a: jnp.log(
    1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                         a.get("threshold", 40.0)))))
_act("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, jnp.zeros_like(x)))
_act("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, jnp.zeros_like(x)))
_act("softshrink", lambda x, a: jnp.sign(x) * jnp.maximum(
    jnp.abs(x) - a.get("lambda", 0.5), 0.0))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
# tanh-approximate gelu — the transformer MLP activation; the fused
# matmul epilogue (kernels/matmul_fused.py apply_act) must stay in
# lockstep with this definition
_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=True))
_act("sign", lambda x, a: jnp.sign(x), grad_maker=None)


@register_op("prelu")
def _prelu(ctx, ins, attrs, op):
    x, alpha = ins["X"], ins["Alpha"]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    return {"Out": jnp.where(x > 0, x, alpha * x)}


# ---------------------------------------------------------------------------
# Linear algebra
# ---------------------------------------------------------------------------

@register_op("mul")
def _mul(ctx, ins, attrs, op):
    """reference mul_op.cc: flatten X to 2-D by x_num_col_dims, Y by
    y_num_col_dims, matmul, restore leading dims.  This is THE fc matmul —
    it must land on the MXU, hence a plain jnp.dot."""
    x, y = ins["X"], ins["Y"]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), -1))
    y2 = y.reshape((int(np.prod(ys[:yn])), -1))
    out = jnp.dot(x2, y2, preferred_element_type=jnp.result_type(x2, y2))
    return {"Out": out.reshape(xs[:xn] + ys[yn:])}


@register_op("matmul")
def _matmul(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("scale")
def _scale(ctx, ins, attrs, op):
    from paddle_tpu.core.selected_rows import SelectedRows

    x = ins["X"]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if isinstance(x, SelectedRows):   # grad scaling of sparse grads
        assert bias == 0.0, "scale(SelectedRows) supports bias=0 only"
        return {"Out": x.scale(scale)}
    if attrs.get("bias_after_scale", True):
        return {"Out": x * scale + bias}
    return {"Out": (x + bias) * scale}


@register_op("sum")
def _sum(ctx, ins, attrs, op):
    from paddle_tpu.core.selected_rows import SelectedRows, concat_rows

    xs = [x for x in ins.list("X") if x is not None]
    sparse = [isinstance(x, SelectedRows) for x in xs]
    if all(sparse) and xs:
        # sum of sparse grads = concatenated rows (scatter-add semantics),
        # reference operators/sum_op SelectedRows kernel
        return {"Out": concat_rows(xs)}
    if any(sparse):
        xs = [x.to_dense() if isinstance(x, SelectedRows) else x
              for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean", seq_aware=True)
def _mean(ctx, ins, attrs, op):
    """Mean over all elements; over VALID elements for ragged inputs (the
    reference averages over sum_T packed tokens — lod_tensor.h:58 — so a
    padded batch must not count its padding)."""
    x = ins["X"]
    lens = None
    if op is not None:
        names = op.inputs.get("X") or []
        if names and names[0]:
            lens = ctx.seq_len_of(names[0])
    if lens is not None and x.ndim >= 2:
        mask = (jnp.arange(x.shape[1])[None, :] <
                lens[:, None]).astype(x.dtype)
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        denom = jnp.sum(mask) * float(np.prod(x.shape[2:]) or 1.0)
        return {"Out": (jnp.sum(x * mask) /
                        jnp.maximum(denom, 1.0)).reshape((1,))}
    return {"Out": jnp.mean(x).reshape((1,))}


@register_op("minus")
def _minus(ctx, ins, attrs, op):
    return {"Out": ins["X"] - ins["Y"]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
    z = jnp.sum(x * y, axis=1, keepdims=True) / (xn * yn)
    return {"Out": z, "XNorm": xn, "YNorm": yn}


@register_op("clip")
def _clip(ctx, ins, attrs, op):
    return {"Out": jnp.clip(ins["X"], attrs.get("min"), attrs.get("max"))}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs, op):
    x = ins["X"]
    max_norm = attrs.get("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      jnp.ones_like(norm))
    return {"Out": x * scale}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs, op):
    return {"Out": jnp.sum(jnp.square(ins["X"])).reshape((1,))}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    diff = x - y
    return {"sub_result": diff,
            "Out": jnp.sum(jnp.square(diff), axis=1, keepdims=True)}


@register_op("l1_norm")
def _l1_norm(ctx, ins, attrs, op):
    return {"Out": jnp.sum(jnp.abs(ins["X"])).reshape((1,))}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs, op):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    rev = attrs.get("reverse", False)
    if rev:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - (jnp.flip(ins["X"], axis) if rev else ins["X"])
    if rev:
        out = jnp.flip(out, axis)
    return {"Out": out}


@register_op("norm")
def _norm(ctx, ins, attrs, op):
    x = ins["X"]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


# ---------------------------------------------------------------------------
# Reduce family (reference reduce_op.cc)
# ---------------------------------------------------------------------------

def _reduce(name, fn):
    def lower(ctx, ins, attrs, op):
        x = ins["X"]
        dims = attrs.get("dim", [0])
        if isinstance(dims, int):
            dims = [dims]
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False):
            out = fn(x, axis=None, keepdims=keep)
            if not keep:
                out = out.reshape((1,))
        else:
            axes = tuple(d if d >= 0 else d + x.ndim for d in dims)
            out = fn(x, axis=axes, keepdims=keep)
        return {"Out": out}

    register_op(name, lower=lower)


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)


# ---------------------------------------------------------------------------
# Comparison / logical (bool outputs, non-differentiable)
# ---------------------------------------------------------------------------

def _cmp(name, fn):
    def lower(ctx, ins, attrs, op):
        x = ins["X"]
        y = broadcast_y_to_x(x, ins["Y"], attrs.get("axis", -1))
        return {"Out": fn(x, y)}

    register_op(name, lower=lower, grad_maker=None)


_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)


def _logical(name, fn, unary=False):
    def lower(ctx, ins, attrs, op):
        if unary:
            return {"Out": fn(ins["X"])}
        return {"Out": fn(ins["X"], ins["Y"])}

    register_op(name, lower=lower, grad_maker=None)


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, unary=True)


@register_op("cast", grad_maker="default")
def _cast(ctx, ins, attrs, op):
    out_dtype = proto_to_np_dtype(attrs["out_dtype"])
    return {"Out": ins["X"].astype(out_dtype)}


@register_op("isfinite", grad_maker=None)
def _isfinite(ctx, ins, attrs, op):
    return {"Out": jnp.isfinite(ins["X"]).all().reshape((1,))}


@register_op("increment")
def _increment(ctx, ins, attrs, op):
    x = ins["X"]
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), dtype=x.dtype)}


@register_op("maxout")
def _maxout(ctx, ins, attrs, op):
    x = ins["X"]  # NCHW
    groups = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, c // groups, groups, h, w).max(axis=2)}
