"""Program-level reader-op chain (parity: paddle/fluid/operators/reader/
— create_recordio_file_reader_op, create_shuffle_reader_op,
create_batch_reader_op, create_double_buffer_reader_op, read_op, and
framework/reader.h's ReaderBase chain).

The reference builds a C++ decorator chain of ReaderBase objects living
in the scope; here the same chain is host-side Python state objects the
'read' host op pops, with the double-buffer stage prefetching device-put
batches on a thread exactly where the reference staged pinned-memory
copies (reader/create_double_buffer_reader_op.cc).
"""
from __future__ import annotations

import pickle
import queue
import threading

import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.executor_impl import EOFException


def _host(name):
    def deco(impl):
        register_op(name, lower=impl, host_op=True, grad_maker=None)
        return impl

    return deco


class _ReaderBase:
    """next() -> tuple of per-slot numpy arrays for ONE sample/batch;
    raises EOFException when drained; reset() rewinds."""

    def next(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class _RecordIOReader(_ReaderBase):
    def __init__(self, filename, pass_num=1):
        self.filename = filename
        self.pass_num = max(1, int(pass_num))
        self._iter = None
        self._passes_left = self.pass_num

    def _scanner(self):
        from paddle_tpu import recordio
        for rec in recordio.Scanner(self.filename):
            sample = pickle.loads(rec)
            if isinstance(sample, dict):  # feeder-serialized form
                sample = tuple(sample.values())
            yield tuple(np.asarray(x) for x in sample)

    def next(self):
        if self._iter is None:
            self._iter = self._scanner()
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            self._passes_left -= 1
            if self._passes_left > 0:  # pass_num epochs before EOF
                return self.next()
            self._passes_left = self.pass_num
            raise EOFException(self.filename)

    def reset(self):
        self._iter = None
        self._passes_left = self.pass_num


class _ShuffleReader(_ReaderBase):
    def __init__(self, parent, buffer_size, seed=0):
        self.parent = parent
        self.buffer_size = int(buffer_size)
        self.rng = np.random.RandomState(seed)
        self.buf = []
        self.drained = False

    def next(self):
        while not self.drained and len(self.buf) < self.buffer_size:
            try:
                self.buf.append(self.parent.next())
            except EOFException:
                self.drained = True
        if not self.buf:
            self.drained = False
            raise EOFException("shuffle")
        idx = self.rng.randint(len(self.buf))
        self.buf[idx], self.buf[-1] = self.buf[-1], self.buf[idx]
        return self.buf.pop()

    def reset(self):
        self.buf = []
        self.drained = False
        self.parent.reset()


class _BatchReader(_ReaderBase):
    """drop_last=True is the default here (NOT the reference's: its
    BatchReader emits the final partial batch,
    create_batch_reader_op.cc) — a ragged tail batch would trigger an
    XLA recompile per epoch; pass drop_last=False through
    layers.io.batch to restore reference semantics."""

    def __init__(self, parent, batch_size, drop_last=True):
        self.parent = parent
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def next(self):
        rows = []
        try:
            for _ in range(self.batch_size):
                rows.append(self.parent.next())
        except EOFException:
            if not rows or self.drop_last:
                raise EOFException("batch")
        return tuple(np.stack([r[i] for r in rows])
                     for i in range(len(rows[0])))

    def reset(self):
        self.parent.reset()


class _DoubleBufferReader(_ReaderBase):
    """Thread prefetches upcoming batches and stages them on the target
    device, overlapping host decode + transfer with device compute."""

    def __init__(self, parent, capacity=2, place=None):
        self.parent = parent
        self.capacity = int(capacity)
        self.place = place
        self._q = None
        self._thread = None
        self._stop = None

    def _start(self):
        q = queue.Queue(self.capacity)
        stop = threading.Event()
        self._q, self._stop = q, stop

        def work():
            # q/stop are captured locally: a superseded worker can never
            # touch the queue of the thread that replaced it
            try:
                while not stop.is_set():
                    batch = self.parent.next()
                    if self.place is not None:
                        import jax
                        dev = self.place.jax_device()
                        batch = tuple(jax.device_put(x, dev)
                                      for x in batch)
                    q.put(batch)
            except EOFException:
                q.put(EOFException("double_buffer"))
            except Exception as e:  # surface decode errors to the reader
                q.put(e)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def next(self):
        if self._thread is None:
            self._start()
        item = self._q.get()
        if isinstance(item, Exception):
            self._thread = None
            raise item
        return item

    def reset(self):
        thread, q, stop = self._thread, self._q, self._stop
        self._thread = None
        if thread is not None and thread.is_alive():
            # mid-epoch reset: signal the worker, unblock any pending
            # put, and WAIT for it to die before rewinding the parent —
            # otherwise two threads race on the unsynchronized chain
            stop.set()
            while thread.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)
        self.parent.reset()


class _MultiPassReader(_ReaderBase):
    """Replay the underlying chain pass_num times before raising EOF
    (reference create_multi_pass_reader_op.cc: the trainer loop sees N
    epochs as one stream); tracks the current pass for introspection."""

    def __init__(self, parent, pass_num):
        self.parent = parent
        self.pass_num = max(1, int(pass_num))
        self.current_pass = 0

    def next(self):
        # loop, don't recurse into parent.next() bare: an EOF right
        # after an intra-pass reset (empty parent) must keep counting
        # passes, or the NEXT epoch starts with a stale current_pass
        while True:
            try:
                return self.parent.next()
            except EOFException:
                self.current_pass += 1
                if self.current_pass >= self.pass_num:
                    self.current_pass = 0
                    raise
                self.parent.reset()

    def reset(self):
        self.current_pass = 0
        self.parent.reset()


class _ThreadedReader(_ReaderBase):
    """Thread-safe prefetching front (reference
    create_threaded_reader_op.cc: wraps a chain so concurrent ReadNext
    calls are safe).  A single worker drains the (unsynchronized)
    parent into a bounded queue; any number of consumer threads pop."""

    def __init__(self, parent, capacity=16):
        self.parent = parent
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._q = None
        self._thread = None
        self._stop = None

    def _start(self):
        q = queue.Queue(self.capacity)
        stop = threading.Event()
        self._q, self._stop = q, stop

        def work():
            try:
                while not stop.is_set():
                    q.put(self.parent.next())
            except EOFException:
                q.put(EOFException("threaded"))
            except Exception as e:
                q.put(e)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def next(self):
        with self._lock:
            if self._thread is None:
                self._start()
            q = self._q
        item = q.get()
        if isinstance(item, Exception):
            with self._lock:
                self._thread = None
            # re-enqueue terminal items (EOF or an error) so EVERY
            # blocked consumer sees them, not just the first to pop —
            # the worker has exited and will produce nothing else
            q.put(item)
            raise item
        return item

    def reset(self):
        with self._lock:
            thread, q, stop = self._thread, self._q, self._stop
            self._thread = None
            if thread is not None and thread.is_alive():
                stop.set()
                while thread.is_alive():
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass
                    thread.join(timeout=0.05)
            self.parent.reset()


class _CustomReader(_ReaderBase):
    """Per-batch preprocessing through a fluid sub-block (reference
    create_custom_reader_op.cc CustomReader::ReadNext): each batch's
    slots land in the source vars, the sub-block runs through a nested
    executor, and the sink vars come back as the decorated batch."""

    def __init__(self, parent, program, block_id, source_names,
                 sink_names, place, scope):
        from paddle_tpu.core.executor_impl import ExecutorCore

        self.parent = parent
        self.program = program
        self.block_id = int(block_id)
        self.source_names = list(source_names)
        self.sink_names = list(sink_names)
        self._core = ExecutorCore(place)
        # kid scope of the RUN scope (reference CustomReader executes in
        # the run scope): a parameterized sub-block (fc etc.) must see
        # the weights the startup program initialized
        self._scope = scope.new_scope()

    def next(self):
        batch = self.parent.next()
        if len(batch) != len(self.source_names):
            raise ValueError(
                "custom reader: batch has %d slots but %d source vars"
                % (len(batch), len(self.source_names)))
        feed = dict(zip(self.source_names, batch))
        outs = self._core.run(self.program, self._scope, self.block_id,
                              feed=feed, fetch_list=self.sink_names)
        return tuple(np.asarray(o) for o in outs)

    def reset(self):
        self.parent.reset()


def _set_state(scope, name, state):
    (scope.find_scope_of(name) or scope).set(name, state)


def _get_state(scope, name):
    state = scope.find_var(name)
    if not isinstance(state, _ReaderBase):
        raise RuntimeError(
            "%r is not an initialized reader (run the startup program "
            "first)" % name)
    return state


@_host("create_recordio_file_reader")
def _create_recordio(executor, op, scope, feed, env=None):
    _set_state(scope, op.output("Out")[0],
               _RecordIOReader(op.attr("filename"),
                               pass_num=op.attr("pass_num") or 1))


class _MultiFileReader(_ReaderBase):
    """Concatenate several recordio files (reference
    open_files_op/multi_file_reader: N prefetch threads over a file
    list; here files stream sequentially — the double-buffer decorator
    supplies the prefetch thread)."""

    def __init__(self, filenames, pass_num=1):
        self.readers = [_RecordIOReader(f) for f in filenames]
        self.pass_num = max(1, int(pass_num))
        self._idx = 0
        self._passes_left = self.pass_num

    def next(self):
        while True:
            if self._idx >= len(self.readers):
                self._idx = 0
                self._passes_left -= 1
                if self._passes_left <= 0:
                    self._passes_left = self.pass_num
                    raise EOFException("open_files")
            try:
                return self.readers[self._idx].next()
            except EOFException:
                self._idx += 1

    def reset(self):
        self._idx = 0
        self._passes_left = self.pass_num
        for r in self.readers:
            r.reset()


class _ParallelFilesReader(_ReaderBase):
    """N worker threads each scan a round-robin subset of the files
    into one bounded queue (reference open_files_op's multi_file_reader
    thread pool); sample order across files is nondeterministic, EOF
    fires once every worker drained its subset."""

    def __init__(self, filenames, thread_num, capacity=64):
        self.filenames = list(filenames)
        self.thread_num = max(1, min(int(thread_num),
                                     len(self.filenames) or 1))
        self.capacity = int(capacity)
        self._q = None
        self._threads = None
        self._stop = None

    def _start(self):
        q = queue.Queue(self.capacity)
        stop = threading.Event()
        done = []

        def work(files):
            try:
                for f in files:
                    r = _RecordIOReader(f)
                    while not stop.is_set():
                        try:
                            q.put(r.next())
                        except EOFException:
                            break
            except Exception as e:
                q.put(e)
            finally:
                done.append(1)
                if len(done) == self.thread_num:
                    q.put(EOFException("open_files"))

        self._q, self._stop = q, stop
        self._threads = []
        for i in range(self.thread_num):
            t = threading.Thread(
                target=work, args=(self.filenames[i::self.thread_num],),
                daemon=True)
            t.start()
            self._threads.append(t)

    def next(self):
        if self._threads is None:
            self._start()
        item = self._q.get()
        if isinstance(item, Exception):
            # wind the POOL down before dropping it: surviving workers
            # are blocked putting into this bounded queue and would
            # leak (threads + open scanners) if just abandoned
            self._shutdown()
            raise item
        return item

    def _shutdown(self):
        threads, q, stop = self._threads, self._q, self._stop
        self._threads = None
        if threads:
            stop.set()
            while any(t.is_alive() for t in threads):
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                for t in threads:
                    t.join(timeout=0.02)

    def reset(self):
        self._shutdown()


class _RandomDataReader(_ReaderBase):
    """Uniform random sample generator (reference
    create_random_data_generator_op) — a dummy reader to drive a
    network without any file."""

    def __init__(self, low, high, shapes, seed=0):
        # shapes are concrete per-sample dims (the layer strips the
        # batch dim before flattening into attrs)
        self.low, self.high = float(low), float(high)
        self.shapes = [tuple(int(x) for x in s) for s in shapes]
        self.seed = seed
        self.rng = np.random.RandomState(seed)

    def next(self):
        return tuple(
            self.rng.uniform(self.low, self.high, s).astype(np.float32)
            for s in self.shapes)

    def reset(self):
        self.rng = np.random.RandomState(self.seed)


@_host("open_files")
def _open_files(executor, op, scope, feed, env=None):
    files = list(op.attr("filenames") or [])
    threads = int(op.attr("thread_num") or 1)
    if threads > 1:
        # thread-pool scan (order nondeterministic across files);
        # pass_num epochs compose via the multi_pass decorator
        rd = _ParallelFilesReader(files, threads)
        if (op.attr("pass_num") or 1) > 1:
            rd = _MultiPassReader(rd, op.attr("pass_num"))
    else:
        rd = _MultiFileReader(files, pass_num=op.attr("pass_num") or 1)
    _set_state(scope, op.output("Out")[0], rd)


@_host("create_random_data_generator")
def _create_random(executor, op, scope, feed, env=None):
    # shapes travel flattened (attrs hold flat lists only):
    # shape_concat=[3,224,224,1], ranks=[3,1] -> [(3,224,224), (1,)]
    concat = list(op.attr("shape_concat") or [])
    shapes, i = [], 0
    for r in (op.attr("ranks") or []):
        shapes.append(tuple(concat[i:i + r]))
        i += r
    _set_state(scope, op.output("Out")[0],
               _RandomDataReader(op.attr("low"), op.attr("high"), shapes))


@_host("create_custom_reader")
def _create_custom(executor, op, scope, feed, env=None):
    out = op.output("Out")[0]
    if scope.has_var(out) and isinstance(scope.find_var(out),
                                         _CustomReader):
        return  # main-block op: idempotent across steps
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    block_id = op.attr("sub_block")
    if hasattr(block_id, "idx"):
        block_id = block_id.idx
    _set_state(scope, out, _CustomReader(
        parent, executor._current_program, block_id,
        op.attr("source_var_names") or [],
        op.attr("sink_var_names") or [], executor.place, scope))


@_host("create_multi_pass_reader")
def _create_multi_pass(executor, op, scope, feed, env=None):
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    _set_state(scope, op.output("Out")[0],
               _MultiPassReader(parent, op.attr("pass_num") or 1))


@_host("create_threaded_reader")
def _create_threaded(executor, op, scope, feed, env=None):
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    _set_state(scope, op.output("Out")[0],
               _ThreadedReader(parent, op.attr("capacity") or 16))


@_host("create_shuffle_reader")
def _create_shuffle(executor, op, scope, feed, env=None):
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    _set_state(scope, op.output("Out")[0],
               _ShuffleReader(parent, op.attr("buffer_size")))


@_host("create_batch_reader")
def _create_batch(executor, op, scope, feed, env=None):
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    _set_state(scope, op.output("Out")[0],
               _BatchReader(parent, op.attr("batch_size"),
                            drop_last=bool(op.attr("drop_last")
                                           if op.attr("drop_last")
                                           is not None else True)))


@_host("create_double_buffer_reader")
def _create_double_buffer(executor, op, scope, feed, env=None):
    parent = _get_state(scope, op.input("UnderlyingReader")[0])
    _set_state(scope, op.output("Out")[0],
               _DoubleBufferReader(parent, capacity=2,
                                   place=executor.place))


@_host("read")
def _read(executor, op, scope, feed, env=None):
    state = _get_state(scope, op.input("Reader")[0])
    batch = state.next()  # EOFException propagates to the caller
    outs = op.output("Out")
    if len(batch) != len(outs):
        raise ValueError(
            "reader yields %d slots but read op has %d outputs"
            % (len(batch), len(outs)))
    for name, val in zip(outs, batch):
        if env is not None:
            env[name] = val
        # data vars go in the scope so the compiled core block (which
        # runs after this prelude host op) picks them up as inputs;
        # they are tagged as LOCAL-row data — on a multi-host mesh a
        # reader batch is this process's shard, not a replicated global
        # value (executor_impl._put local_rows semantics)
        (scope.find_scope_of(name) or scope).set(name, val)
        if not hasattr(scope, "_reader_batch_vars"):
            scope._reader_batch_vars = set()
        scope._reader_batch_vars.add(name)
