"""Structured-prediction ops: linear-chain CRF, Viterbi decoding, CTC
loss, CTC alignment, chunk evaluation.

Parity: reference operators/linear_chain_crf_op.{cc,h} (forward algorithm
returning the negative log-likelihood; grads there are hand-derived,
here jax.vjp of the forward), crf_decoding_op.cc (Viterbi),
warpctc_op.cc (the warp-ctc CUDA library; here a log-space alpha
recursion under lax.scan — same loss, no external kernel),
ctc_align_op.cc, chunk_eval_op.cc.

All ops run on the padded [N, T, ...] + '@LEN' representation (see
ops/sequence.py module docstring); the scans are time-major so XLA
compiles one fused loop per op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op

NEG = -1e30


def _lens_or_full(ctx, op, slot, n, t):
    names = (op.inputs.get(slot) or []) if op is not None else []
    lens = ctx.seq_len_of(names[0]) if names and names[0] else None
    if lens is None:
        return jnp.full((n,), t, jnp.int32)
    return lens.astype(jnp.int32)


# ---------------------------------------------------------------------------
# linear_chain_crf / crf_decoding
# ---------------------------------------------------------------------------

@register_op("linear_chain_crf", seq_aware=True)
def _linear_chain_crf(ctx, ins, attrs, op=None):
    """Emission [N,T,K]; Transition [K+2,K] (row 0 start, row 1 stop,
    rows 2.. pairwise [K,K]); Label [N,T,1] or [N,T] int.
    Output LogLikelihood [N,1] = logZ - gold score (the reference's
    negative log-likelihood, linear_chain_crf_op.h:193 returns -ll)."""
    em = ins["Emission"]
    w = ins["Transition"]
    label = ins["Label"]
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    n, t, k = em.shape
    lens = _lens_or_full(ctx, op, "Emission", n, t)
    start, stop, trans = w[0], w[1], w[2:]

    emf = em.astype(jnp.float32)
    steps = jnp.arange(t)
    valid = steps[None, :] < lens[:, None]          # [N,T]

    # --- logZ by the forward algorithm (log-space) ---
    alpha0 = start[None, :] + emf[:, 0, :]          # [N,K]

    def fwd(alpha, tm):
        e_t, v_t = tm                               # [N,K], [N]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + e_t
        return jnp.where(v_t[:, None], nxt, alpha), None

    alpha, _ = jax.lax.scan(
        fwd, alpha0, (jnp.moveaxis(emf, 1, 0)[1:],
                      jnp.moveaxis(valid, 1, 0)[1:]))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)   # [N]

    # --- gold path score ---
    em_lab = jnp.take_along_axis(emf, label[:, :, None],
                                 axis=2)[..., 0]             # [N,T]
    em_score = jnp.sum(jnp.where(valid, em_lab, 0.0), axis=1)
    pair = trans[label[:, :-1], label[:, 1:]]                # [N,T-1]
    pair_valid = valid[:, 1:]
    trans_score = jnp.sum(jnp.where(pair_valid, pair, 0.0), axis=1)
    last_idx = jnp.clip(lens - 1, 0, t - 1)
    last_lab = jnp.take_along_axis(label, last_idx[:, None],
                                   axis=1)[:, 0]
    gold = em_score + trans_score + start[label[:, 0]] + stop[last_lab]

    nll = (logz - gold) * (lens > 0)     # empty sequence costs 0
    return {"LogLikelihood": nll[:, None].astype(em.dtype)}


@register_op("crf_decoding", grad_maker=None, seq_aware=True)
def _crf_decoding(ctx, ins, attrs, op=None):
    """Viterbi decode (reference crf_decoding_op.h).  With Label given,
    emits the per-token correctness mask instead of the raw path (that
    is the reference behavior used by metrics)."""
    em = ins["Emission"].astype(jnp.float32)
    w = ins["Transition"]
    n, t, k = em.shape
    lens = _lens_or_full(ctx, op, "Emission", n, t)
    start, stop, trans = w[0], w[1], w[2:]
    steps = jnp.arange(t)
    valid = steps[None, :] < lens[:, None]

    delta0 = start[None, :] + em[:, 0, :]

    def fwd(delta, tm):
        e_t, v_t = tm
        scores = delta[:, :, None] + trans[None, :, :]       # [N,K,K]
        best = jnp.max(scores, axis=1) + e_t
        arg = jnp.argmax(scores, axis=1).astype(jnp.int32)   # [N,K]
        nxt = jnp.where(v_t[:, None], best, delta)
        return nxt, arg

    delta, back = jax.lax.scan(
        fwd, delta0, (jnp.moveaxis(em, 1, 0)[1:],
                      jnp.moveaxis(valid, 1, 0)[1:]))        # back [T-1,N,K]

    last = jnp.argmax(delta + stop[None, :], axis=1).astype(jnp.int32)

    # backtrack from each sequence's last step; frozen rows (t beyond the
    # sequence) pass the state through unchanged
    def bwd(state, tb):
        ptr, v_t = tb                                        # [N,K],[N]
        prev = jnp.take_along_axis(ptr, state[:, None], axis=1)[:, 0]
        new = jnp.where(v_t, prev, state)
        return new, state

    # path_rev[t] is the tag at t+1; the final carry is the time-0 tag
    first, path_rev = jax.lax.scan(
        bwd, last, (back, jnp.moveaxis(valid, 1, 0)[1:]), reverse=True)
    path = jnp.concatenate([first[None], path_rev], axis=0)  # [T,N]
    path = jnp.moveaxis(path, 0, 1)                          # [N,T]
    path = jnp.where(valid, path, 0).astype(jnp.int64)

    label = ins.get("Label")
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        out = (path == label.astype(jnp.int64)) & valid
        return {"ViterbiPath": out.astype(jnp.int64)[..., None]}
    return {"ViterbiPath": path[..., None]}


# ---------------------------------------------------------------------------
# warpctc / ctc_align
# ---------------------------------------------------------------------------

@register_op("warpctc", seq_aware=True, no_vjp_outputs=("WarpCTCGrad",))
def _warpctc(ctx, ins, attrs, op=None):
    """CTC loss (reference warpctc_op.cc wraps the warp-ctc library).
    Logits [N,T,V] raw (softmax applied internally, like warp-ctc);
    Label [N,L] int with its own '@LEN'.  Loss [N,1]."""
    logits = ins["Logits"].astype(jnp.float32)
    label = ins["Label"]
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    n, t, v = logits.shape
    lmax = label.shape[1]
    t_lens = _lens_or_full(ctx, op, "Logits", n, t)
    l_lens = _lens_or_full(ctx, op, "Label", n, lmax)

    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended label sequence [blank, l1, blank, ..., lL, blank]: S=2L+1
    s = 2 * lmax + 1
    ext = jnp.full((n, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    s_lens = 2 * l_lens + 1
    pos = jnp.arange(s)[None, :]
    s_valid = pos < s_lens[:, None]                          # [N,S]

    # skip-transition allowed into odd (label) states whose label differs
    # from the one two back
    can_skip = jnp.zeros((n, s), bool)
    can_skip = can_skip.at[:, 3::2].set(label[:, 1:] != label[:, :-1])

    def emit(t_idx):
        lp = logp[:, t_idx, :]                               # [N,V]
        return jnp.take_along_axis(lp, ext, axis=1)          # [N,S]

    alpha = jnp.full((n, s), NEG, jnp.float32)
    alpha = alpha.at[:, 0].set(logp[:, 0, blank])
    has_lab = lmax > 0
    if has_lab:
        first_lab = jnp.take_along_axis(logp[:, 0, :], label[:, :1],
                                        axis=1)[:, 0]
        alpha = alpha.at[:, 1].set(
            jnp.where(l_lens > 0, first_lab, NEG))

    def shift(a, by):
        return jnp.concatenate(
            [jnp.full((n, by), NEG, jnp.float32), a[:, :-by]], axis=1)

    def step(alpha, t_idx):
        stay = alpha
        one = shift(alpha, 1)
        two = jnp.where(can_skip, shift(alpha, 2), NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, one), two)
        nxt = jnp.where(s_valid, merged + emit(t_idx), NEG)
        live = t_idx < t_lens[:, None]
        return jnp.where(live, nxt, alpha), None

    alpha, _ = jax.lax.scan(step, alpha, jnp.arange(1, t))

    last = jnp.clip(s_lens - 1, 0, s - 1)
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.clip(last - 1, 0, s - 1)
                                 [:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(a_last,
                          jnp.where(l_lens > 0, a_prev, NEG))
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(t_lens.astype(jnp.float32), 1.0)
    return {"Loss": loss[:, None].astype(ins["Logits"].dtype),
            "WarpCTCGrad": jnp.zeros_like(logits)}


@register_op("ctc_align", grad_maker=None, seq_aware=True)
def _ctc_align(ctx, ins, attrs, op=None):
    """Merge repeats then drop blanks, left-aligned (reference
    ctc_align_op.h).  Input [N,T] (or [N,T,1]) int; Output same shape,
    tail padded with ``padding_value``; '@LEN' carries new lengths."""
    x = ins["Input"]
    squeeze = x.ndim == 3
    if squeeze:
        x = x[..., 0]
    blank = int(attrs.get("blank", 0))
    pad_val = int(attrs.get("padding_value", 0))
    n, t = x.shape
    lens = _lens_or_full(ctx, op, "Input", n, t)
    steps = jnp.arange(t)[None, :]
    valid = steps < lens[:, None]

    prev = jnp.concatenate(
        [jnp.full((n, 1), -1, x.dtype), x[:, :-1]], axis=1)
    keep = (x != blank) & (x != prev) & valid
    new_lens = keep.sum(axis=1).astype(jnp.int32)
    # stable left-compaction: argsort on (drop, position)
    order = jnp.argsort(jnp.where(keep, steps, t + steps), axis=1,
                        stable=True)
    gathered = jnp.take_along_axis(x, order, axis=1)
    out_pos = jnp.arange(t)[None, :] < new_lens[:, None]
    out = jnp.where(out_pos, gathered, pad_val)
    if op is not None:
        for nm in (op.outputs.get("Output") or []):
            if nm:
                ctx.set_seq_len(nm, new_lens)
    if squeeze:
        out = out[..., None]
    return {"Output": out}


# ---------------------------------------------------------------------------
# chunk_eval (host op: scheme-aware chunk extraction, a metric)
# ---------------------------------------------------------------------------

_SCHEME_KINDS = {"IOB": "BI", "IOE": "IE", "IOBES": "BIES"}


def _extract_chunks(tags, scheme, num_types, excluded):
    """-> set of (begin, end_exclusive, type); conlleval-style begin/end
    predicates (reference chunk_eval_op.h ChunkBegin/ChunkEnd for
    plain/IOB/IOE/IOBES; tag encoding = type * n_kinds + kind)."""
    if scheme == "plain":
        parsed = [(int(t), "S") for t in tags]

        def begins(prev, cur):
            return prev is None or prev[0] != cur[0]

        def ends(cur, nxt):
            return nxt is None or nxt[0] != cur[0]
    else:
        kinds = _SCHEME_KINDS[scheme]
        nk = len(kinds)
        o_tag = num_types * nk

        def parse(t):
            t = int(t)
            if t < 0 or t >= o_tag:
                return None  # O / out of range
            return (t // nk, kinds[t % nk])

        parsed = [parse(t) for t in tags]

        def begins(prev, cur):
            if prev is None or prev[0] != cur[0]:
                return True
            if scheme == "IOB":
                return cur[1] == "B"
            if scheme == "IOE":
                return prev[1] == "E"
            return cur[1] in "BS" or prev[1] in "ES"

        def ends(cur, nxt):
            if nxt is None or nxt[0] != cur[0]:
                return True
            if scheme == "IOB":
                return nxt[1] == "B"
            if scheme == "IOE":
                return cur[1] == "E"
            return cur[1] in "ES" or nxt[1] in "BS"

    chunks = set()
    start = None
    for i, cur in enumerate(parsed):
        if cur is None:
            start = None
            continue
        prev = parsed[i - 1] if i > 0 else None
        nxt = parsed[i + 1] if i + 1 < len(parsed) else None
        if start is None or begins(prev, cur):
            start = i
        if ends(cur, nxt):
            if cur[0] not in excluded:
                chunks.add((start, i + 1, cur[0]))
            start = None
    return chunks


from paddle_tpu.ops.io_ops import _host  # noqa: E402  (shared helper)


@_host("chunk_eval")
def _chunk_eval(executor, op, scope, feed, env=None):
    """Precision/recall/F1 over extracted chunks (reference
    chunk_eval_op.cc; schemes plain/IOB/IOE/IOBES)."""
    def read(name, default=None):
        for src in (env, feed):
            if src is not None and name in src:
                return np.asarray(src[name])
        try:
            return np.asarray(scope.find_var(name))
        except KeyError:
            if default is not None:
                return default
            raise

    inf_name = op.input("Inference")[0]
    lab_name = op.input("Label")[0]
    inference = read(inf_name)
    label = read(lab_name)
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    lens = read(inf_name + "@LEN",
                default=np.full((inference.shape[0],),
                                inference.shape[1], np.int64))

    scheme = op.attr("chunk_scheme", "IOB")
    num_types = int(op.attr("num_chunk_types"))
    excluded = set(op.attr("excluded_chunk_types", []) or [])

    n_inf = n_lab = n_correct = 0
    for row in range(inference.shape[0]):
        ln = int(lens[row])
        ic = _extract_chunks(inference[row, :ln].tolist(), scheme,
                             num_types, excluded)
        lc = _extract_chunks(label[row, :ln].tolist(), scheme,
                             num_types, excluded)
        n_inf += len(ic)
        n_lab += len(lc)
        n_correct += len(ic & lc)

    precision = n_correct / n_inf if n_inf else 0.0
    recall = n_correct / n_lab if n_lab else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)

    outs = {"Precision": np.asarray([precision], np.float32),
            "Recall": np.asarray([recall], np.float32),
            "F1-Score": np.asarray([f1], np.float32),
            "NumInferChunks": np.asarray([n_inf], np.int64),
            "NumLabelChunks": np.asarray([n_lab], np.int64),
            "NumCorrectChunks": np.asarray([n_correct], np.int64)}
    for slot, val in outs.items():
        names = op.outputs.get(slot) or []
        if names and names[0]:
            if env is not None:
                env[names[0]] = val
            s = scope.find_scope_of(names[0]) or scope
            s.set(names[0], val)
