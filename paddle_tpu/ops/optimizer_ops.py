"""Optimizer ops — each updates parameters "in place" (functionally: the op
writes the same var name, and the executor donates/wires the buffer back).

Parity: reference operators/{sgd,momentum,adam,adamax,adagrad,adadelta,
decayed_adagrad,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.cc.  All are
pure elementwise updates that XLA fuses into the step program — the
reference's separate optimizer kernel launches disappear entirely.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.selected_rows import SelectedRows, merge_rows


def _lr(ins):
    return ins["LearningRate"].reshape(())


def _dense_grad(g):
    """Optimizers without a row-subset kernel densify SelectedRows grads
    (the reference errors out for ops lacking a SelectedRows kernel; we
    fall back to the mathematically-identical dense update instead)."""
    return g.to_dense() if isinstance(g, SelectedRows) else g


@register_op("sgd", grad_maker=None)
def _sgd(ctx, ins, attrs, op):
    g = ins["Grad"]
    if isinstance(g, SelectedRows):
        # sparse path (reference sgd_op.h SelectedRows kernel): scatter-add
        # touches only the looked-up rows; duplicates accumulate
        return {"ParamOut":
                ins["Param"].at[g.rows].add(-_lr(ins) * g.values)}
    return {"ParamOut": ins["Param"] - _lr(ins) * ins["Grad"]}


@register_op("momentum", grad_maker=None)
def _momentum(ctx, ins, attrs, op):
    p, g, v = ins["Param"], _dense_grad(ins["Grad"]), ins["Velocity"]
    mu = attrs.get("mu")
    lr = _lr(ins)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("adam", grad_maker=None)
def _adam(ctx, ins, attrs, op):
    p, g = ins["Param"], ins["Grad"]
    m1, m2 = ins["Moment1"], ins["Moment2"]
    b1p, b2p = ins["Beta1Pow"].reshape(()), ins["Beta2Pow"].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SelectedRows):
        # sparse (lazy) path, reference adam_op.h SelectedRows kernel:
        # duplicates merged first, then moments/param updated only at the
        # touched rows (out-of-bounds rows of the merge are dropped)
        sr = merge_rows(g)
        rows = jnp.clip(sr.rows, 0, sr.height - 1)  # safe gather indices
        m1_r, m2_r, p_r = m1[rows], m2[rows], p[rows]
        m1_n = b1 * m1_r + (1 - b1) * sr.values
        m2_n = b2 * m2_r + (1 - b2) * jnp.square(sr.values)
        p_n = p_r - lr * m1_n / (jnp.sqrt(m2_n) + eps)
        return {"ParamOut": p.at[sr.rows].set(p_n),
                "Moment1Out": m1.at[sr.rows].set(m1_n),
                "Moment2Out": m2.at[sr.rows].set(m2_n),
                "Beta1PowOut": ins["Beta1Pow"] * b1,
                "Beta2PowOut": ins["Beta2Pow"] * b2}
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    p_out = p - lr * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out,
            "Beta1PowOut": ins["Beta1Pow"] * b1,
            "Beta2PowOut": ins["Beta2Pow"] * b2}


@register_op("adamax", grad_maker=None)
def _adamax(ctx, ins, attrs, op):
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    m, inf = ins["Moment"], ins["InfNorm"]
    b1p = ins["Beta1Pow"].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g))
    lr = _lr(ins) / (1 - b1p)
    p_out = p - lr * m_out / (inf_out + eps)
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out,
            "Beta1PowOut": ins["Beta1Pow"] * b1}


@register_op("adagrad", grad_maker=None)
def _adagrad(ctx, ins, attrs, op):
    p, g, m = ins["Param"], ins["Grad"], ins["Moment"]
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        sr = merge_rows(g)
        rows = jnp.clip(sr.rows, 0, sr.height - 1)
        m_n = m[rows] + jnp.square(sr.values)
        p_n = p[rows] - _lr(ins) * sr.values / (jnp.sqrt(m_n) + eps)
        return {"ParamOut": p.at[sr.rows].set(p_n),
                "MomentOut": m.at[sr.rows].set(m_n)}
    m_out = m + jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("decayed_adagrad", grad_maker=None)
def _decayed_adagrad(ctx, ins, attrs, op):
    p, g, m = ins["Param"], _dense_grad(ins["Grad"]), ins["Moment"]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta", grad_maker=None)
def _adadelta(ctx, ins, attrs, op):
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"], ins["AvgSquaredUpdate"]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    return {"ParamOut": p + upd, "AvgSquaredGradOut": g2,
            "AvgSquaredUpdateOut": u2}


@register_op("rmsprop", grad_maker=None)
def _rmsprop(ctx, ins, attrs, op):
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    ms, mom = ins["MeanSquare"], ins["Moment"]
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum = attrs.get("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = momentum * mom + _lr(ins) * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
            "MomentOut": mom_out}


@register_op("ftrl", grad_maker=None)
def _ftrl(ctx, ins, attrs, op):
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    sq, lin = ins["SquaredAccumulator"], ins["LinearAccumulator"]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) -
                 jnp.power(sq, -lr_power)) / lr
    lin_out = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / denom
    return {"ParamOut": p_out, "SquaredAccumOut": new_sq,
            "LinearAccumOut": lin_out}


@register_op("proximal_gd", grad_maker=None)
def _proximal_gd(ctx, ins, attrs, op):
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return {"ParamOut": prox / (1.0 + lr * l2)}


@register_op("proximal_adagrad", grad_maker=None)
def _proximal_adagrad(ctx, ins, attrs, op):
    p, g, m = ins["Param"], _dense_grad(ins["Grad"]), ins["Moment"]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    m_out = m + jnp.square(g)
    lr = _lr(ins) / jnp.sqrt(m_out)
    prox = p - lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return {"ParamOut": prox / (1.0 + lr * l2), "MomentOut": m_out}


@register_op("average_accumulates", grad_maker=None)
def _average_accumulates(ctx, ins, attrs, op):
    """Accumulators for ModelAverage (reference average_accumulates_op.cc)."""
    param = ins["Param"]
    sum1, sum2, sum3 = ins["in_sum_1"], ins["in_sum_2"], ins["in_sum_3"]
    num_acc = ins["in_num_accumulates"].reshape(())
    old_num = ins["in_old_num_accumulates"].reshape(())
    num_upd = ins["in_num_updates"].reshape(())
    avg_window = attrs.get("average_window", 0.0)
    max_avg = attrs.get("max_average_window", 10000)
    min_avg = attrs.get("min_average_window", 10000)
    num_acc = num_acc + 1
    num_upd = num_upd + 1
    sum1 = sum1 + param
    window = jnp.maximum(jnp.minimum(num_upd.astype(jnp.float32) * avg_window,
                                     float(max_avg)), float(min_avg))
    roll = num_acc.astype(jnp.float32) >= window
    sum2 = jnp.where(roll, sum2 + sum1, sum2)
    sum1 = jnp.where(roll, jnp.zeros_like(sum1), sum1)
    old_num = jnp.where(roll, old_num + num_acc, old_num)
    num_acc = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    big = old_num.astype(jnp.float32) >= 2.0 * window
    sum3 = jnp.where(big, sum2, sum3)
    sum2 = jnp.where(big, jnp.zeros_like(sum2), sum2)
    old_num = jnp.where(big, num_acc, old_num)
    return {"out_sum_1": sum1, "out_sum_2": sum2, "out_sum_3": sum3,
            "out_num_accumulates": num_acc.reshape((1,)),
            "out_old_num_accumulates": old_num.reshape((1,)),
            "out_num_updates": num_upd.reshape((1,))}
