"""Operator library: importing this package registers every op.

Parity: reference paddle/fluid/operators/ (~160 op types, 228 .cc / 129 .cu
files).  Here each op is a JAX lowering registered into core.registry; grad
ops default to the vjp of the forward lowering (core/lowering.py).
"""
from paddle_tpu.ops import (  # noqa: F401
    math,
    nn,
    fused_ops,
    loss,
    tensor,
    random,
    optimizer_ops,
    io_ops,
    reader_ops,
    metric,
    parallel_ops,
    sequence,
    control_flow,
    distributed_ops,
    beam_search,
    crf_ctc,
    detection,
    misc,
    concurrency_ops,
)
