"""Control-flow operators.

Parity: reference operators/{conditional_block_op,while_op,recurrent_op,
is_empty_op}.cc plus the array-op family (tensor_array_read_write.cc,
lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc, lod_rank_table_op.cc,
max_sequence_len_op.cc, lod_array_length_op.cc, shrink_rnn_memory_op.cc,
split_lod_tensor_op.cc, merge_lod_tensor_op.cc).

The reference runs sub-blocks imperatively against step scopes (STEP_SCOPES
vars); here a sub-block is traced functionally and handed to the XLA
structured control-flow primitive (lax.cond / lax.while_loop / lax.scan), so
gradients fall out of jax.vjp instead of hand-built *_grad blocks —
while_grad's stacked-memory machinery (SURVEY hard part #4) is subsumed by
scan's native differentiability.

TPU-first translations of the LoD machinery:

- ``LoDTensorArray`` -> :class:`TensorArray`, a fixed-capacity device buffer
  registered as a JAX pytree so it can ride a ``lax.while_loop`` carry;
  reads/writes are dynamic slices (the reference grows a vector of tensors).
- ``split_lod_tensor``/``merge_lod_tensor`` (the IfElse engine) -> batched
  select: both branches compute on the full batch and the merge is a
  row-wise ``where``.  Identical results for per-row branch computations,
  and the idiomatic XLA shape (lax.select computes both sides anyway).
- ``lod_rank_table``/``shrink_rnn_memory`` -> in the padded [N, T, ...]
  world sequences need no length-descending reorder and the active batch
  never shrinks: masking inside the scan (the ``recurrent`` op) plays that
  role, so these lower to length bookkeeping / identity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


@register_op("is_empty", grad_maker=None)
def _is_empty(ctx, ins, attrs, op=None):
    x = ins["X"]
    return {"Out": jnp.asarray([int(np.prod(x.shape)) == 0])}


# ---------------------------------------------------------------------------
# TensorArray (reference LoDTensorArray, framework.proto LOD_TENSOR_ARRAY)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class TensorArray:
    """Fixed-capacity stack of same-shape tensors on device.

    ``buffer`` is ``[capacity, ...]``; ``size`` is the number of live
    entries (traced int32 scalar).  Registered as a pytree so arrays can be
    loop-carried through ``lax.while_loop`` / appear in jit results.
    """

    __slots__ = ("buffer", "size")

    def __init__(self, buffer, size):
        self.buffer = buffer
        self.size = size

    def tree_flatten(self):
        return (self.buffer, self.size), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def empty(element_shape, dtype, capacity):
        return TensorArray(
            jnp.zeros((int(capacity),) + tuple(int(d) for d in element_shape),
                      dtype), jnp.asarray(0, jnp.int32))


def _as_index(i):
    i = jnp.asarray(i)
    return jnp.reshape(i, ()).astype(jnp.int32)


@register_op("create_array", grad_maker=None)
def _create_array(ctx, ins, attrs, op=None):
    """Preallocated empty TensorArray.  ``element_shape``+``capacity`` attrs
    size the buffer (XLA needs static shapes; the reference grows a
    std::vector instead)."""
    from paddle_tpu.core.types import proto_to_np_dtype

    if "element_shape" not in attrs:
        # defer sizing to the first (out-of-loop) write_to_array
        return {"Out": TensorArray(None, jnp.asarray(0, jnp.int32))}
    shape = tuple(attrs["element_shape"])
    dtype = proto_to_np_dtype(attrs["dtype"]) if "dtype" in attrs \
        else np.float32
    cap = int(attrs.get("capacity", 64))
    return {"Out": TensorArray.empty(shape, dtype, cap)}


@register_op("write_to_array", seq_aware=True)
def _write_to_array(ctx, ins, attrs, op=None):
    """array[i] = x (reference tensor_array_read_write.cc WriteToArray).
    A missing/empty input array is allocated from x's shape."""
    x = ins["X"]
    i = _as_index(ins["I"])
    arr = ins.get("Array")
    if arr is None or arr.buffer is None:
        cap = int(attrs.get("capacity", 64))
        arr = TensorArray.empty(x.shape, jnp.result_type(x), cap)
    buf = jax.lax.dynamic_update_index_in_dim(
        arr.buffer, x.astype(arr.buffer.dtype), i, 0)
    size = jnp.maximum(arr.size, i + 1)
    return {"Out": TensorArray(buf, size)}


@register_op("read_from_array", seq_aware=True)
def _read_from_array(ctx, ins, attrs, op=None):
    arr = ins["X"]
    i = _as_index(ins["I"])
    return {"Out": jax.lax.dynamic_index_in_dim(arr.buffer, i, 0,
                                                keepdims=False)}


def _wide_int():
    """Widest int the active JAX mode keeps (int64 silently truncates to
    int32 under the default x32 mode)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


@register_op("lod_array_length", grad_maker=None)
def _lod_array_length(ctx, ins, attrs, op=None):
    return {"Out": jnp.reshape(ins["X"].size, (1,)).astype(_wide_int())}


@register_op("lod_rank_table", grad_maker=None, seq_aware=True)
def _lod_rank_table(ctx, ins, attrs, op=None):
    """Reference lod_rank_table_op.cc sorts sequences by descending length
    so the while-RNN can shrink its active batch.  Padded batches stay in
    order; the 'table' is just the [N] length vector (all-T when dense)."""
    x = ins["X"]
    name = (op.inputs.get("X") or [None])[0] if op is not None else None
    lens = ctx.seq_len_of(name) if name else None
    if lens is None:
        n, t = x.shape[0], (x.shape[1] if x.ndim > 1 else 1)
        lens = jnp.full((n,), t, jnp.int32)
    return {"Out": lens.astype(jnp.int32)}


@register_op("max_sequence_len", grad_maker=None)
def _max_sequence_len(ctx, ins, attrs, op=None):
    return {"Out": jnp.reshape(jnp.max(ins["RankTable"]), (1,)).astype(
        _wide_int())}


@register_op("lod_tensor_to_array", seq_aware=True)
def _lod_tensor_to_array(ctx, ins, attrs, op=None):
    """Padded [N, T, ...] -> TensorArray of T time slices [N, ...]
    (reference packs ragged rows per timestep; masking replaces that)."""
    x = ins["X"]
    t = x.shape[1]
    return {"Out": TensorArray(jnp.moveaxis(x, 1, 0),
                               jnp.asarray(t, jnp.int32))}


@register_op("array_to_lod_tensor", seq_aware=True)
def _array_to_lod_tensor(ctx, ins, attrs, op=None):
    arr = ins["X"]
    out = jnp.moveaxis(arr.buffer, 0, 1)  # [N, T, ...]
    if op is not None:
        table_names = op.inputs.get("RankTable") or []
        if table_names and table_names[0] and table_names[0] in ctx.env:
            lens = ctx.env[table_names[0]]
            out_names = op.outputs.get("Out") or []
            for nm in out_names:
                if nm:
                    ctx.set_seq_len(nm, lens)
    return {"Out": out}


@register_op("shrink_rnn_memory", seq_aware=True)
def _shrink_rnn_memory(ctx, ins, attrs, op=None):
    """Identity: the padded scan keeps the full batch and freezes finished
    rows by mask (ops/control_flow.py recurrent), so there is no shrinking
    to do (reference shrink_rnn_memory_op.cc)."""
    return {"Out": ins["X"]}


@register_op("reorder_lod_tensor_by_rank", seq_aware=True)
def _reorder_lod_tensor_by_rank(ctx, ins, attrs, op=None):
    """Identity: padded batches are never length-sorted."""
    return {"Out": ins["X"]}


# ---------------------------------------------------------------------------
# IfElse engine: batched row select (reference split/merge_lod_tensor_op.cc)
# ---------------------------------------------------------------------------

@register_op("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs, op=None):
    """Both 'halves' alias the full batch; the branch-select happens in
    merge_lod_tensor.  Row-wise branch computations produce identical
    results to the reference's physical row split."""
    x = ins["X"]
    return {"OutTrue": x, "OutFalse": x}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs, op=None):
    mask = ins["Mask"]
    in_true, in_false = ins["InTrue"], ins["InFalse"]
    m = jnp.reshape(mask, (-1,)).astype(bool)
    m = m.reshape((m.shape[0],) + (1,) * (in_true.ndim - 1))
    return {"Out": jnp.where(m, in_true, in_false)}


# ---------------------------------------------------------------------------
# conditional_block / while / recurrent
# ---------------------------------------------------------------------------

def _trace_block(ctx, block_idx, env):
    from paddle_tpu.core.lowering import run_ops
    sub = ctx.sub_context(block_idx, env)
    run_ops(sub)
    return env


def _match_dtype(val, ref, amp):
    """Pin a loop-carried / branch-merged value to its reference dtype:
    under AMP the body may compute in bf16 while the init is fp32, and
    lax.scan/while/cond require carry dtypes to be invariant.  Outside
    AMP a mismatch is a real bug — let lax raise its invariance error."""
    if (amp and ref is not None and hasattr(val, "dtype")
            and hasattr(ref, "dtype") and val.dtype != ref.dtype):
        return val.astype(ref.dtype)
    return val


@register_op("conditional_block")
def _conditional_block(ctx, ins, attrs, op=None):
    """Scalar-condition sub-block -> lax.cond (reference
    conditional_block_op.cc).  Inputs: Cond [1] bool, Input = every
    outer var the block reads (so grads flow); Out = outer vars the
    block writes.  When an Out var has no prior value, the false branch
    yields zeros of the block-computed shape."""
    cond = ins.list("Cond")[0]
    sub_idx = int(attrs["sub_block"])
    in_names = [n for n in (op.inputs.get("Input") or []) if n]
    in_vals = [v for v in ins.list("Input")]
    out_names = [n for n in (op.outputs.get("Out") or []) if n]
    prior = [ctx.env.get(n) for n in out_names]

    def true_fn(operands):
        in_vals, prior = operands
        env = dict(zip(in_names, in_vals))
        _trace_block(ctx, sub_idx, env)
        return tuple(
            _match_dtype(env[n], p, ctx.amp) if n in env else
            (p if p is not None else jnp.zeros(()))
            for n, p in zip(out_names, prior))

    def false_fn(operands):
        in_vals, prior = operands
        if any(p is None for p in prior):
            shapes = jax.eval_shape(true_fn, operands)
            return tuple(p if p is not None else jnp.zeros(s.shape, s.dtype)
                         for p, s in zip(prior, shapes))
        return tuple(prior)

    cond_scalar = jnp.reshape(cond, ()).astype(bool)
    outs = jax.lax.cond(cond_scalar, true_fn, false_fn,
                        (tuple(in_vals), tuple(prior)))
    return {"Out": list(outs)}


@register_op("while", grad_maker=None, seq_aware=True)
def _while(ctx, ins, attrs, op=None):
    """while-loop (reference while_op.cc): Condition [1] bool; X = loop
    vars (read AND written by the block, carried through the loop);
    Params = outer vars the block only reads (closed over, not carried);
    the sub-block recomputes Condition.  Lowered to lax.while_loop — NOT
    differentiable (XLA While has no vjp); use StaticRNN/DynamicRNN (the
    scan-lowered ``recurrent`` op) for trainable recurrence, as the
    reference's own RNN layers do."""
    sub_idx = int(attrs["sub_block"])
    cond_name = (op.inputs.get("Condition") or [None])[0]
    x_names = [n for n in (op.inputs.get("X") or []) if n]
    x_vals = list(ins.list("X"))
    p_names = [n for n in (op.inputs.get("Params") or []) if n]
    p_vals = list(ins.list("Params"))
    cond0 = ins.list("Condition")[0]

    def cond_fn(carry):
        c, _ = carry
        return jnp.reshape(c, ()).astype(bool)

    def body_fn(carry):
        c, xs = carry
        env = dict(zip(p_names, p_vals))
        env.update(zip(x_names, xs))
        env[cond_name] = c
        _trace_block(ctx, sub_idx, env)
        return (env[cond_name],
                tuple(_match_dtype(env[n], x, ctx.amp)
                      for n, x in zip(x_names, xs)))

    final_c, outs = jax.lax.while_loop(cond_fn, body_fn,
                                       (cond0, tuple(x_vals)))
    return {"Out": list(outs), "CondOut": final_c}


@register_op("recurrent", seq_aware=True)
def _recurrent(ctx, ins, attrs, op=None):
    """Step a sub-block over the time axis with lax.scan — the TPU-native
    backend of StaticRNN/DynamicRNN (reference recurrent_op.cc:636 /
    while-op DynamicRNN, layers/control_flow.py:383,1313).

    Inputs
      Inputs      sequence tensors [N, T, ...] (sliced to [N, ...]/step)
      InitStates  initial memory values, one per state
      Parameters  every outer var the block reads (weights) — declared
                  explicitly so jax.vjp reaches them
    Attrs
      sub_block, step_input_names, state_in_names, state_out_names,
      step_output_names, masked (freeze states & zero outputs past each
      sequence's length, from the first input's @LEN vector), reverse
      (iterate time back-to-front, for bidirectional RNNs)
    Outputs
      Outputs     stacked step outputs [N, T, ...]
      FinalStates last state values [N, ...]
    """
    sub_idx = int(attrs["sub_block"])
    step_in_names = list(attrs.get("step_input_names", []))
    st_in_names = list(attrs.get("state_in_names", []))
    st_out_names = list(attrs.get("state_out_names", []))
    out_names = list(attrs.get("step_output_names", []))
    masked = bool(attrs.get("masked", False))
    reverse = bool(attrs.get("reverse", False))
    param_names = [n for n in (op.inputs.get("Parameters") or []) if n]

    xs = [v for v in ins.list("Inputs")]
    inits = [v for v in ins.list("InitStates")]
    params = [v for v in ins.list("Parameters")]

    lens = None
    if masked and op is not None:
        src_names = op.inputs.get("Inputs") or []
        if src_names and src_names[0]:
            lens = ctx.seq_len_of(src_names[0])
    n, t = xs[0].shape[0], xs[0].shape[1]
    if lens is None:
        mask_t = jnp.ones((t, n), xs[0].dtype if jnp.issubdtype(
            jnp.result_type(xs[0]), jnp.floating) else jnp.float32)
    else:
        mask_t = (jnp.arange(t)[:, None] < lens[None, :]).astype(
            jnp.float32)

    xs_t = [jnp.moveaxis(x, 1, 0) for x in xs]       # time-major

    def step(states, xm):
        xts, mt = xm
        env = dict(zip(param_names, params))
        env.update(zip(step_in_names, xts))
        env.update(zip(st_in_names, states))
        _trace_block(ctx, sub_idx, env)
        new_states = tuple(
            _match_dtype(env[nm], s, ctx.amp)
            for nm, s in zip(st_out_names, states))
        if masked:
            kept = []
            for s_new, s_old in zip(new_states, states):
                m = mt.reshape((n,) + (1,) * (s_new.ndim - 1))
                kept.append(_match_dtype(m * s_new + (1 - m) * s_old,
                                         s_old, ctx.amp))
            new_states = tuple(kept)
        outs = []
        for nm in out_names:
            o = env[nm]
            if masked:
                o = o * mt.reshape((n,) + (1,) * (o.ndim - 1))
            outs.append(o)
        return new_states, tuple(outs)

    final_states, stacked = jax.lax.scan(step, tuple(inits),
                                         (tuple(xs_t), mask_t),
                                         reverse=reverse)
    outputs = [jnp.moveaxis(o, 0, 1) for o in stacked]
    result = {"Outputs": outputs, "FinalStates": list(final_states)}
    if lens is not None and op is not None:
        for nm in (op.outputs.get("Outputs") or []):
            if nm:
                ctx.set_seq_len(nm, lens)
    return result
