"""Control-flow operators.

Parity: reference operators/{compare_op,logical_op,conditional_block_op,
while_op,recurrent_op,is_empty_op,increment_op}.cc.  The reference runs
sub-blocks imperatively against step scopes (STEP_SCOPES vars); here a
sub-block is traced functionally and handed to the XLA structured
control-flow primitive (lax.cond / lax.while_loop / lax.scan), so
gradients fall out of jax.vjp instead of hand-built *_grad blocks —
while_grad's stacked-memory machinery (SURVEY hard part #4) is subsumed
by scan's native differentiability.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


def _cmp(name, fn):
    def lower(ctx, ins, attrs, op=None):
        return {"Out": fn(ins["X"], ins["Y"])}
    lower.__name__ = "_" + name
    register_op(name, lower=lower, grad_maker=None)


_cmp("less_than", lambda x, y: x < y)
_cmp("less_equal", lambda x, y: x <= y)
_cmp("greater_than", lambda x, y: x > y)
_cmp("greater_equal", lambda x, y: x >= y)
_cmp("equal", lambda x, y: x == y)
_cmp("not_equal", lambda x, y: x != y)


def _logical(name, fn, binary=True):
    def lower(ctx, ins, attrs, op=None):
        if binary:
            return {"Out": fn(ins["X"], ins["Y"])}
        return {"Out": fn(ins["X"])}
    lower.__name__ = "_" + name
    register_op(name, lower=lower, grad_maker=None)


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, binary=False)


@register_op("increment", grad_maker=None)
def _increment(ctx, ins, attrs, op=None):
    return {"Out": ins["X"] + attrs.get("step", 1.0)}


@register_op("is_empty", grad_maker=None)
def _is_empty(ctx, ins, attrs, op=None):
    x = ins["X"]
    return {"Out": jnp.asarray([int(np.prod(x.shape)) == 0])}


def _trace_block(ctx, block_idx, env):
    from paddle_tpu.core.lowering import run_ops
    sub = ctx.sub_context(block_idx, env)
    run_ops(sub)
    return env


@register_op("conditional_block")
def _conditional_block(ctx, ins, attrs, op=None):
    """Scalar-condition sub-block -> lax.cond (reference
    conditional_block_op.cc).  Inputs: Cond [1] bool, Input = every
    outer var the block reads (so grads flow); Out = outer vars the
    block writes.  When an Out var has no prior value, the false branch
    yields zeros of the block-computed shape."""
    cond = ins.list("Cond")[0]
    sub_idx = int(attrs["sub_block"])
    in_names = [n for n in (op.inputs.get("Input") or []) if n]
    in_vals = [v for v in ins.list("Input")]
    out_names = [n for n in (op.outputs.get("Out") or []) if n]
    prior = [ctx.env.get(n) for n in out_names]

    def true_fn(operands):
        in_vals, prior = operands
        env = dict(zip(in_names, in_vals))
        _trace_block(ctx, sub_idx, env)
        return tuple(
            env[n] if n in env else
            (p if p is not None else jnp.zeros(()))
            for n, p in zip(out_names, prior))

    def false_fn(operands):
        in_vals, prior = operands
        if any(p is None for p in prior):
            shapes = jax.eval_shape(true_fn, operands)
            return tuple(p if p is not None else jnp.zeros(s.shape, s.dtype)
                         for p, s in zip(prior, shapes))
        return tuple(prior)

    cond_scalar = jnp.reshape(cond, ()).astype(bool)
    outs = jax.lax.cond(cond_scalar, true_fn, false_fn,
                        (tuple(in_vals), tuple(prior)))
    return {"Out": list(outs)}


@register_op("while")
def _while(ctx, ins, attrs, op=None):
    """while-loop (reference while_op.cc): Condition [1] bool; X = loop
    vars (read+written by the block); sub-block recomputes Condition.
    Lowered to lax.while_loop — NOT differentiable (XLA While has no
    vjp); use StaticRNN/DynamicRNN (the scan-lowered ``recurrent`` op)
    for trainable recurrence, as the reference's own RNN layers do."""
    sub_idx = int(attrs["sub_block"])
    cond_name = (op.inputs.get("Condition") or [None])[0]
    x_names = [n for n in (op.inputs.get("X") or []) if n]
    x_vals = list(ins.list("X"))
    cond0 = ins.list("Condition")[0]

    def cond_fn(carry):
        c, _ = carry
        return jnp.reshape(c, ()).astype(bool)

    def body_fn(carry):
        c, xs = carry
        env = dict(zip(x_names, xs))
        env[cond_name] = c
        _trace_block(ctx, sub_idx, env)
        return (env[cond_name], tuple(env[n] for n in x_names))

    _, outs = jax.lax.while_loop(cond_fn, body_fn,
                                 (cond0, tuple(x_vals)))
    return {"Out": list(outs)}


@register_op("recurrent", seq_aware=True)
def _recurrent(ctx, ins, attrs, op=None):
    """Step a sub-block over the time axis with lax.scan — the TPU-native
    backend of StaticRNN/DynamicRNN (reference recurrent_op.cc:636 /
    while-op DynamicRNN, layers/control_flow.py:383,1313).

    Inputs
      Inputs      sequence tensors [N, T, ...] (sliced to [N, ...]/step)
      InitStates  initial memory values, one per state
      Parameters  every outer var the block reads (weights) — declared
                  explicitly so jax.vjp reaches them
    Attrs
      sub_block, step_input_names, state_in_names, state_out_names,
      step_output_names, masked (freeze states & zero outputs past each
      sequence's length, from the first input's @LEN vector)
    Outputs
      Outputs     stacked step outputs [N, T, ...]
      FinalStates last state values [N, ...]
    """
    sub_idx = int(attrs["sub_block"])
    step_in_names = list(attrs.get("step_input_names", []))
    st_in_names = list(attrs.get("state_in_names", []))
    st_out_names = list(attrs.get("state_out_names", []))
    out_names = list(attrs.get("step_output_names", []))
    masked = bool(attrs.get("masked", False))
    param_names = [n for n in (op.inputs.get("Parameters") or []) if n]

    xs = [v for v in ins.list("Inputs")]
    inits = [v for v in ins.list("InitStates")]
    params = [v for v in ins.list("Parameters")]

    lens = None
    if masked and op is not None:
        src_names = op.inputs.get("Inputs") or []
        if src_names and src_names[0]:
            lens = ctx.seq_len_of(src_names[0])
    n, t = xs[0].shape[0], xs[0].shape[1]
    if lens is None:
        mask_t = jnp.ones((t, n), xs[0].dtype if jnp.issubdtype(
            jnp.result_type(xs[0]), jnp.floating) else jnp.float32)
    else:
        mask_t = (jnp.arange(t)[:, None] < lens[None, :]).astype(
            jnp.float32)

    xs_t = [jnp.moveaxis(x, 1, 0) for x in xs]       # time-major

    def step(states, xm):
        xts, mt = xm
        env = dict(zip(param_names, params))
        env.update(zip(step_in_names, xts))
        env.update(zip(st_in_names, states))
        _trace_block(ctx, sub_idx, env)
        new_states = tuple(env[n] for n in st_out_names)
        if masked:
            kept = []
            for s_new, s_old in zip(new_states, states):
                m = mt.reshape((n,) + (1,) * (s_new.ndim - 1))
                kept.append(m * s_new + (1 - m) * s_old)
            new_states = tuple(kept)
        outs = []
        for nm in out_names:
            o = env[nm]
            if masked:
                o = o * mt.reshape((n,) + (1,) * (o.ndim - 1))
            outs.append(o)
        return new_states, tuple(outs)

    final_states, stacked = jax.lax.scan(step, tuple(inits),
                                         (tuple(xs_t), mask_t))
    outputs = [jnp.moveaxis(o, 0, 1) for o in stacked]
    result = {"Outputs": outputs, "FinalStates": list(final_states)}
    if lens is not None and op is not None:
        for nm in (op.outputs.get("Outputs") or []):
            if nm:
                ctx.set_seq_len(nm, lens)
    return result
