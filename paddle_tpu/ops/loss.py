"""Loss ops.

Parity: reference operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
hinge_loss_op.cc, huber_loss_op.cc, log_loss_op.cc, rank_loss_op.cc,
margin_rank_loss_op.cc, smooth_l1_loss_op.cc, modified_huber_loss_op.cc,
bilinear_tensor_product_op.cc, nce_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

_TOL = 1e-20  # reference math/cross_entropy.h TolerableValue


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs, op):
    x = ins["X"]          # [N, D] probabilities
    label = ins["Label"]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, _TOL)), axis=-1,
                        keepdims=True)
    else:
        idx = _hard_label_idx(label, x.ndim)
        picked = jnp.take_along_axis(x, idx, axis=-1)
        loss = -jnp.log(jnp.maximum(picked, _TOL))
    return {"Y": _mask_padded(ctx, op, "X", loss)}


@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(ctx, ins, attrs, op):
    logits = ins["Logits"]
    label = ins["Label"]
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    log_softmax = logits - lse
    softmax = jnp.exp(log_softmax)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_softmax, axis=-1, keepdims=True)
    else:
        idx = _hard_label_idx(label, logits.ndim)
        picked = jnp.take_along_axis(log_softmax, idx, axis=-1)
        loss = -picked
    return {"Softmax": softmax,
            "Loss": _mask_padded(ctx, op, "Logits", loss)}


def _mask_padded(ctx, op, slot, loss):
    """Zero the per-token loss at padded positions of a ragged input (the
    packed reference never sees padding, cross_entropy_op.cc)."""
    if op is None:
        return loss
    names = op.inputs.get(slot) or []
    lens = ctx.seq_len_of(names[0]) if names and names[0] else None
    if lens is None or loss.ndim < 2:
        return loss
    mask = (jnp.arange(loss.shape[1])[None, :] <
            lens[:, None]).astype(loss.dtype)
    return loss * mask.reshape(mask.shape + (1,) * (loss.ndim - 2))


def _hard_label_idx(label, logits_ndim):
    """Label [..., 1] (or [...]) -> int index tensor with logits' rank,
    so N-d logits (e.g. [B, S, V] LM heads) work."""
    idx = label.astype(jnp.int32)
    if idx.ndim < logits_ndim:
        idx = idx[..., None]
    return idx


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs, op):
    x, label = ins["X"], ins["Label"]
    # log(1+exp(x)) - x*label, numerically stable
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": loss}


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs, op):
    logits, labels = ins["Logits"], ins["Labels"]
    signs = 2.0 * labels - 1.0
    return {"Loss": jnp.maximum(0.0, 1.0 - signs * logits)}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs, op):
    p, label = ins["Predicted"], ins["Labels"]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": loss}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs, op):
    label, left, right = ins["Label"], ins["Left"], ins["Right"]
    d = left - right
    return {"Out": jnp.maximum(d, 0) - d * label + jnp.log1p(
        jnp.exp(-jnp.abs(d)))}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs, op):
    label, x1, x2 = ins["Label"], ins["X1"], ins["X2"]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.has("InsideWeight"):
        diff = diff * ins["InsideWeight"]
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff,
                     ad - 0.5 / s2)
    if ins.has("OutsideWeight"):
        elem = elem * ins["OutsideWeight"]
    return {"Diff": diff, "Out": jnp.sum(
        elem.reshape(elem.shape[0], -1), axis=1, keepdims=True)}


@register_op("modified_huber_loss")
def _modified_huber(ctx, ins, attrs, op):
    x, y = ins["X"], ins["Y"]
    s = 2.0 * y - 1.0
    z = x * s
    loss = jnp.where(z >= 1.0, jnp.zeros_like(z),
                     jnp.where(z >= -1.0, jnp.square(1.0 - z), -4.0 * z))
    return {"IntermediateVal": z, "Out": loss}


@register_op("bilinear_tensor_product")
def _bilinear_tp(ctx, ins, attrs, op):
    x, y, w = ins["X"], ins["Y"], ins["Weight"]  # [N,M],[N,P],[S,M,P]
    out = jnp.einsum("nm,smp,np->ns", x, w, y)
    if ins.has("Bias"):
        out = out + ins["Bias"]
    return {"Out": out}


@register_op("nce", stateful=True)
def _nce(ctx, ins, attrs, op):
    """Noise-contrastive estimation (reference nce_op.cc), uniform sampler."""
    x = ins["Input"]              # [N, D]
    label = ins["Label"]          # [N, T]
    w = ins["Weight"]             # [V, D]
    num_neg = attrs.get("num_neg_samples", 10)
    total = attrs.get("num_total_classes")
    n = x.shape[0]
    t = label.shape[1] if label.ndim > 1 else 1
    label2 = label.reshape(n, t)
    key = ctx.next_key()
    neg = jax.random.randint(key, (n, num_neg), 0, total)
    samples = jnp.concatenate([label2, neg], axis=1)      # [N, T+S]
    ws = w[samples]                                       # [N, T+S, D]
    logits = jnp.einsum("nd,nkd->nk", x, ws)
    if ins.has("Bias"):
        logits = logits + ins["Bias"][samples]
    p_noise = 1.0 / total
    # logits adjusted by noise distribution: sigmoid CE against true/noise
    lbl = jnp.concatenate([jnp.ones((n, t)), jnp.zeros((n, num_neg))], axis=1)
    adj = logits - jnp.log(num_neg * p_noise)
    per = jnp.maximum(adj, 0) - adj * lbl + jnp.log1p(jnp.exp(-jnp.abs(adj)))
    cost = jnp.sum(per, axis=1, keepdims=True)
    return {"Cost": cost, "SampleLogits": logits,
            "SampleLabels": samples}


@register_op("lambda_rank", seq_aware=True, no_vjp_outputs=("NDCG",))
def _lambda_rank(ctx, ins, attrs, op=None):
    """LambdaRank cost (reference gserver/layers/CostLayer.cpp:363-528
    LambdaCost, via trainer_config_helpers lambda_cost:6094).

    Score = model outputs, Label = gold relevance, one ragged sequence
    per query.  The reference hand-writes the lambda gradient
    (calcGrad:423): for each pair (i, j) by GOLD-sorted position i<j,

        dcgDif = (2^{l_i} - 2^{l_j}) (1/ln(i+2) - 1/ln(j+2))
        grad_i += -|dcgDif| / (1 + e^{s_i - s_j}) / maxDCG      (+/- j)

    with maxDCG = sum of the NDCG_num best gold gains (2^l - 1)/ln(p+2)
    — note NATURAL logs, pair discounts NOT truncated, positions from
    the gold sort.  ``Out`` here is the surrogate
    sum |dcgDif|/maxDCG * log(1 + e^{-(s_i - s_j)}) whose autodiff
    gradient is EXACTLY that lambda; ``NDCG`` is the reference
    forward's reported value (calcNDCG:484 — gold gains at the top-k
    positions of the OUTPUT order, over maxDCG)."""
    from paddle_tpu.ops.sequence import _lens_of, _mask

    score = ins["Score"]
    label = ins["Label"]
    k = int(attrs.get("NDCG_num", 5))
    if score.ndim == 3:
        score = score[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.float32)
    sf = score.astype(jnp.float32)
    n, t = sf.shape
    lens = _lens_of(ctx, op, "Score")
    if lens is None:
        lens = _lens_of(ctx, op, "Label")
    valid = (_mask(lens, n, t, jnp.bool_) if lens is not None
             else jnp.ones((n, t), bool))

    neg_inf = jnp.float32(-1e30)
    # 0-based position of each item in the DESCENDING GOLD order
    gold_key = jnp.where(valid, label, neg_inf)
    order = jnp.argsort(-gold_key, axis=1, stable=True)
    pos = jnp.argsort(order, axis=1).astype(jnp.float32)     # [N, T]
    disc = 1.0 / jnp.log(pos + 2.0)                # natural log, no cut
    gain = jnp.exp2(jnp.where(valid, label, 0.0))            # 2^l

    # maxDCG over the NDCG_num best gold gains
    sg = -jnp.sort(-jnp.where(valid, gain - 1.0, 0.0), axis=1)
    top_disc = jnp.where(jnp.arange(t) < k,
                         1.0 / jnp.log(jnp.arange(t, dtype=jnp.float32)
                                       + 2.0), 0.0)
    maxdcg = jnp.maximum((sg * top_disc[None, :]).sum(axis=1), 1e-6)

    d_gain = gain[:, :, None] - gain[:, None, :]             # [N, T, T]
    d_disc = disc[:, :, None] - disc[:, None, :]
    weight = jnp.abs(d_gain * d_disc) / maxdcg[:, None, None]
    # each unordered pair once: l_i > l_j (equal-gold pairs weigh 0)
    pair = (valid[:, :, None] & valid[:, None, :] &
            (label[:, :, None] > label[:, None, :]))
    d_s = sf[:, :, None] - sf[:, None, :]
    logistic = jnp.log1p(jnp.exp(-jnp.abs(d_s))) + jnp.maximum(-d_s, 0.0)
    cost = jnp.where(pair, weight * logistic, 0.0).sum(axis=(1, 2))

    # reported NDCG: gold gains at the top-k OUTPUT-order positions
    out_key = jnp.where(valid, sf, neg_inf)
    by_out = jnp.take_along_axis(jnp.where(valid, gain - 1.0, 0.0),
                                 jnp.argsort(-out_key, axis=1), axis=1)
    dcg = (by_out * top_disc[None, :]).sum(axis=1)
    ndcg = dcg / maxdcg
    return {"Out": cost[:, None], "NDCG": ndcg[:, None]}
