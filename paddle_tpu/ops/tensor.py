"""Tensor manipulation ops.

Parity: reference operators/{concat,split,reshape,transpose,expand,gather,
scatter,pad,crop,slice,reverse,shape,top_k,arg_max,arg_min,one_hot,assign,
assign_value,fill_constant,fill_constant_batch_size_like,fill_zeros_like,
lookup_table,multiplex,bilinear_interp,label_smooth,squeeze,unsqueeze,
multiplex,mean_iou}_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.types import proto_to_np_dtype, DataType


@register_op("concat")
def _concat(ctx, ins, attrs, op):
    xs = [x for x in ins.list("X") if x is not None]
    return {"Out": jnp.concatenate(xs, axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ctx, ins, attrs, op):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections", [])
    num = attrs.get("num", 0)
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("reshape")
def _reshape(ctx, ins, attrs, op):
    x = ins["X"]
    shape = list(attrs.get("shape"))
    # 0 = keep input dim (reference reshape semantics), -1 = infer
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return {"Out": x.reshape(shape)}


@register_op("reshape2")
def _reshape2(ctx, ins, attrs, op):
    x = ins["X"]
    shape = list(attrs.get("shape"))
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return {"Out": x.reshape(shape),
            "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("transpose")
def _transpose(ctx, ins, attrs, op):
    return {"Out": jnp.transpose(ins["X"], attrs.get("axis"))}


@register_op("transpose2")
def _transpose2(ctx, ins, attrs, op):
    x = ins["X"]
    return {"Out": jnp.transpose(x, attrs.get("axis")),
            "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype)}


@register_op("squeeze")
def _squeeze(ctx, ins, attrs, op):
    x = ins["X"]
    axes = attrs.get("axes", [])
    if axes:
        shape = [d for i, d in enumerate(x.shape)
                 if not (i in axes or i - x.ndim in axes) or d != 1]
        return {"Out": x.reshape(shape)}
    return {"Out": jnp.squeeze(x)}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs, op):
    x = ins["X"]
    for ax in sorted(attrs.get("axes", [])):
        x = jnp.expand_dims(x, ax)
    return {"Out": x}


@register_op("expand")
def _expand(ctx, ins, attrs, op):
    return {"Out": jnp.tile(ins["X"], attrs.get("expand_times"))}


@register_op("gather")
def _gather(ctx, ins, attrs, op):
    idx = ins["Index"].reshape(-1).astype(jnp.int32)
    return {"Out": jnp.take(ins["X"], idx, axis=0)}


@register_op("scatter")
def _scatter(ctx, ins, attrs, op):
    x, ids, upd = ins["X"], ins["Ids"], ins["Updates"]
    ids = ids.reshape(-1).astype(jnp.int32)
    return {"Out": x.at[ids].set(upd)}


@register_op("pad")
def _pad(ctx, ins, attrs, op):
    x = ins["X"]
    p = attrs.get("paddings")
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads,
                           constant_values=attrs.get("pad_value", 0.0))}


@register_op("crop")
def _crop(ctx, ins, attrs, op):
    x = ins["X"]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    if ins.has("Y"):
        shape = ins["Y"].shape
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": x[slices]}


@register_op("slice")
def _slice(ctx, ins, attrs, op):
    x = ins["Input"]
    axes = attrs.get("axes")
    starts = attrs.get("starts")
    ends = attrs.get("ends")
    slices = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        slices[ax] = slice(st, en)
    return {"Out": x[tuple(slices)]}


@register_op("reverse")
def _reverse(ctx, ins, attrs, op):
    return {"Out": jnp.flip(ins["X"], attrs.get("axis"))}


@register_op("shape", grad_maker=None)
def _shape(ctx, ins, attrs, op):
    return {"Out": jnp.asarray(ins["Input"].shape, dtype=jnp.int64)}


@register_op("top_k", grad_maker=None)
def _top_k(ctx, ins, attrs, op):
    vals, idx = jax.lax.top_k(ins["X"], attrs.get("k", 1))
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("arg_max", grad_maker=None)
def _arg_max(ctx, ins, attrs, op):
    return {"Out": jnp.argmax(ins["X"], axis=attrs.get("axis", -1))
            .astype(jnp.int64)}


@register_op("arg_min", grad_maker=None)
def _arg_min(ctx, ins, attrs, op):
    return {"Out": jnp.argmin(ins["X"], axis=attrs.get("axis", -1))
            .astype(jnp.int64)}


@register_op("argsort", grad_maker=None)
def _argsort(ctx, ins, attrs, op):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": jnp.sort(x, axis=axis), "Indices": idx.astype(jnp.int64)}


@register_op("one_hot", grad_maker=None)
def _one_hot(ctx, ins, attrs, op):
    x = ins["X"]
    depth = attrs.get("depth")
    flat = x.reshape(x.shape[:-1] if x.shape[-1] == 1 else x.shape)
    return {"Out": jax.nn.one_hot(flat.astype(jnp.int32), depth,
                                  dtype=jnp.float32)}


@register_op("assign")
def _assign(ctx, ins, attrs, op):
    return {"Out": ins["X"]}


@register_op("assign_value", grad_maker=None)
def _assign_value(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    shape = attrs.get("shape")
    if attrs.get("fp32_values"):
        vals = np.asarray(attrs["fp32_values"], dtype=np.float32)
    else:
        vals = np.asarray(attrs.get("int32_values", []), dtype=np.int32)
    return {"Out": jnp.asarray(vals.reshape(shape), dtype=dtype)}


@register_op("fill_constant", grad_maker=None)
def _fill_constant(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    return {"Out": jnp.full(tuple(attrs.get("shape", [1])),
                            attrs.get("value", 0.0), dtype=dtype)}


@register_op("fill_constant_batch_size_like", grad_maker=None)
def _fill_cbsl(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    shape = list(attrs.get("shape"))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ins["Input"].shape[in_idx]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0),
                            dtype=dtype)}


@register_op("fill_zeros_like", grad_maker=None)
def _fill_zeros_like(ctx, ins, attrs, op):
    return {"Out": jnp.zeros_like(ins["X"])}


@register_op("fill", grad_maker=None)
def _fill(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    vals = np.asarray(attrs.get("value"), dtype=np.float32)
    return {"Out": jnp.asarray(vals.reshape(attrs.get("shape")),
                               dtype=dtype)}


def _lookup_idx(ids):
    idx = ids.reshape(ids.shape[:-1]) if ids.shape[-1] == 1 else ids
    return idx.astype(jnp.int32)


@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs, op):
    """Embedding lookup (reference lookup_table_op.cc).  Ids [..., 1] int64.
    The gather's vjp is a scatter-add, which XLA lowers efficiently; with
    is_sparse=True the explicit grad lowering below emits a SelectedRows
    instead of materializing the [V, D] dense grad."""
    w, ids = ins["W"], ins["Ids"]
    padding_idx = attrs.get("padding_idx", -1)
    idx = _lookup_idx(ids)
    out = jnp.take(w, idx, axis=0)
    if padding_idx != -1:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros_like(out), out)
    return {"Out": out}


@register_op("lookup_table_grad", grad_maker=None)
def _lookup_table_grad(ctx, ins, attrs, op):
    """W@GRAD of the lookup: SelectedRows (rows = the looked-up ids,
    values = the out-grad rows) when is_sparse, else dense scatter-add
    (reference lookup_table_op.cc grad kernels + selected_rows_functor)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    ids, g = ins["Ids"], ins["Out@GRAD"]
    w = ins.get("W")
    # distributed lookup tables never exist on the trainer: shape comes
    # from the 'table_shape' attr the transpiler stamps instead
    if w is not None:
        height, d, wdtype = int(w.shape[0]), int(w.shape[1]), w.dtype
    else:
        height, d = [int(s) for s in attrs["table_shape"]]
        wdtype = g.dtype
    padding_idx = attrs.get("padding_idx", -1)
    idx = _lookup_idx(ids)
    rows = idx.reshape(-1)
    vals = g.reshape(-1, d).astype(wdtype)
    if padding_idx != -1:
        # vjp of the padding mask: those rows contribute nothing
        vals = jnp.where((rows == padding_idx)[:, None],
                         jnp.zeros_like(vals), vals)
    if attrs.get("is_sparse", False):
        return {"W@GRAD": SelectedRows(rows, vals, height)}
    if w is None:
        raise ValueError(
            "lookup_table_grad without W requires is_sparse=True "
            "(distributed tables always ship sparse grads)")
    dense = jnp.zeros_like(w).at[rows].add(vals)
    return {"W@GRAD": dense}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs, op):
    ids = ins["Ids"].reshape(-1).astype(jnp.int32)
    xs = jnp.stack([x for x in ins.list("X")], axis=0)  # [K, N, D]
    rows = jnp.arange(ids.shape[0])
    return {"Out": xs[ids, rows]}


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs, op):
    x = ins["X"]  # NCHW
    oh = attrs.get("out_h")
    ow = attrs.get("out_w")
    if ins.has("OutSize"):
        pass  # dynamic size unsupported under XLA static shapes; attr wins
    n, c, h, w = x.shape
    ratio_h = (h - 1.0) / (oh - 1.0) if oh > 1 else 0.0
    ratio_w = (w - 1.0) / (ow - 1.0) if ow > 1 else 0.0
    hi = jnp.arange(oh) * ratio_h
    wi = jnp.arange(ow) * ratio_w
    h0 = jnp.floor(hi).astype(jnp.int32)
    w0 = jnp.floor(wi).astype(jnp.int32)
    h1 = jnp.minimum(h0 + 1, h - 1)
    w1 = jnp.minimum(w0 + 1, w - 1)
    lh = (hi - h0)[None, None, :, None]
    lw = (wi - w0)[None, None, None, :]
    v00 = x[:, :, h0][:, :, :, w0]
    v01 = x[:, :, h0][:, :, :, w1]
    v10 = x[:, :, h1][:, :, :, w0]
    v11 = x[:, :, h1][:, :, :, w1]
    out = (v00 * (1 - lh) * (1 - lw) + v01 * (1 - lh) * lw
           + v10 * lh * (1 - lw) + v11 * lh * lw)
    return {"Out": out}


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs, op):
    x = ins["X"]
    eps = attrs.get("epsilon", 0.0)
    if ins.has("PriorDist"):
        return {"Out": (1 - eps) * x + eps * ins["PriorDist"]}
    return {"Out": (1 - eps) * x + eps / x.shape[-1]}


@register_op("mean_iou", grad_maker=None)
def _mean_iou(ctx, ins, attrs, op):
    pred = ins["Predictions"].reshape(-1).astype(jnp.int32)
    label = ins["Labels"].reshape(-1).astype(jnp.int32)
    num = attrs.get("num_classes")
    cm = jnp.zeros((num, num), jnp.int64).at[label, pred].add(1)
    inter = jnp.diagonal(cm).astype(jnp.float32)
    union = (cm.sum(0) + cm.sum(1)).astype(jnp.float32) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    return {"OutMeanIou": miou.reshape(()),
            "OutWrong": (cm.sum(1).astype(jnp.int32) -
                         jnp.diagonal(cm).astype(jnp.int32)),
            "OutCorrect": jnp.diagonal(cm).astype(jnp.int32)}


@register_op("im2sequence")
def _im2sequence(ctx, ins, attrs, op):
    """Extract patches (reference im2sequence_op.cc), dense form."""
    x = ins["X"]  # NCHW
    kh, kw = attrs.get("kernels")
    sh, sw = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    n, c, h, w = xp.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # [N, C*kh*kw, OH, OW] -> [N*OH*OW, C*kh*kw]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    return {"Out": out}


@register_op("random_crop", stateful=True, grad_maker=None)
def _random_crop(ctx, ins, attrs, op):
    x = ins["X"]
    shape = attrs.get("shape")
    key = ctx.next_key()
    ndim_crop = len(shape)
    lead = x.ndim - ndim_crop
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        k = jax.random.fold_in(key, i)
        starts.append(jax.random.randint(k, (), 0, max(limit, 0) + 1))
    idx = [slice(None)] * lead
    out = jax.lax.dynamic_slice(
        x, [jnp.zeros((), jnp.int32)] * lead + starts,
        list(x.shape[:lead]) + list(shape))
    return {"Out": out, "SeedOut": ins.get("Seed")}
