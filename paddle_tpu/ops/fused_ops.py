"""Fused transformer-block ops (ISSUE 7).

The ops FuseTransformerBlockPass (fluid/transpiler/transformer_fuse.py)
emits, backed by the Pallas kernels in kernels/matmul_fused.py:

- ``fused_qkv_matmul``:   X @ [W_q | W_k | W_v] — one wide matmul
  feeding flash attention's q/k/v instead of three reads of X.
- ``fused_matmul_bias_act``: matmul + bias (+relu/gelu) (+dropout)
  (+residual add) with the elementwise tail fused into the matmul's
  f32 VMEM accumulator epilogue.
- ``fused_add_ln``: LayerNorm(X + Y) with the residual sum and the LN
  statistics computed from one VMEM tile; the sum is also an output
  (the residual stream reads it downstream).

Each has an EXPLICIT grad lowering consuming the forward's saved
activations (MulOut / Mask / Sum) — the dropout-Mask pattern from
fused_conv2d_bn_act: the backward never re-executes the forward matmul
or activation chain.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


def _flat2(x, num_col_dims):
    lead = x.shape[:num_col_dims]
    return x.reshape(int(np.prod(lead)), -1), lead


def _compute_dtype(ctx, *vals):
    if getattr(ctx, "amp", False):
        return jnp.bfloat16
    return jnp.result_type(*vals)


# ---------------------------------------------------------------------------
# fused_qkv_matmul
# ---------------------------------------------------------------------------

def _qkv_lower(ctx, ins, attrs, op):
    from paddle_tpu.kernels import matmul_fused

    x = ins["X"]
    ws = [w for w in ins.list("W") if w is not None]
    xn = attrs.get("x_num_col_dims", 1)
    x2, lead = _flat2(x, xn)
    wcat = jnp.concatenate(ws, axis=1)
    y2 = matmul_fused.matmul_epilogue(
        x2, wcat,
        force_xla=bool(attrs.get("force_xla", False)),
        interpret=bool(attrs.get("interpret", False)))
    outs = []
    off = 0
    for w in ws:
        n = w.shape[1]
        outs.append(y2[:, off:off + n].reshape(lead + (n,)))
        off += n
    return {"Out": outs}


def _qkv_infer(ins, attrs, op):
    x = ins["X"]
    xn = attrs.get("x_num_col_dims", 1)
    lead = x.shape[:xn]
    return {"Out": [jax.ShapeDtypeStruct(lead + (w.shape[1],), x.dtype)
                    for w in ins.list("W")]}


register_op("fused_qkv_matmul", lower=_qkv_lower, infer_shape=_qkv_infer)


@register_op("fused_qkv_matmul_grad", grad_maker=None)
def _qkv_grad(ctx, ins, attrs, op):
    """One wide backward pair: dX = dYcat @ Wcat^T and
    dWcat = X^T @ dYcat, sliced back per head — the same two matmuls
    the unfused three-mul chain needs, at a third of the X reads."""
    x = ins["X"]
    ws = list(ins.list("W"))
    xn = attrs.get("x_num_col_dims", 1)
    x2, _ = _flat2(x, xn)
    m = x2.shape[0]
    dys = list(ins.list("Out@GRAD"))
    d2s = []
    for w, dy in zip(ws, dys):
        if dy is None:
            d2s.append(jnp.zeros((m, w.shape[1]),
                                 jnp.result_type(x2, w)))
        else:
            d2s.append(dy.reshape(m, w.shape[1]))
    dcat = jnp.concatenate(d2s, axis=1)
    wcat = jnp.concatenate(ws, axis=1)
    cdt = _compute_dtype(ctx, x2, wcat)
    dx2 = jnp.dot(dcat.astype(cdt), wcat.astype(cdt).T,
                  preferred_element_type=jnp.result_type(x2))
    dwcat = jnp.dot(x2.astype(cdt).T, dcat.astype(cdt),
                    preferred_element_type=jnp.result_type(wcat))
    dws = []
    off = 0
    for w in ws:
        n = w.shape[1]
        dws.append(dwcat[:, off:off + n].astype(w.dtype))
        off += n
    return {"X@GRAD": dx2.reshape(x.shape).astype(x.dtype),
            "W@GRAD": dws}


# ---------------------------------------------------------------------------
# fused_matmul_bias_act
# ---------------------------------------------------------------------------

def _mba_lower(ctx, ins, attrs, op):
    from paddle_tpu.kernels import matmul_fused

    x, w = ins["X"], ins["W"]
    bias = ins.get("Bias")
    residual = ins.get("Residual")
    xn = attrs.get("x_num_col_dims", 1)
    act = attrs.get("act", "")
    p = float(attrs.get("dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False)) or ctx.mode == "test"
    force_xla = bool(attrs.get("force_xla", False))
    interpret = bool(attrs.get("interpret", False))
    x2, lead = _flat2(x, xn)
    n = w.shape[1]
    res2 = residual.reshape(-1, n) if residual is not None else None
    save_pre = bool(op.outputs.get("MulOut"))
    want_mask = bool(op.outputs.get("Mask"))

    outs = {}
    if p > 0.0 and not is_test:
        # matmul+bias+act in the kernel; the dropout mask and the
        # residual tail compose in XLA (mask generation needs the
        # program PRNG stream, which lives outside the kernel)
        r = matmul_fused.matmul_epilogue(
            x2, w, bias, None, act, save_preact=save_pre,
            force_xla=force_xla, interpret=interpret)
        h2, pre2 = r if save_pre else (r, None)
        seed = attrs.get("seed", 0)
        key = jax.random.PRNGKey(seed) if seed else ctx.next_key()
        # draw at the op-output shape so an explicit seed reproduces
        # the unfused dropout op's mask bit-for-bit (same key, same
        # element count, same layout)
        keep = jax.random.bernoulli(key, 1.0 - p, lead + (h2.shape[-1],))
        mask2 = keep.astype(h2.dtype).reshape(h2.shape)
        if attrs.get("dropout_implementation",
                     "downgrade_in_infer") == "upscale_in_train":
            mask2 = mask2 / (1.0 - p)
        y2 = h2 * mask2
        if res2 is not None:
            y2 = y2 + res2
        outs["Mask"] = mask2.reshape(lead + (n,))
    else:
        if p > 0.0:  # test mode: downgrade (reference dropout_op)
            impl = attrs.get("dropout_implementation",
                             "downgrade_in_infer")
            r = matmul_fused.matmul_epilogue(
                x2, w, bias, None, act, save_preact=save_pre,
                force_xla=force_xla, interpret=interpret)
            h2, pre2 = r if save_pre else (r, None)
            if impl != "upscale_in_train":
                h2 = h2 * (1.0 - p)
            y2 = h2 + res2 if res2 is not None else h2
            if want_mask:
                outs["Mask"] = jnp.ones(lead + (n,), h2.dtype)
        else:
            r = matmul_fused.matmul_epilogue(
                x2, w, bias, res2, act, save_preact=save_pre,
                force_xla=force_xla, interpret=interpret)
            y2, pre2 = r if save_pre else (r, None)
    outs["Out"] = y2.reshape(lead + (n,)).astype(x.dtype)
    if save_pre and pre2 is not None:
        outs["MulOut"] = pre2.reshape(lead + (n,))
    return outs


def _mba_infer(ins, attrs, op):
    x, w = ins["X"], ins["W"]
    xn = attrs.get("x_num_col_dims", 1)
    shp = x.shape[:xn] + (w.shape[1],)
    sds = jax.ShapeDtypeStruct
    return {"Out": sds(shp, x.dtype), "MulOut": sds(shp, x.dtype),
            "Mask": sds(shp, x.dtype)}


register_op("fused_matmul_bias_act", lower=_mba_lower,
            infer_shape=_mba_infer, stateful=True)


@register_op("fused_matmul_bias_act_grad", grad_maker=None)
def _mba_grad(ctx, ins, attrs, op):
    """Backward from saved residuals only: the activation derivative
    comes from MulOut (or the Out sign for plain relu), the dropout
    tail replays the saved Mask, and the two grad matmuls run on the
    forward's operands — no forward re-execution."""
    x, w = ins["X"], ins["W"]
    bias = ins.get("Bias")
    residual = ins.get("Residual")
    dy = ins["Out@GRAD"]
    xn = attrs.get("x_num_col_dims", 1)
    act = attrs.get("act", "")
    p = float(attrs.get("dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False))
    x2, _ = _flat2(x, xn)
    n = w.shape[1]
    dy2 = dy.reshape(-1, n)

    dh = dy2
    out_grads = {}
    if residual is not None:
        out_grads["Residual@GRAD"] = dy.reshape(
            residual.shape).astype(residual.dtype)
    mask = ins.get("Mask")
    if p > 0.0 and not is_test and mask is not None:
        dh = dh * mask.reshape(-1, n)
    elif p > 0.0 and is_test and attrs.get(
            "dropout_implementation",
            "downgrade_in_infer") != "upscale_in_train":
        dh = dh * (1.0 - p)

    if act:
        pre = ins.get("MulOut")
        if pre is not None:
            pre2 = pre.reshape(-1, n)
            from paddle_tpu.kernels.matmul_fused import apply_act
            _, act_vjp = jax.vjp(lambda t: apply_act(t, act), pre2)
            dpre = act_vjp(dh.astype(pre2.dtype))[0]
        elif act == "relu":
            # no saved pre-activation: Out IS relu(pre) (the pass only
            # omits MulOut when nothing follows the activation)
            out = ins["Out"].reshape(-1, n)
            dpre = jnp.where(out > 0, dh, jnp.zeros_like(dh))
        else:
            raise ValueError(
                "fused_matmul_bias_act_grad: act %r needs the saved "
                "MulOut output" % (act,))
    else:
        dpre = dh
    # a direct MulOut consumer (a test harness differentiating through
    # the saved pre-activation) contributes straight into dpre
    dmul = ins.get("MulOut@GRAD")
    if dmul is not None:
        dpre = dpre + dmul.reshape(-1, n).astype(dpre.dtype)

    if bias is not None:
        out_grads["Bias@GRAD"] = dpre.sum(axis=0).astype(bias.dtype)
    cdt = _compute_dtype(ctx, x2, w)
    dx2 = jnp.dot(dpre.astype(cdt), w.astype(cdt).T,
                  preferred_element_type=jnp.result_type(x2))
    dw = jnp.dot(x2.astype(cdt).T, dpre.astype(cdt),
                 preferred_element_type=jnp.result_type(w))
    out_grads["X@GRAD"] = dx2.reshape(x.shape).astype(x.dtype)
    out_grads["W@GRAD"] = dw.astype(w.dtype)
    return out_grads


# ---------------------------------------------------------------------------
# fused_add_ln
# ---------------------------------------------------------------------------

def _add_ln_lower(ctx, ins, attrs, op):
    from paddle_tpu.kernels import matmul_fused

    x, y = ins["X"], ins["Y"]
    scale, bias = ins.get("Scale"), ins.get("Bias")
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    lead = x.shape[:begin]
    d = int(np.prod(x.shape[begin:]))
    x2 = x.reshape(-1, d)
    y2 = y.reshape(-1, d)
    out2, sum2, mean, var = matmul_fused.add_ln(
        x2, y2, scale, bias, eps,
        force_xla=bool(attrs.get("force_xla", False)),
        interpret=bool(attrs.get("interpret", False)))
    return {"Out": out2.reshape(x.shape), "Sum": sum2.reshape(x.shape),
            "Mean": mean.reshape(lead), "Variance": var.reshape(lead)}


def _add_ln_infer(ins, attrs, op):
    x = ins["X"]
    begin = attrs.get("begin_norm_axis", 1)
    sds = jax.ShapeDtypeStruct
    return {"Out": sds(x.shape, x.dtype), "Sum": sds(x.shape, x.dtype),
            "Mean": sds(x.shape[:begin], x.dtype),
            "Variance": sds(x.shape[:begin], x.dtype)}


register_op("fused_add_ln", lower=_add_ln_lower,
            infer_shape=_add_ln_infer)


@register_op("fused_add_ln_grad", grad_maker=None)
def _add_ln_grad(ctx, ins, attrs, op):
    """Backward from the SAVED residual sum: the LN normalization is
    replayed from Sum (association-identical to the layer_norm
    lowering, so its vjp matches the unfused chain's vjp exactly) and
    dX = dY = d(Sum) — the X+Y add is never re-executed, and a direct
    Sum@GRAD contribution from other Sum consumers folds in."""
    from paddle_tpu.kernels import matmul_fused

    x, y = ins["X"], ins["Y"]
    scale, bias = ins.get("Scale"), ins.get("Bias")
    s = ins["Sum"]
    dout = ins["Out@GRAD"]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    d = int(np.prod(x.shape[begin:]))
    s2 = s.reshape(-1, d)
    dout2 = dout.reshape(-1, d)

    def replay(s2_, scale_, bias_):
        # the layer_norm lowering's exact math on the saved sum; Mean/
        # Variance ride along so a direct consumer's cotangent (test
        # harnesses; real programs mark them stop_gradient) folds in
        return matmul_fused.ln_from_sum(s2_, scale_, bias_, eps)

    rows = s2.shape[0]

    def _aux_cot(slot):
        g = ins.get(slot)
        if g is None:
            return jnp.zeros((rows,), s2.dtype)
        return g.reshape(rows).astype(s2.dtype)

    cots = (dout2.astype(s2.dtype), _aux_cot("Mean@GRAD"),
            _aux_cot("Variance@GRAD"))
    if scale is not None and bias is not None:
        _, vjp = jax.vjp(replay, s2, scale, bias)
        ds2, dscale, dbias = vjp(cots)
    elif scale is not None:
        _, vjp = jax.vjp(lambda a, b: replay(a, b, None), s2, scale)
        ds2, dscale = vjp(cots)
        dbias = None
    elif bias is not None:
        _, vjp = jax.vjp(lambda a, b: replay(a, None, b), s2, bias)
        ds2, dbias = vjp(cots)
        dscale = None
    else:
        _, vjp = jax.vjp(lambda a: replay(a, None, None), s2)
        ds2, = vjp(cots)
        dscale = dbias = None

    dsum = ds2.reshape(x.shape)
    dsum_in = ins.get("Sum@GRAD")
    if dsum_in is not None:
        dsum = dsum + dsum_in.astype(dsum.dtype)
    out = {"X@GRAD": dsum.astype(x.dtype),
           "Y@GRAD": dsum.astype(y.dtype)}
    if dscale is not None:
        out["Scale@GRAD"] = dscale.astype(scale.dtype)
    if dbias is not None:
        out["Bias@GRAD"] = dbias.astype(bias.dtype)
    return out
