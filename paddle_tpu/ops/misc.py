"""Remaining specialty operators closing the reference op census.

Parity: reference operators/{conv_shift,fake_dequantize,
polygon_box_transform,pool_with_index,unpool,roi_pool,
positive_negative_pair}_op.cc — the last same-name gaps after aliases
(activation/compare/conv/... register per-op) and by-design
subsumptions (mkldnn/tensorrt/nccl variants, reader chain, channels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.io_ops import _host


@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs, op=None):
    """Circular correlation (reference conv_shift_op.cc): X [B, M],
    Y [B, N] with N odd, N <= M; Out[b, i] = sum_j X[b, (i+j-N/2) % M]
    * Y[b, j]."""
    x, y = ins["X"], ins["Y"]
    m = x.shape[1]
    n = y.shape[1]
    half = n // 2
    # gather the N circularly-shifted views: [B, M, N]
    offs = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    gathered = x[:, offs]                       # [B, M, N]
    return {"Out": jnp.einsum("bmn,bn->bm", gathered, y)}


@register_op("fake_dequantize_max_abs", grad_maker=None)
def _fake_dequantize_max_abs(ctx, ins, attrs, op=None):
    """Out = Scale * X / max_range (reference fake_dequantize_op.cc) —
    the int8 simulation's dequantize step."""
    x = ins["X"].astype(jnp.float32)
    scale = ins["Scale"].reshape(()).astype(jnp.float32)
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x * scale / max_range}


@register_op("polygon_box_transform", grad_maker=None)
def _polygon_box_transform(ctx, ins, attrs, op=None):
    """EAST-style geometry decode (reference
    polygon_box_transform_op.cc): input [N, K*2, H, W] per-pixel
    offsets; output = pixel coordinate (index*4) minus the offset at
    even channels (x) / odd channels (y)."""
    x = ins["Input"]
    n, c, h, w = x.shape
    xs = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4
    ys = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, xs, ys)
    return {"Output": base - x}


def _pool_index_common(x, ksize, strides, paddings):
    """Max pool returning values + flat argmax within each input map
    (reference pool_with_index_op.h: Mask holds h*W + w)."""
    n, c, h, w = x.shape
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    # index map of the ORIGINAL coordinates, padded with -1
    flat_idx = (jnp.arange(h)[:, None] * w +
                jnp.arange(w)[None, :]).astype(jnp.int32)
    idxp = jnp.pad(flat_idx, ((ph, ph), (pw, pw)), constant_values=-1)

    # extract windows: [OH, OW, KH, KW] index grids
    hh = (jnp.arange(oh)[:, None] * sh + jnp.arange(kh)[None, :])
    ww = (jnp.arange(ow)[:, None] * sw + jnp.arange(kw)[None, :])
    win = xp[:, :, hh[:, :, None, None], ww[None, None, :, :]]
    # win: [N, C, OH, KH, OW, KW] -> [N, C, OH, OW, KH*KW]
    win = jnp.moveaxis(win, 3, 4).reshape(n, c, oh, ow, kh * kw)
    arg = jnp.argmax(win, axis=-1)
    out = jnp.max(win, axis=-1)
    iwin = idxp[hh[:, :, None, None], ww[None, None, :, :]]
    iwin = jnp.moveaxis(iwin, 1, 2).reshape(oh, ow, kh * kw)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(iwin, (n, c, oh, ow, kh * kw)),
        arg[..., None], axis=-1)[..., 0]
    return out, mask.astype(jnp.int32)


@register_op("max_pool2d_with_index",
             no_vjp_outputs=("Mask",))
def _max_pool2d_with_index(ctx, ins, attrs, op=None):
    x = ins["X"]
    ksize = [int(k) for k in attrs["ksize"]]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    out, mask = _pool_index_common(x, ksize, strides, paddings)
    return {"Out": out, "Mask": mask}


@register_op("unpool")
def _unpool(ctx, ins, attrs, op=None):
    """Max unpooling (reference unpool_op.cc): scatter X back to the
    positions recorded in Indices; everything else zero."""
    x = ins["X"]                      # [N, C, OH, OW]
    idx = ins["Indices"].astype(jnp.int32)
    ksize = [int(k) for k in attrs["ksize"]]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    n, c, oh, ow = x.shape
    h = (oh - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    w = (ow - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n, c, h * w), x.dtype)
    sc = idx.reshape(n, c, oh * ow)
    # -1 marks pad-region argmax (never selected in practice): drop via
    # out-of-bounds scatter
    sc = jnp.where(sc < 0, h * w, sc)
    # ASSIGN, not add: overlapping pooling windows record the same
    # source index several times and must not sum it (reference
    # unpool_op.h writes output[index] = input[i])
    flat = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None], sc].set(
        x.reshape(n, c, oh * ow))
    return {"Out": flat.reshape(n, c, h, w)}


@register_op("roi_pool", no_vjp_outputs=("Argmax",))
def _roi_pool(ctx, ins, attrs, op=None):
    """ROI max pooling (reference roi_pool_op.cc): X [N,C,H,W]; ROIs
    [R, 5] rows [batch_idx, x1, y1, x2, y2] (image coordinates, scaled
    by spatial_scale).  Out [R, C, PH, PW]."""
    x = ins["X"]
    rois = ins["ROIs"].astype(jnp.float32)
    scale = float(attrs.get("spatial_scale", 1.0))
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        img = x[b]                    # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        # cells are masked full-map reductions rather than a one-pass
        # segment max: the reference's floor/ceil boundaries make
        # adjacent cells OVERLAP (a pixel may win two cells), which a
        # pixel->one-cell bucketing cannot express.  PH/PW are small
        # constants (7x7 in standard configs), so the unroll is bounded.
        def cell(i, j):
            hstart = y1 + jnp.floor(i * rh / ph).astype(jnp.int32)
            hend = y1 + jnp.ceil((i + 1) * rh / ph).astype(jnp.int32)
            wstart = x1 + jnp.floor(j * rw / pw).astype(jnp.int32)
            wend = x1 + jnp.ceil((j + 1) * rw / pw).astype(jnp.int32)
            m = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                 (xs[None, :] >= wstart) & (xs[None, :] < wend))
            neg = jnp.finfo(x.dtype).min
            vals = jnp.where(m[None], img, neg).reshape(c, -1)
            best = vals.max(axis=1)
            arg = vals.argmax(axis=1).astype(jnp.int32)
            any_m = jnp.any(m)
            return jnp.where(any_m, best, 0.0), \
                jnp.where(any_m, arg, 0)

        pairs = [[cell(i, j) for j in range(pw)] for i in range(ph)]
        cells = jnp.stack(
            [jnp.stack([pairs[i][j][0] for j in range(pw)], axis=-1)
             for i in range(ph)], axis=-2)
        args = jnp.stack(
            [jnp.stack([pairs[i][j][1] for j in range(pw)], axis=-1)
             for i in range(ph)], axis=-2)
        return cells, args             # each [C, PH, PW]

    out, argmax = jax.vmap(one_roi)(rois)
    return {"Out": out, "Argmax": argmax}


@_host("positive_negative_pair")
def _positive_negative_pair(executor, op, scope, feed, env=None):
    """Ranking-pair metric (reference positive_negative_pair_op.cc):
    within each query id, count prediction-score pairs ordered
    consistently (positive) / inconsistently (negative) with the label
    order; a score tie increments NeutralPair by 1."""
    def read(name):
        for src in (env, feed):
            if src is not None and name in src:
                return np.asarray(src[name])
        return np.asarray(scope.find_var(name))

    score = read(op.input("Score")[0]).reshape(-1)
    label = read(op.input("Label")[0]).reshape(-1)
    qid = read(op.input("QueryID")[0]).reshape(-1)
    pos = neg = neu = 0.0
    for q in np.unique(qid):
        idx = np.where(qid == q)[0]
        for a in range(len(idx)):
            for b in range(a + 1, len(idx)):
                i, j = idx[a], idx[b]
                if label[i] == label[j]:
                    continue
                ds = score[i] - score[j]
                dl = label[i] - label[j]
                if ds == 0:
                    neu += 1
                elif (ds > 0) == (dl > 0):
                    pos += 1
                else:
                    neg += 1
    outs = {"PositivePair": pos, "NegativePair": neg,
            "NeutralPair": neu}
    for slot, val in outs.items():
        names = op.outputs.get(slot) or []
        if names and names[0]:
            arr = np.asarray([val], np.float32)
            if env is not None:
                env[names[0]] = arr
            (scope.find_scope_of(names[0]) or scope).set(names[0], arr)


@register_op("scale_sub_region")
def _scale_sub_region(ctx, ins, attrs, op=None):
    """Scale a per-sample [C,H,W] sub-box by ``value`` (reference
    gserver/layers/ScaleSubRegionLayer.cpp via scale_sub_region_layer:
    7493).  Indices [N, 6] rows are 1-based inclusive
    (c0, c1, h0, h1, w0, w1), the reference convention.  Lowered as a
    broadcast mask select — per-sample dynamic bounds compare against
    iotas, no dynamic slicing."""
    x = ins["X"]
    idx = ins["Indices"].astype(jnp.int32)          # [N, 6], 1-based
    value = float(attrs.get("value", 1.0))
    n, c, h, w = x.shape

    def bounds(lo, hi, size, axis_pos):
        pos = jnp.arange(size).reshape(
            (1,) + (1,) * axis_pos + (size,) +
            (1,) * (2 - axis_pos))                   # [1,...,size,...,1]
        lo = (lo - 1).reshape(n, 1, 1, 1)
        hi = (hi - 1).reshape(n, 1, 1, 1)
        return (pos >= lo) & (pos <= hi)

    mask = (bounds(idx[:, 0], idx[:, 1], c, 0) &
            bounds(idx[:, 2], idx[:, 3], h, 1) &
            bounds(idx[:, 4], idx[:, 5], w, 2))
    return {"Out": jnp.where(mask, x * value, x)}
