"""NN core ops: conv, pool, norm, softmax, dropout.

Parity: reference operators/conv_op.cc, conv_transpose_op.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, softmax_op.cc, dropout_op.cc, lrn_op.cc.
The reference dispatches to cuDNN; here each op is one lax expression that
XLA maps onto the MXU (convs as conv_general_dilated) — layouts are left to
XLA's TPU layout assignment.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


def _conv_lower(ctx, ins, attrs, op):
    from paddle_tpu.core.flags import FLAGS

    x = ins["Input"]
    w = ins["Filter"]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    # Layout-pinned path (layout_transpiler): input travels NHWC and the
    # filter parameter is STORED in the kernel-preferred layout, so the
    # conv consumes both as-is — no transposes at the op boundary and no
    # re-layout traffic for XLA to re-insert per fusion.
    data_format = attrs.get("data_format", "NCHW")
    filter_format = attrs.get("filter_format",
                              "HWIO" if data_format == "NHWC" else "OIHW")
    if data_format == "NCHW" and FLAGS.conv_nhwc:
        # legacy per-op experiment (PROFILE_r04.md): transpose at the op
        # boundary and let XLA cancel adjacent pairs; kept for bisection
        data_format, filter_format = "NHWC", "HWIO"
        x = jnp.transpose(x, (0, 2, 3, 1))
        w = jnp.transpose(w, (2, 3, 1, 0))
        retranspose = True
    else:
        retranspose = False
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=(data_format, filter_format, data_format),
        feature_group_count=groups,
        preferred_element_type=jnp.result_type(x, w))
    if retranspose:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": out}


register_op("conv2d", lower=_conv_lower)
# depthwise conv is just grouped conv; XLA lowers it natively on TPU
register_op("depthwise_conv2d", lower=_conv_lower)


@register_op("conv3d")
def _conv3d(ctx, ins, attrs, op):
    x, w = ins["Input"], ins["Filter"]
    strides = list(attrs.get("strides", [1, 1, 1]))
    paddings = list(attrs.get("paddings", [0, 0, 0]))
    dilations = list(attrs.get("dilations", [1, 1, 1]))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in paddings],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1))
    return {"Output": out}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs, op):
    """Filter layout (C_in, C_out, kH, kW) as in reference
    conv_transpose_op.cc; lowered as the transpose (lhs-dilated) conv."""
    x, w = ins["Input"], ins["Filter"]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    kh = (w.shape[2] - 1) * dilations[0] + 1
    kw = (w.shape[3] - 1) * dilations[1] + 1
    # transpose conv = conv with lhs_dilation=strides and flipped kernel
    w_flip = jnp.flip(w, axis=(2, 3))            # IOHW -> flipped
    w_t = jnp.swapaxes(w_flip, 0, 1)             # -> OIHW w/ O=C_out
    out = jax.lax.conv_general_dilated(
        x, w_t,
        window_strides=(1, 1),
        padding=[(kh - 1 - paddings[0], kh - 1 - paddings[0]),
                 (kw - 1 - paddings[1], kw - 1 - paddings[1])],
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": out}


@register_op("pool2d")
def _pool2d(ctx, ins, attrs, op):
    x = ins["X"]
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    # spatial dims by layout: NCHW (fluid default) or NHWC (pinned by
    # the layout transpiler — pooling then never forces a re-layout
    # between the surrounding NHWC conv fusions)
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    hd, wd_ = (1, 2) if nhwc else (2, 3)
    if attrs.get("global_pooling", False):
        ksize = [x.shape[hd], x.shape[wd_]]
        paddings = [0, 0]
        strides = [1, 1]
    if attrs.get("adaptive", False):
        # adaptive pooling to ksize output bins
        oh, ow = ksize
        red = jnp.max if ptype == "max" else jnp.mean
        if nhwc:
            n, h, w_, c = x.shape
            x4 = x.reshape(n, oh, h // oh, ow, w_ // ow, c)
            return {"Out": red(x4, axis=(2, 4))}
        n, c, h, w_ = x.shape
        x4 = x.reshape(n, c, oh, h // oh, ow, w_ // ow)
        return {"Out": red(x4, axis=(3, 5))}
    window = [1, 1, 1, 1]
    strides4 = [1, 1, 1, 1]
    pads4 = [(0, 0), (0, 0), (0, 0), (0, 0)]
    window[hd], window[wd_] = ksize[0], ksize[1]
    strides4[hd], strides4[wd_] = strides[0], strides[1]
    pads4[hd] = (paddings[0], paddings[0])
    pads4[wd_] = (paddings[1], paddings[1])
    window, strides4 = tuple(window), tuple(strides4)
    pads4 = tuple(pads4)
    # NOTE: init values must be Python scalars so JAX recognizes the
    # max/add monoids and lowers to the differentiable reduce-window prims.
    if ptype == "max":
        init = (-float("inf") if jnp.issubdtype(x.dtype, jnp.floating)
                else int(jnp.iinfo(x.dtype).min))
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides4,
                                    pads4)
    else:
        ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides4,
                                     pads4)
        if attrs.get("exclusive", True) and (paddings[0] or paddings[1]):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides4, pads4)
            out = ssum / cnt
        else:
            out = ssum / (ksize[0] * ksize[1])
    return {"Out": out}


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs, op):
    """reference batch_norm_op.cc: in train mode returns batch stats and
    updates the running stats in place (MeanOut/VarianceOut alias
    Mean/Variance); in test mode normalizes with running stats."""
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean_in, var_in = ins["Mean"], ins["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.mode == "test"
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if is_test:
        mean, var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
        saved_mean = mean
        saved_var = var
    else:
        # compute batch statistics in f32 for stability under bf16 inputs
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red_axes)
        var = jnp.mean(jnp.square(xf), axis=red_axes) - jnp.square(mean)
        mean = mean.astype(mean_in.dtype)
        var = var.astype(var_in.dtype)
        mean_out = mean_in * momentum + mean * (1 - momentum)
        var_out = var_in * momentum + var * (1 - momentum)
        saved_mean = mean
        saved_var = var

    inv_std = jax.lax.rsqrt(var.astype(x.dtype).reshape(bshape) + eps)
    y = (x - mean.astype(x.dtype).reshape(bshape)) * inv_std
    # affine in x.dtype: an f32 scale would promote every post-BN
    # activation back to f32 and lose the bf16 bandwidth win under the
    # bn_bf16 AMP pass-through (stats above stay f32 either way)
    y = y * scale.astype(x.dtype).reshape(bshape) \
        + bias.astype(x.dtype).reshape(bshape)
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": saved_mean, "SavedVariance": saved_var}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs, op):
    x = ins["X"]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    # statistics in f32 for stability under bf16 inputs (normalized
    # output stays in x.dtype so bf16 activation chains aren't promoted)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    mean = mean.astype(x.dtype)
    var = var.astype(x.dtype)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    nfeat = int(np.prod(x.shape[begin:]))
    fshape = (1,) * begin + tuple(x.shape[begin:])
    scale = ins.get("Scale")
    bias = ins.get("Bias")
    # affine in x.dtype: an fp32 scale would promote every post-LN
    # activation back to f32 and lose the bf16 bandwidth win under AMP
    if scale is not None:
        y = y * scale.astype(x.dtype).reshape(fshape)
    if bias is not None:
        y = y + bias.astype(x.dtype).reshape(fshape)
    lead = x.shape[:begin]
    return {"Y": y, "Mean": mean.reshape(lead), "Variance": var.reshape(lead)}


@register_op("softmax")
def _softmax(ctx, ins, attrs, op):
    return {"Out": jax.nn.softmax(ins["X"], axis=-1)}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs, op):
    return {"Out": jax.nn.log_softmax(ins["X"], axis=attrs.get("axis", -1))}


def _dropout_lower(ctx, ins, attrs, op):
    """reference dropout_op.cc ("downgrade_in_infer"): train: out = x * mask,
    mask ~ Bernoulli(1-p); infer: out = x * (1-p)."""
    x = ins["X"]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.mode == "test"
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * (1.0 - p), "Mask": jnp.ones_like(x)}
    seed = attrs.get("seed", 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        mask = mask / (1.0 - p)
    return {"Out": x * mask, "Mask": mask}


def _dropout_grad_maker(op, block, no_grad_set):
    from paddle_tpu.core.desc import OpDesc
    xg = op.input("X")[0] + "@GRAD"
    g = OpDesc("dropout_grad",
               inputs={"Mask": op.output("Mask"),
                       "Out@GRAD": [op.output("Out")[0] + "@GRAD"]},
               outputs={"X@GRAD": [xg]},
               attrs={k: a.value for k, a in op.attrs.items()})
    return [g], {xg: op.input("X")[0]}


register_op("dropout", lower=_dropout_lower, stateful=True,
            grad_maker=_dropout_grad_maker)


@register_op("dropout_grad", grad_maker=None)
def _dropout_grad(ctx, ins, attrs, op):
    return {"X@GRAD": ins["Out@GRAD"] * ins["Mask"]}


# ---------------------------------------------------------------------------
# Fused conv+BN(+residual)(+act) stage (NHWC/HWIO) — the Pallas conv-stage
# op the layout transpiler's FuseConvBNActPass emits for the ResNet 7x7
# stem and 3x3 residual stages.  Training forward fuses the BN statistics
# into the conv epilogue (kernels/conv_fused.py); the backward is an
# EXPLICIT grad lowering over the forward's saved residuals (ConvOut,
# SavedMean, SavedInvStd, Y) — the dropout-Mask pattern: the grad op never
# re-executes the forward, and its two grad convs run in the same pinned
# NHWC/HWIO layout.
# ---------------------------------------------------------------------------

def _fused_conv_bn_lower(ctx, ins, attrs, op):
    from paddle_tpu.kernels import conv_fused

    x, w = ins["Input"], ins["Filter"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean_in, var_in = ins["Mean"], ins["Variance"]
    residual = ins.get("Residual")
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    act = attrs.get("act", "")
    is_test = attrs.get("is_test", False) or ctx.mode == "test"
    interpret = bool(attrs.get("interpret", False))
    force_xla = bool(attrs.get("force_xla", False))
    co = w.shape[3]
    if not force_xla:
        # persistent autotune cache (ISSUE 7): conv_tune.py records the
        # measured winner per stage shape — 'pallas' (the fused kernel)
        # or 'xla' (the identical-math fallback was faster there)
        from paddle_tpu import tuning

        cfg = tuning.lookup(
            "fused_conv2d_bn_act",
            tuple(x.shape) + tuple(w.shape) +
            tuple(strides) + tuple(paddings),
            jnp.dtype(x.dtype).name)
        if cfg and cfg.get("impl") == "xla":
            force_xla = True

    if is_test:
        inv = jax.lax.rsqrt(var_in.astype(jnp.float32) + eps)
        a = scale.astype(jnp.float32) * inv
        b = bias.astype(jnp.float32) - mean_in.astype(jnp.float32) * a
        y = conv_fused.conv2d_nhwc(
            x, w, strides, paddings, affine=(a, b), residual=residual,
            act=act, interpret=interpret, force_xla=force_xla)
        return {"Y": y, "MeanOut": mean_in, "VarianceOut": var_in,
                "SavedMean": mean_in.astype(jnp.float32),
                "SavedInvStd": inv,
                # fully fused: the raw conv output never materializes.
                # Test-mode programs carry no grad ops; a stray reader
                # fails loudly at env resolution instead of silently.
                "ConvOut": None}

    conv_out, s, ss = conv_fused.conv2d_nhwc(
        x, w, strides, paddings, stats=True, interpret=interpret,
        force_xla=force_xla)
    m = conv_out.size // co                       # N*Ho*Wo
    mean = s / m
    var = ss / m - jnp.square(mean)               # f32, from f32 partials
    inv = jax.lax.rsqrt(var + eps)
    a = scale.astype(jnp.float32) * inv
    b = bias.astype(jnp.float32) - mean * a
    yf = conv_out.astype(jnp.float32) * a + b
    if residual is not None:
        yf = yf + residual.astype(jnp.float32)
    if act == "relu":
        yf = jnp.maximum(yf, 0.0)
    mean_out = mean_in * momentum + mean.astype(mean_in.dtype) * \
        (1 - momentum)
    var_out = var_in * momentum + var.astype(var_in.dtype) * \
        (1 - momentum)
    return {"Y": yf.astype(x.dtype), "ConvOut": conv_out,
            "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": mean, "SavedInvStd": inv}


def _fused_conv_bn_infer(ins, attrs, op):
    """Shapes without touching Pallas: conv shape arithmetic + [C]."""
    x = ins["Input"]
    w = ins["Filter"]
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    ph, pw = _pair(attrs.get("paddings", [0, 0]))
    n, h, wd, _ = x.shape
    kh, kw, _, co = w.shape
    ho = (h + 2 * ph - kh) // sh + 1
    wo = (wd + 2 * pw - kw) // sw + 1
    f32 = jnp.float32
    sds = jax.ShapeDtypeStruct
    return {"Y": sds((n, ho, wo, co), x.dtype),
            "ConvOut": sds((n, ho, wo, co), x.dtype),
            "MeanOut": sds((co,), ins["Mean"].dtype),
            "VarianceOut": sds((co,), ins["Variance"].dtype),
            "SavedMean": sds((co,), f32),
            "SavedInvStd": sds((co,), f32)}


register_op("fused_conv2d_bn_act", lower=_fused_conv_bn_lower,
            infer_shape=_fused_conv_bn_infer)


@register_op("fused_conv2d_bn_act_grad", grad_maker=None)
def _fused_conv_bn_grad(ctx, ins, attrs, op):
    """Backward from saved residuals only (no forward re-execution):
    relu mask from the reconstructed pre-activation, batch-stats BN
    gradient from (ConvOut, SavedMean, SavedInvStd), and the two conv
    gradients as NHWC/HWIO transposed convs via jax.vjp of the conv."""
    from paddle_tpu.kernels import conv_fused

    x, w = ins["Input"], ins["Filter"]
    scale = ins["Scale"]
    conv_out = ins["ConvOut"]
    mean, inv = ins["SavedMean"], ins["SavedInvStd"]
    residual = ins.get("Residual")
    dy = ins["Y@GRAD"]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    act = attrs.get("act", "")
    is_test = attrs.get("is_test", False)
    co = w.shape[3]
    red = (0, 1, 2)                                  # N, Ho, Wo

    a = scale.astype(jnp.float32) * inv
    b = ins["Bias"].astype(jnp.float32) - mean * a
    xc = conv_out.astype(jnp.float32) - mean
    xhat = xc * inv
    dyf = dy.astype(jnp.float32)
    if act == "relu":
        pre = conv_out.astype(jnp.float32) * a + b
        if residual is not None:
            pre = pre + residual.astype(jnp.float32)
        dyf = jnp.where(pre > 0, dyf, 0.0)
    dresidual = dyf
    dscale = (dyf * xhat).sum(axis=red)
    dbias = dyf.sum(axis=red)
    if is_test:
        dconv = dyf * a
    else:
        m = conv_out.size // co
        dconv = a * (dyf - dbias / m - xhat * dscale / m)

    cdt = jnp.bfloat16 if ctx.amp else jnp.result_type(x, w)

    def fwd_conv(xv, wv):
        # plain-dtype conv (bf16 under AMP): the vjp's transposed convs
        # then run in the same pinned NHWC/HWIO layout and dtype as the
        # forward; bf16 convs still accumulate f32 in the MXU
        return jax.lax.conv_general_dilated(
            xv.astype(cdt), wv.astype(cdt),
            window_strides=strides,
            padding=[(paddings[0], paddings[0]),
                     (paddings[1], paddings[1])],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    _, vjp = jax.vjp(fwd_conv, x, w)
    dx, dw = vjp(dconv.astype(cdt))
    out = {"Input@GRAD": dx.astype(x.dtype),
           "Filter@GRAD": dw.astype(w.dtype),
           "Scale@GRAD": dscale.astype(scale.dtype),
           "Bias@GRAD": dbias.astype(ins["Bias"].dtype)}
    if residual is not None:
        out["Residual@GRAD"] = dresidual.astype(residual.dtype)
    # Running stats are stop_gradient in real programs; when a harness
    # declares their grads anyway (the op sweep feeds them as plain
    # vars), the only dependency is the momentum blend into
    # MeanOut/VarianceOut.
    momentum = attrs.get("momentum", 0.9)
    for slot, gslot in (("Mean", "MeanOut@GRAD"),
                        ("Variance", "VarianceOut@GRAD")):
        if slot + "@GRAD" in op.outputs:
            src = ins.get(gslot) if gslot in ins.slots() else None
            ref = ins[slot]
            out[slot + "@GRAD"] = (src * momentum if src is not None
                                   else jnp.zeros_like(ref))
    return out


@register_op("lrn")
def _lrn(ctx, ins, attrs, op):
    """Local response norm across channels (reference lrn_op.cc)."""
    x = ins["X"]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs, op):
    """Lookahead row convolution (reference row_conv_op.cc), dense batch
    form: x [N, T, D], filter [future_ctx, D]."""
    x, f = ins["X"], ins["Filter"]
    ctx_len = f.shape[0]
    pads = [(0, 0), (0, ctx_len - 1), (0, 0)]
    xp = jnp.pad(x, pads)
    out = sum(xp[:, i:i + x.shape[1]] * f[i] for i in range(ctx_len))
    return {"Out": out}


@register_op("spp")
def _spp(ctx, ins, attrs, op):
    """Spatial pyramid pooling (reference spp_op.cc)."""
    x = ins["X"]
    levels = attrs.get("pyramid_height", 1)
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lvl in range(levels):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = kh * bins - h, kw * bins - w
        fill = -jnp.inf if ptype == "max" else 0.0
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, ph), (0, pw)),
                     constant_values=fill)
        x6 = xp.reshape(n, c, bins, kh, bins, kw)
        red = jnp.max if ptype == "max" else jnp.mean
        outs.append(red(x6, axis=(3, 5)).reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}
