"""Metric ops.

Parity: reference operators/accuracy_op.cc, auc_op.cc, precision_recall_op.cc,
edit_distance_op.cc (dense form), chunk_eval is host-side in metrics.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("accuracy", grad_maker=None)
def _accuracy(ctx, ins, attrs, op):
    """Top-k accuracy: Indices [N,k] from top_k, Label [N,1]."""
    indices = ins["Indices"]
    label = ins["Label"].reshape(-1, 1)
    correct = jnp.any(indices == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = indices.shape[0]
    acc = num_correct.astype(jnp.float32) / float(total)
    return {"Accuracy": acc.reshape((1,)),
            "Correct": num_correct.reshape((1,)),
            "Total": jnp.asarray([total], dtype=jnp.int32)}


@register_op("auc", grad_maker=None)
def _auc(ctx, ins, attrs, op):
    """Streaming AUC with histogram buckets (reference auc_op.cc).
    Inputs: Predict [N,2] (prob of class 1 in col 1), Label [N,1],
    stat vars TP/FP/TN/FN [num_thresholds]."""
    predict = ins["Predict"]
    label = ins["Label"].reshape(-1)
    num_t = attrs.get("num_thresholds", 200)
    pos_prob = predict[:, -1]
    thresholds = (jnp.arange(num_t, dtype=jnp.float32) + 1.0) / (num_t + 1.0)
    pred_pos = pos_prob[None, :] > thresholds[:, None]      # [T, N]
    is_pos = (label > 0)[None, :]
    tp = ins["TP"] + jnp.sum(pred_pos & is_pos, axis=1)
    fp = ins["FP"] + jnp.sum(pred_pos & ~is_pos, axis=1)
    tn = ins["TN"] + jnp.sum(~pred_pos & ~is_pos, axis=1)
    fn = ins["FN"] + jnp.sum(~pred_pos & is_pos, axis=1)
    tpr = tp.astype(jnp.float32) / jnp.maximum(
        (tp + fn).astype(jnp.float32), 1e-6)
    fpr = fp.astype(jnp.float32) / jnp.maximum(
        (fp + tn).astype(jnp.float32), 1e-6)
    # trapezoid over decreasing fpr
    auc = jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)
    return {"AUC": auc.reshape((1,)), "TPOut": tp, "FPOut": fp,
            "TNOut": tn, "FNOut": fn}


@register_op("precision_recall", grad_maker=None)
def _precision_recall(ctx, ins, attrs, op):
    """Multi-class precision/recall (reference precision_recall_op.cc)."""
    max_probs = ins["MaxProbs"].reshape(-1)
    indices = ins["Indices"].reshape(-1).astype(jnp.int32)
    labels = ins["Labels"].reshape(-1).astype(jnp.int32)
    cls = attrs.get("class_number")
    weights = (ins["Weights"].reshape(-1) if ins.has("Weights")
               else jnp.ones_like(max_probs))
    tp = jnp.zeros((cls,), jnp.float32).at[labels].add(
        jnp.where(indices == labels, weights, 0.0))
    pred_cnt = jnp.zeros((cls,), jnp.float32).at[indices].add(weights)
    true_cnt = jnp.zeros((cls,), jnp.float32).at[labels].add(weights)
    states = jnp.stack([tp, pred_cnt - tp, true_cnt - tp,
                        jnp.zeros_like(tp)], axis=1)
    if ins.has("StatesInfo"):
        states = states + ins["StatesInfo"]
    tp_a, fp_a, fn_a = states[:, 0], states[:, 1], states[:, 2]
    prec = tp_a / jnp.maximum(tp_a + fp_a, 1e-6)
    rec = tp_a / jnp.maximum(tp_a + fn_a, 1e-6)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    tp_s, fp_s, fn_s = tp_a.sum(), fp_a.sum(), fn_a.sum()
    mprec = tp_s / jnp.maximum(tp_s + fp_s, 1e-6)
    mrec = tp_s / jnp.maximum(tp_s + fn_s, 1e-6)
    micro = jnp.stack([mprec, mrec,
                       2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-6)])
    return {"BatchMetrics": jnp.concatenate([macro, micro]).reshape(1, 6),
            "AccumMetrics": jnp.concatenate([macro, micro]).reshape(1, 6),
            "AccumStatesInfo": states}
