"""In-program CSP ops: channels + go routines usable INSIDE a fluid
ProgramDesc.

Parity: reference framework/channel.h:33 (buffered/unbuffered Go-style
channels) and the ops operators/channel_create_op.cc,
channel_send_op.cc, channel_recv_op.cc, channel_close_op.cc, go_op.cc.
The host-level orchestration API lives in fluid/concurrency.py; these
ops make a *program* contain channel traffic — channel_create leaves a
Channel in the scope, send/recv are host ops reading/writing program
variables, and ``go`` launches its sub-block on a daemon thread through
a nested interpreted executor (go_op.cc:84 ExecuteOnThread).  ``select``
(reference operators/select_op.cc over framework/channel.h:33) is an
in-program op since ISSUE 8: the case list serializes as a string attr
('recv:<k>' / 'send:<k>' / 'default', <k> indexing the Channels input
slot), the chosen case's recv target / send value are program
variables, and the chosen case INDEX lands in the CaseIndex output so
downstream program logic (IfElse / conditional_block on CaseIndex)
plays the role of the reference's per-case sub-blocks — which its
superseded front-end never stabilized.
"""
from __future__ import annotations

import threading

import numpy as np

from paddle_tpu.core.registry import register_op


def _host(name):
    def deco(impl):
        register_op(name, lower=impl, host_op=True, grad_maker=None)
        return impl

    return deco


def _scope_set(scope, name, value):
    (scope.find_scope_of(name) or scope).set(name, value)


@_host("channel_create")
def _channel_create(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import Channel

    out = op.output("Out")[0]
    if scope.has_var(out) and isinstance(scope.find_var(out), Channel):
        return  # idempotent: re-running startup keeps the live channel
    _scope_set(scope, out,
               Channel(capacity=int(op.attr("capacity") or 0),
                       dtype=op.attr("data_type")))


def _value_of(name, scope, feed, env):
    if env is not None and name in env:
        return env[name]
    if feed and name in feed:
        return feed[name]
    return scope.find_var(name)


@_host("channel_send")
def _channel_send(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import Channel, ChannelClosed

    ch = scope.find_var(op.input("Channel")[0])
    if not isinstance(ch, Channel):
        raise RuntimeError("channel_send: %r is not a live channel"
                           % op.input("Channel")[0])
    val = _value_of(op.input("X")[0], scope, feed, env)
    ok = True
    try:
        ch.send(np.asarray(val))
    except ChannelClosed:
        ok = False  # reference: send on closed sets Status false
    status = op.outputs.get("Status")
    if status and status[0]:
        out = np.asarray([ok])
        _scope_set(scope, status[0], out)
        if env is not None:
            env[status[0]] = out


@_host("channel_recv")
def _channel_recv(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import channel_recv as _recv

    ch = scope.find_var(op.input("Channel")[0])
    val, ok = _recv(ch)
    out = op.output("Out")[0]
    if val is None:  # closed + drained: typed zero like the reference
        dt = np.dtype(getattr(ch, "dtype", None) or np.float32)
        val = np.zeros((1,), dt)
    val = np.asarray(val)
    _scope_set(scope, out, val)
    status = op.outputs.get("Status")
    if env is not None:
        env[out] = val
    if status and status[0]:
        st = np.asarray([ok])
        _scope_set(scope, status[0], st)
        if env is not None:
            env[status[0]] = st


@_host("channel_close")
def _channel_close(executor, op, scope, feed, env=None):
    scope.find_var(op.input("Channel")[0]).close()


@_host("select")
def _select(executor, op, scope, feed, env=None):
    """In-program multi-channel select (reference select_op.cc).

    inputs:  Channels — the live Channel vars the cases name;
             X        — send-case values, in send-case order.
    outputs: Out       — recv-case targets, in recv-case order;
             CaseIndex — [1] int32, the position of the case that ran.
    attrs:   cases   — ['recv:<k>' | 'send:<k>' | 'default', ...]
                       (<k> indexes the Channels slot);
             timeout — seconds; <= 0 blocks forever (Go semantics).

    Exactly one ready case executes (fluid.concurrency.Select does the
    polling); a recv on a closed+drained channel yields the typed zero
    channel_recv produces, so a select over a dead producer terminates
    instead of hanging."""
    from paddle_tpu.fluid.concurrency import Select

    chans = op.inputs.get("Channels", [])
    xs = op.inputs.get("X", [])
    outs = op.outputs.get("Out", [])
    case_specs = [str(c) for c in (op.attr("cases") or [])]
    timeout = float(op.attr("timeout") or 0.0)

    def _write(name, val):
        _scope_set(scope, name, val)
        if env is not None:
            env[name] = val

    cases = []
    ri = si = 0
    for ci, spec in enumerate(case_specs):
        kind, _, k = spec.partition(":")
        if kind == "default":
            cases.append(("default", lambda _ci=ci: _ci))
            continue
        ch = scope.find_var(chans[int(k)])
        if kind == "recv":
            out_name = outs[ri]
            ri += 1

            def on_recv(val, _ci=ci, _out=out_name, _ch=ch):
                if val is None:  # closed + drained: typed zero
                    dt = np.dtype(getattr(_ch, "dtype", None)
                                  or np.float32)
                    val = np.zeros((1,), dt)
                _write(_out, np.asarray(val))
                return _ci

            cases.append(("recv", ch, on_recv))
        elif kind == "send":
            val = _value_of(xs[si], scope, feed, env)
            si += 1
            cases.append(("send", ch, np.asarray(val),
                          lambda _ci=ci: _ci))
        else:
            raise ValueError("select: bad case spec %r" % spec)
    chosen = Select(cases).run(
        timeout=timeout if timeout > 0 else None)
    idx_out = op.outputs.get("CaseIndex")
    if idx_out and idx_out[0]:
        _write(idx_out[0], np.asarray([chosen], np.int32))


def _block_idx(attr_val):
    return attr_val.idx if hasattr(attr_val, "idx") else int(attr_val)


def _sole_sender_channels(program, block_id):
    """Channel names sent on from ``block_id``'s control-flow subtree
    (NOT descending into nested go ops — those routines own their own
    channels' lifecycle) and from nowhere else in the program."""
    def subtree(bid, acc):
        acc.add(bid)
        for sop in program.blocks[bid].ops:
            if sop.type != "go" and sop.has_attr("sub_block"):
                subtree(_block_idx(sop.attr("sub_block")), acc)
        return acc

    mine = subtree(block_id, set())
    sends = set()
    for bid in mine:
        for sop in program.blocks[bid].ops:
            if sop.type == "channel_send":
                sends.update(sop.input("Channel"))
    for bid, b in enumerate(program.blocks):
        if bid in mine:
            continue
        for sop in b.ops:
            if sop.type == "channel_send":
                sends.difference_update(sop.input("Channel"))
    return sends


@_host("go")
def _go(executor, op, scope, feed, env=None):
    """Launch the sub-block on a daemon thread (reference go_op.cc:84):
    the routine runs through a nested interpreted executor against a
    CHILD scope (kid-scope semantics) sharing the parent's channels and
    parameters; exceptions surface on join via scope._go_threads."""
    from paddle_tpu.core.executor_impl import ExecutorCore

    program = executor._current_program
    block_id = op.attr("sub_block")
    if hasattr(block_id, "idx"):
        block_id = block_id.idx
    sub = ExecutorCore(executor.place)
    child = scope.new_scope() if hasattr(scope, "new_scope") else scope
    captured_feed = dict(feed or {})
    # Capture the sub-block's external reads AT LAUNCH (reference go_op
    # captures its X inputs the same way): parent-block temporaries live
    # in the running step's env, not the scope, so a routine reading one
    # would otherwise see a missing var and die — deadlocking whoever
    # recvs on its channel.
    blk = program.blocks[int(block_id)]
    written = set()
    for sop in blk.ops:
        for n in sop.input_arg_names():
            if (n and n not in written and n not in captured_feed
                    and not scope.has_var(n)
                    and env is not None and n in env):
                captured_feed[n] = env[n]
        for n in sop.output_arg_names():
            if n:
                written.add(n)
    # Channels this routine is the SOLE sender on — closed if it dies,
    # so a main-block channel_recv blocked on this producer observes
    # ChannelClosed instead of hanging.  Recv-only channels, fan-in
    # channels with other senders (main block or sibling routines), and
    # channels fed by NESTED go routines (which install their own
    # handler when their go op runs) stay open: closing those would
    # poison live producers.
    chan_names = _sole_sender_channels(program, int(block_id))
    record = {"thread": None, "error": None}

    def run():
        try:
            sub.run(program, child, block_id=int(block_id),
                    feed=captured_feed)
        except Exception as e:  # surfaced on join()
            record["error"] = e
            for cn in chan_names:
                ch = scope.find_var(cn)
                if ch is not None and hasattr(ch, "close"):
                    try:
                        ch.close()
                    except Exception:
                        pass

    t = threading.Thread(target=run, daemon=True)
    record["thread"] = t
    if not hasattr(scope, "_go_threads"):
        scope._go_threads = []
    # Prune finished, error-free records so a training loop running a
    # main-block go op each step doesn't grow the list unboundedly
    # (errored records are kept for join_go_threads to surface).
    scope._go_threads = [
        r for r in scope._go_threads
        if r["error"] is not None or r["thread"].is_alive()]
    scope._go_threads.append(record)
    t.start()


def join_go_threads(scope, timeout=30.0):
    """Wait for every go routine launched under ``scope``; re-raise the
    first routine error (test/teardown helper — the reference leaks the
    thread, go_op.cc's documented FIXME)."""
    for rec in getattr(scope, "_go_threads", []):
        rec["thread"].join(timeout)
        if rec["error"] is not None:
            raise rec["error"]
    scope._go_threads = []
