"""In-program CSP ops: channels + go routines usable INSIDE a fluid
ProgramDesc.

Parity: reference framework/channel.h:33 (buffered/unbuffered Go-style
channels) and the ops operators/channel_create_op.cc,
channel_send_op.cc, channel_recv_op.cc, channel_close_op.cc, go_op.cc.
The host-level orchestration API lives in fluid/concurrency.py; these
ops make a *program* contain channel traffic — channel_create leaves a
Channel in the scope, send/recv are host ops reading/writing program
variables, and ``go`` launches its sub-block on a daemon thread through
a nested interpreted executor (go_op.cc:84 ExecuteOnThread).  ``select``
stays a host-level facility (fluid.concurrency.Select) — a data-driven
select inside a ProgramDesc would need per-case sub-blocks wired by the
front-end, which the superseded reference API never stabilized.
"""
from __future__ import annotations

import threading

import numpy as np

from paddle_tpu.core.registry import register_op


def _host(name):
    def deco(impl):
        register_op(name, lower=impl, host_op=True, grad_maker=None)
        return impl

    return deco


def _scope_set(scope, name, value):
    (scope.find_scope_of(name) or scope).set(name, value)


@_host("channel_create")
def _channel_create(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import Channel

    out = op.output("Out")[0]
    if scope.has_var(out) and isinstance(scope.find_var(out), Channel):
        return  # idempotent: re-running startup keeps the live channel
    _scope_set(scope, out,
               Channel(capacity=int(op.attr("capacity") or 0),
                       dtype=op.attr("data_type")))


def _value_of(name, scope, feed, env):
    if env is not None and name in env:
        return env[name]
    if feed and name in feed:
        return feed[name]
    return scope.find_var(name)


@_host("channel_send")
def _channel_send(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import Channel, ChannelClosed

    ch = scope.find_var(op.input("Channel")[0])
    if not isinstance(ch, Channel):
        raise RuntimeError("channel_send: %r is not a live channel"
                           % op.input("Channel")[0])
    val = _value_of(op.input("X")[0], scope, feed, env)
    ok = True
    try:
        ch.send(np.asarray(val))
    except ChannelClosed:
        ok = False  # reference: send on closed sets Status false
    status = op.outputs.get("Status")
    if status and status[0]:
        out = np.asarray([ok])
        _scope_set(scope, status[0], out)
        if env is not None:
            env[status[0]] = out


@_host("channel_recv")
def _channel_recv(executor, op, scope, feed, env=None):
    from paddle_tpu.fluid.concurrency import channel_recv as _recv

    ch = scope.find_var(op.input("Channel")[0])
    val, ok = _recv(ch)
    out = op.output("Out")[0]
    if val is None:  # closed + drained: typed zero like the reference
        dt = np.dtype(getattr(ch, "dtype", None) or np.float32)
        val = np.zeros((1,), dt)
    val = np.asarray(val)
    _scope_set(scope, out, val)
    status = op.outputs.get("Status")
    if env is not None:
        env[out] = val
    if status and status[0]:
        st = np.asarray([ok])
        _scope_set(scope, status[0], st)
        if env is not None:
            env[status[0]] = st


@_host("channel_close")
def _channel_close(executor, op, scope, feed, env=None):
    scope.find_var(op.input("Channel")[0]).close()


@_host("go")
def _go(executor, op, scope, feed, env=None):
    """Launch the sub-block on a daemon thread (reference go_op.cc:84):
    the routine runs through a nested interpreted executor against a
    CHILD scope (kid-scope semantics) sharing the parent's channels and
    parameters; exceptions surface on join via scope._go_threads."""
    from paddle_tpu.core.executor_impl import ExecutorCore

    program = executor._current_program
    block_id = op.attr("sub_block")
    if hasattr(block_id, "idx"):
        block_id = block_id.idx
    sub = ExecutorCore(executor.place)
    child = scope.new_scope() if hasattr(scope, "new_scope") else scope
    captured_feed = dict(feed or {})
    # Capture the sub-block's external reads AT LAUNCH (reference go_op
    # captures its X inputs the same way): parent-block temporaries live
    # in the running step's env, not the scope, so a routine reading one
    # would otherwise see a missing var and die — deadlocking whoever
    # recvs on its channel.
    blk = program.blocks[int(block_id)]
    written = set()
    for sop in blk.ops:
        for n in sop.input_arg_names():
            if (n and n not in written and n not in captured_feed
                    and not scope.has_var(n)
                    and env is not None and n in env):
                captured_feed[n] = env[n]
        for n in sop.output_arg_names():
            if n:
                written.add(n)
    record = {"thread": None, "error": None}

    def run():
        try:
            sub.run(program, child, block_id=int(block_id),
                    feed=captured_feed)
        except Exception as e:  # surfaced on join()
            record["error"] = e

    t = threading.Thread(target=run, daemon=True)
    record["thread"] = t
    if not hasattr(scope, "_go_threads"):
        scope._go_threads = []
    scope._go_threads.append(record)
    t.start()


def join_go_threads(scope, timeout=30.0):
    """Wait for every go routine launched under ``scope``; re-raise the
    first routine error (test/teardown helper — the reference leaks the
    thread, go_op.cc's documented FIXME)."""
    for rec in getattr(scope, "_go_threads", []):
        rec["thread"].join(timeout)
        if rec["error"] is not None:
            raise rec["error"]
    scope._go_threads = []
