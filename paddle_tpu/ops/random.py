"""Random ops, driven by the threaded PRNG key (see LoweringContext.next_key).

Parity: reference operators/uniform_random_op.cc, gaussian_random_op.cc,
uniform_random_batch_size_like_op.cc, gaussian_random_batch_size_like_op.cc,
sampling_id_op.cc — curand states replaced by counter-based jax PRNG, which
is reproducible across backends and under SPMD.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.types import proto_to_np_dtype, DataType


def _key(ctx, attrs):
    seed = attrs.get("seed", 0)
    return jax.random.PRNGKey(seed) if seed else ctx.next_key()


@register_op("uniform_random", stateful=True, grad_maker=None)
def _uniform_random(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    out = jax.random.uniform(
        _key(ctx, attrs), tuple(attrs.get("shape")), dtype=jnp.float32,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0))
    return {"Out": out.astype(dtype)}


@register_op("uniform_random_batch_size_like", stateful=True, grad_maker=None)
def _uniform_random_bsl(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    shape = list(attrs.get("shape"))
    shape[attrs.get("output_dim_idx", 0)] = \
        ins["Input"].shape[attrs.get("input_dim_idx", 0)]
    out = jax.random.uniform(_key(ctx, attrs), tuple(shape),
                             minval=attrs.get("min", -1.0),
                             maxval=attrs.get("max", 1.0))
    return {"Out": out.astype(dtype)}


@register_op("gaussian_random", stateful=True, grad_maker=None)
def _gaussian_random(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    out = jax.random.normal(_key(ctx, attrs), tuple(attrs.get("shape")))
    out = out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": out.astype(dtype)}


@register_op("gaussian_random_batch_size_like", stateful=True,
             grad_maker=None)
def _gaussian_random_bsl(ctx, ins, attrs, op):
    dtype = proto_to_np_dtype(attrs.get("dtype", DataType.FP32))
    shape = list(attrs.get("shape"))
    shape[attrs.get("output_dim_idx", 0)] = \
        ins["Input"].shape[attrs.get("input_dim_idx", 0)]
    out = jax.random.normal(_key(ctx, attrs), tuple(shape))
    out = out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": out.astype(dtype)}


@register_op("sampling_id", stateful=True, grad_maker=None)
def _sampling_id(ctx, ins, attrs, op):
    x = ins["X"]  # [N, D] probabilities
    idx = jax.random.categorical(_key(ctx, attrs), jnp.log(
        jnp.maximum(x, 1e-20)), axis=-1)
    return {"Out": idx.astype(jnp.int64)}
