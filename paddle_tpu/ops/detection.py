"""Detection (SSD) operators.

Parity: reference paddle/fluid/operators/detection/ — prior_box_op.cc,
iou_similarity_op.h, box_coder_op.h, bipartite_match_op.cc,
target_assign_op.h, mine_hard_examples_op.cc, multiclass_nms_op.cc,
detection_map_op.cc.

TPU-first split: the dense geometry (priors, IoU matrices, box
encode/decode, matching, mining, target assignment) is vectorized XLA —
the matching loop is a fori_loop over ground-truth boxes, everything
else is pure array math.  multiclass_nms and detection_map stay host
ops, exactly like the reference (both are CPU-only kernels there:
multiclass_nms_op.cc registers no CUDA kernel) — they sit at the tail
of an inference program, after the compiled core.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.io_ops import _host

_MISSING = object()


def _read_host(scope, feed, env, name, default=_MISSING):
    """env -> feed -> scope lookup shared by the host detection ops."""
    for src_ in (env, feed):
        if src_ is not None and name in src_:
            return np.asarray(src_[name])
    try:
        return np.asarray(scope.find_var(name))
    except KeyError:
        if default is not _MISSING:
            return default
        raise


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------

@register_op("prior_box", grad_maker=None)
def _prior_box(ctx, ins, attrs, op=None):
    """SSD prior (anchor) boxes for one feature map (reference
    prior_box_op.cc).  Input [N,C,H,W] fixes the grid; Image [N,C,Hi,Wi]
    fixes the normalization.  Boxes/Variances [H,W,P,4]."""
    feat = ins["Input"]
    image = ins["Image"]
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if attrs.get("flip", False):
                ars.append(1.0 / ar)
    variances = [float(v) for v in
                 attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / w
    step_h = float(attrs.get("step_h", 0.0)) or img_h / h
    offset = float(attrs.get("offset", 0.5))

    # box widths/heights per prior, reference order: for each min_size:
    # [square, per-aspect-ratio boxes, max_size geometric-mean square]
    ws, hs = [], []
    for k, ms in enumerate(min_sizes):
        ws.append(ms)
        hs.append(ms)
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            ws.append(ms * np.sqrt(ar))
            hs.append(ms / np.sqrt(ar))
        if max_sizes:
            sq = np.sqrt(ms * max_sizes[k])
            ws.append(sq)
            hs.append(sq)
    ws = jnp.asarray(ws, jnp.float32)[None, None, :]
    hs = jnp.asarray(hs, jnp.float32)[None, None, :]
    p = ws.shape[-1]

    cx = ((jnp.arange(w, dtype=jnp.float32) + offset) * step_w)[None, :,
                                                                None]
    cy = ((jnp.arange(h, dtype=jnp.float32) + offset) * step_h)[:, None,
                                                                None]
    xmin = (cx - ws / 2) / img_w
    xmax = (cx + ws / 2) / img_w
    ymin = (cy - hs / 2) / img_h
    ymax = (cy + hs / 2) / img_h
    boxes = jnp.stack(jnp.broadcast_arrays(xmin, ymin, xmax, ymax),
                      axis=-1)                        # [H,W,P,4]
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    return {"Boxes": boxes, "Variances": var}


# ---------------------------------------------------------------------------
# iou_similarity / box_coder
# ---------------------------------------------------------------------------

def _iou(a, b):
    """[..., Na, 4] x [Nb, 4] -> [..., Na, Nb] IoU (xmin,ymin,xmax,ymax)."""
    ax0, ay0, ax1, ay1 = [a[..., i] for i in range(4)]
    bx0, by0, bx1, by1 = [b[..., i] for i in range(4)]
    ix0 = jnp.maximum(ax0[..., :, None], bx0[..., None, :])
    iy0 = jnp.maximum(ay0[..., :, None], by0[..., None, :])
    ix1 = jnp.minimum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.minimum(ay1[..., :, None], by1[..., None, :])
    iw = jnp.maximum(ix1 - ix0, 0.0)
    ih = jnp.maximum(iy1 - iy0, 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax1 - ax0, 0.0) * jnp.maximum(ay1 - ay0, 0.0)
    area_b = jnp.maximum(bx1 - bx0, 0.0) * jnp.maximum(by1 - by0, 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", grad_maker=None, seq_aware=True)
def _iou_similarity(ctx, ins, attrs, op=None):
    """X [N,4] or [B,N,4] vs Y [M,4] -> IoU matrix (reference
    iou_similarity_op.h)."""
    out = _iou(ins["X"].astype(jnp.float32),
               ins["Y"].astype(jnp.float32))
    if op is not None:   # rows inherit X's ragged lengths
        for nm in (op.outputs.get("Out") or []):
            src = (op.inputs.get("X") or [None])[0]
            if nm and src:
                lens = ctx.seq_len_of(src)
                if lens is not None:
                    ctx.set_seq_len(nm, lens)
    return {"Out": out}


def _center_size(boxes):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + w / 2
    cy = boxes[..., 1] + h / 2
    return cx, cy, w, h


@register_op("box_coder", grad_maker=None)
def _box_coder(ctx, ins, attrs, op=None):
    """Encode/decode boxes against priors in center-size form (reference
    box_coder_op.h).  PriorBox [M,4], PriorBoxVar [M,4],
    TargetBox [N,M,4] (decode) or [N,4]/[M,4] gt (encode)."""
    prior = ins["PriorBox"].astype(jnp.float32)
    pvar = ins.get("PriorBoxVar")
    tb = ins["TargetBox"].astype(jnp.float32)
    code_type = attrs.get("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _center_size(prior)
    if pvar is None:
        pvar = jnp.ones_like(prior)
    v0, v1, v2, v3 = [pvar[..., i] for i in range(4)]
    if "decode" in code_type:
        # tb [N,M,4] offsets -> boxes
        tcx = tb[..., 0] * v0 * pw + pcx
        tcy = tb[..., 1] * v1 * ph + pcy
        tw = jnp.exp(tb[..., 2] * v2) * pw
        th = jnp.exp(tb[..., 3] * v3) * ph
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2, tcy + th / 2], axis=-1)
    else:
        # encode: tb [G,4] gt boxes vs every prior -> [G,M,4]
        gcx, gcy, gw, gh = _center_size(tb)
        tx = (gcx[..., :, None] - pcx[None, :]) / pw[None, :] / v0
        ty = (gcy[..., :, None] - pcy[None, :]) / ph[None, :] / v1
        tw = jnp.log(jnp.maximum(gw[..., :, None] / pw[None, :],
                                 1e-10)) / v2
        th = jnp.log(jnp.maximum(gh[..., :, None] / ph[None, :],
                                 1e-10)) / v3
        out = jnp.stack([tx, ty, tw, th], axis=-1)
    return {"OutputBox": out}


# ---------------------------------------------------------------------------
# bipartite_match / target_assign / mine_hard_examples
# ---------------------------------------------------------------------------

@register_op("bipartite_match", grad_maker=None, seq_aware=True)
def _bipartite_match(ctx, ins, attrs, op=None):
    """Greedy bipartite matching (reference bipartite_match_op.cc):
    repeatedly take the global max of DistMat [B,G,M] (gt x priors),
    binding that gt row and prior column; then (match_type
    'per_prediction') also match leftover priors whose best-gt overlap
    exceeds dist_threshold.  Outputs per-prior match [B,M] (gt index or
    -1) and the matched distance."""
    dist = ins["DistMat"].astype(jnp.float32)
    if dist.ndim == 2:
        dist = dist[None]
    b, g, m = dist.shape
    per_pred = attrs.get("match_type", "bipartite") == "per_prediction"
    thresh = float(attrs.get("dist_threshold", 0.5))
    glens = _rows_lens(ctx, op, "DistMat", b, g)

    row_valid0 = jnp.arange(g)[None, :] < glens[:, None]     # [B,G]

    def body(i, state):
        match, matched_dist, row_ok, col_ok = state
        masked = jnp.where(row_ok[:, :, None] & col_ok[:, None, :],
                           dist, -1.0)
        flat = masked.reshape(b, g * m)
        best = jnp.argmax(flat, axis=1)
        val = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        r, c = best // m, best % m
        ok = val > 0
        match = match.at[jnp.arange(b), c].set(
            jnp.where(ok, r, match[jnp.arange(b), c]))
        matched_dist = matched_dist.at[jnp.arange(b), c].set(
            jnp.where(ok, val, matched_dist[jnp.arange(b), c]))
        row_ok = row_ok.at[jnp.arange(b), r].set(
            jnp.where(ok, False, row_ok[jnp.arange(b), r]))
        col_ok = col_ok.at[jnp.arange(b), c].set(
            jnp.where(ok, False, col_ok[jnp.arange(b), c]))
        return match, matched_dist, row_ok, col_ok

    init = (jnp.full((b, m), -1, jnp.int32),
            jnp.zeros((b, m), jnp.float32),
            row_valid0, jnp.ones((b, m), bool))
    match, matched_dist, _, col_ok = jax.lax.fori_loop(0, g, body, init)

    if per_pred:
        # unmatched priors take their best gt if IoU > threshold
        masked = jnp.where(row_valid0[:, :, None], dist, -1.0)
        best_g = jnp.argmax(masked, axis=1).astype(jnp.int32)   # [B,M]
        best_v = jnp.max(masked, axis=1)
        extra = col_ok & (best_v > thresh)
        match = jnp.where(extra, best_g, match)
        matched_dist = jnp.where(extra, best_v, matched_dist)
    return {"ColToRowMatchIndices": match,
            "ColToRowMatchDist": matched_dist}


def _rows_lens(ctx, op, slot, b, g):
    names = (op.inputs.get(slot) or []) if op is not None else []
    lens = ctx.seq_len_of(names[0]) if names and names[0] else None
    if lens is None:
        return jnp.full((b,), g, jnp.int32)
    return lens.astype(jnp.int32)


@register_op("target_assign", grad_maker=None, seq_aware=True)
def _target_assign(ctx, ins, attrs, op=None):
    """Gather per-prior targets by match indices (reference
    target_assign_op.h): X [B,G,K] per-gt values, MatchIndices [B,M]
    (-1 = background).  Out [B,M,K]; OutWeight [B,M,1] = 1 where
    matched (or where NegIndices marks a negative)."""
    x = ins["X"]
    match = ins["MatchIndices"].astype(jnp.int32)
    mismatch_value = attrs.get("mismatch_value", 0)
    b, m = match.shape
    idx = jnp.clip(match, 0, x.shape[1] - 1)
    out = jnp.take_along_axis(
        x, idx[:, :, None].astype(jnp.int32), axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out,
                    jnp.asarray(mismatch_value, x.dtype))
    wt = matched.astype(jnp.float32)
    neg = ins.get("NegIndices")
    if neg is not None:
        # NegIndices [B, M] 0/1 mask of mined negatives
        wt = jnp.maximum(wt, neg.astype(jnp.float32)[:, :, None])
    return {"Out": out, "OutWeight": wt}


@register_op("mine_hard_examples", grad_maker=None)
def _mine_hard_examples(ctx, ins, attrs, op=None):
    """Online hard negative mining (reference mine_hard_examples_op.cc,
    max_negative mode): rank unmatched priors by ClsLoss and keep the
    top neg_pos_ratio * #positives per image.  Outputs a [B,M] 0/1
    negative mask (the reference's NegIndices LoD list, densified)."""
    mining = attrs.get("mining_type", "max_negative")
    if mining != "max_negative":
        raise NotImplementedError(
            "mine_hard_examples: mining_type %r is not implemented "
            "(only 'max_negative'); reference hard_example mode caps by "
            "sample_size, which max_negative honors too" % mining)
    cls_loss = ins["ClsLoss"].astype(jnp.float32)      # [B,M]
    match = ins["MatchIndices"].astype(jnp.int32)      # [B,M]
    if cls_loss.ndim == 3:
        cls_loss = cls_loss[..., 0]
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    sample_size = int(attrs.get("sample_size", -1))
    b, m = match.shape
    positive = match >= 0
    n_pos = positive.sum(axis=1)
    n_neg = jnp.minimum((n_pos * ratio).astype(jnp.int32),
                        m - n_pos)
    if sample_size > 0:
        n_neg = jnp.minimum(n_neg, sample_size)
    neg_loss = jnp.where(positive, -jnp.inf, cls_loss)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)                  # rank per prior
    neg_mask = (rank < n_neg[:, None]) & ~positive & \
        jnp.isfinite(neg_loss)
    return {"NegIndices": neg_mask.astype(jnp.int32),
            "UpdatedMatchIndices": match}


# ---------------------------------------------------------------------------
# multiclass_nms / detection_map (host, like the reference CPU kernels)
# ---------------------------------------------------------------------------

def _nms_one_class(boxes, scores, score_threshold, nms_threshold, top_k,
                   eta):
    idx = np.argsort(-scores)
    idx = idx[scores[idx] > score_threshold]
    if top_k > -1:
        idx = idx[:top_k]
    keep = []
    adaptive = nms_threshold
    while idx.size:
        i = idx[0]
        keep.append(i)
        if idx.size == 1:
            break
        rest = idx[1:]
        xx0 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy0 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx1 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy1 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = np.maximum(xx1 - xx0, 0) * np.maximum(yy1 - yy0, 0)
        area_i = max((boxes[i, 2] - boxes[i, 0]) *
                     (boxes[i, 3] - boxes[i, 1]), 0)
        area_r = np.maximum(boxes[rest, 2] - boxes[rest, 0], 0) * \
            np.maximum(boxes[rest, 3] - boxes[rest, 1], 0)
        union = area_i + area_r - inter
        iou = np.where(union > 0, inter / union, 0)
        idx = rest[iou <= adaptive]
        if eta < 1 and adaptive > 0.5:
            adaptive *= eta
    return keep


@_host("multiclass_nms")
def _multiclass_nms(executor, op, scope, feed, env=None):
    """Per-class NMS + cross-class keep_top_k (reference
    multiclass_nms_op.cc — a CPU-only kernel there too).  BBoxes
    [B,M,4] decoded boxes, Scores [B,C,M].  Out: [No,6] rows
    [label, score, xmin, ymin, xmax, ymax]; '@ROWS' var holds the
    per-image detection counts (the LoD analog)."""
    def read(name):
        return _read_host(scope, feed, env, name)

    bboxes = read(op.input("BBoxes")[0])
    scores = read(op.input("Scores")[0])
    bg = int(op.attr("background_label", 0))
    score_th = float(op.attr("score_threshold", 0.01))
    nms_th = float(op.attr("nms_threshold", 0.3))
    nms_top_k = int(op.attr("nms_top_k", 400))
    keep_top_k = int(op.attr("keep_top_k", 200))
    eta = float(op.attr("nms_eta", 1.0))

    all_rows = []
    counts = []
    for b in range(bboxes.shape[0]):
        dets = []
        for c in range(scores.shape[1]):
            if c == bg:
                continue
            keep = _nms_one_class(bboxes[b], scores[b, c], score_th,
                                  nms_th, nms_top_k, eta)
            for i in keep:
                dets.append((float(scores[b, c, i]), c, i))
        dets.sort(reverse=True)
        if keep_top_k > -1:
            dets = dets[:keep_top_k]
        counts.append(len(dets))
        for s, c, i in dets:
            all_rows.append([float(c), s] + [float(v)
                                            for v in bboxes[b, i]])
    out = (np.asarray(all_rows, np.float32) if all_rows
           else np.zeros((0, 6), np.float32))

    out_name = op.output("Out")[0]
    for name, val in ((out_name, out),
                      (out_name + "@ROWS",
                       np.asarray(counts, np.int64))):
        if env is not None:
            env[name] = val
        (scope.find_scope_of(name) or scope).set(name, val)


@register_op("gather_encoded_target", grad_maker=None)
def _gather_encoded_target(ctx, ins, attrs, op=None):
    """Per-prior localization target: Out[b,m] = Encoded[b, match[b,m], m]
    (the gather the reference folds into target_assign's SSD call path;
    split out here because Encoded carries a per-column prior axis)."""
    enc = ins["Encoded"]                  # [B,G,M,4]
    match = ins["MatchIndices"].astype(jnp.int32)   # [B,M]
    b, g, m, k = enc.shape
    idx = jnp.clip(match, 0, g - 1)
    rows = jnp.arange(b)[:, None]
    cols = jnp.arange(m)[None, :]
    out = enc[rows, idx, cols]            # [B,M,4]
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out, 0.0)
    return {"Out": out, "OutWeight": matched.astype(jnp.float32)}


@_host("detection_map")
def _detection_map(executor, op, scope, feed, env=None):
    """mAP metric (reference detection_map_op.cc, CPU-only there too).
    DetectRes: [No, 6] rows [label, score, x0, y0, x1, y1] with
    '<name>@ROWS' per-image counts (multiclass_nms's output layout).
    Label: padded [B, G, 5] rows [label, x0, y0, x1, y1] with '@LEN'.
    Outputs MAP [1] (11point or integral ap_version).  The reference's
    cross-batch accumulator inputs are subsumed by metrics.DetectionMAP
    accumulating host-side."""
    def read(name, **kw):
        return _read_host(scope, feed, env, name, **kw)

    det_name = op.input("DetectRes")[0]
    det = read(det_name)
    label = read(op.input("Label")[0])
    rows = read(det_name + "@ROWS", default=None)
    if rows is None:
        if label.shape[0] != 1:
            raise ValueError(
                "detection_map: %r has no '@ROWS' sidecar but the "
                "label batch has %d images — per-image detection "
                "counts are required (multiclass_nms emits them)" %
                (det_name, label.shape[0]))
        rows = np.asarray([det.shape[0]])
    glens = read(op.input("Label")[0] + "@LEN",
                 default=np.full((label.shape[0],), label.shape[1]))
    class_num = int(op.attr("class_num"))
    background = int(op.attr("background_label", 0))
    thresh = float(op.attr("overlap_threshold", 0.5))
    ap_version = op.attr("ap_version", "integral")

    # split detections per image
    offs = np.concatenate([[0], np.cumsum(rows)])
    n_imgs = len(rows)
    # collect (score, is_tp) per class + gt count per class
    scored = {c: [] for c in range(class_num)}
    n_gt = np.zeros(class_num, np.int64)
    for b in range(n_imgs):
        dets_b = det[offs[b]:offs[b + 1]]
        gts_b = label[b, :int(glens[b])]
        for g in gts_b:
            if int(g[0]) != background:
                n_gt[int(g[0])] += 1
        used = np.zeros(len(gts_b), bool)
        # match detections best-first within their class
        for row in dets_b[np.argsort(-dets_b[:, 1])]:
            c = int(row[0])
            if c == background:
                continue
            best, best_iou = -1, thresh
            for gi, g in enumerate(gts_b):
                if used[gi] or int(g[0]) != c:
                    continue
                ix0 = max(row[2], g[1]); iy0 = max(row[3], g[2])
                ix1 = min(row[4], g[3]); iy1 = min(row[5], g[4])
                inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
                ua = ((row[4] - row[2]) * (row[5] - row[3]) +
                      (g[3] - g[1]) * (g[4] - g[2]) - inter)
                iou = inter / ua if ua > 0 else 0.0
                if iou >= best_iou:
                    best, best_iou = gi, iou
            if best >= 0:
                used[best] = True
                scored[c].append((row[1], 1))
            else:
                scored[c].append((row[1], 0))

    aps = []
    for c in range(class_num):
        if c == background or n_gt[c] == 0:
            continue
        hits = sorted(scored[c], reverse=True)
        tp = np.cumsum([h[1] for h in hits]) if hits else np.zeros(0)
        fp = np.cumsum([1 - h[1] for h in hits]) if hits else \
            np.zeros(0)
        recall = tp / n_gt[c] if len(tp) else np.zeros(0)
        precision = tp / np.maximum(tp + fp, 1e-9) if len(tp) else \
            np.zeros(0)
        if ap_version == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t].max() if \
                    (recall >= t).any() else 0.0
                ap += p / 11.0
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for r, p in zip(recall, precision):
                ap += (r - prev_r) * p
                prev_r = r
        aps.append(ap)
    m = float(np.mean(aps)) if aps else 0.0

    out_name = op.output("MAP")[0]
    val = np.asarray([m], np.float32)
    if env is not None:
        env[out_name] = val
    (scope.find_scope_of(out_name) or scope).set(out_name, val)
