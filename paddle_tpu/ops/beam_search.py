"""Beam-search ops.

Parity: reference operators/beam_search_op.cc (per-step candidate
selection) and beam_search_decode_op.cc (end-of-loop backtracking), as
driven by the book machine_translation decode program: per step the
model computes topk candidate ids + ACCUMULATED log scores, and
``beam_search`` keeps the best ``beam_size`` beams per source sentence.

TPU-native redesign: the reference walks LoD levels per sentence on the
CPU and encodes beam ancestry in the output LoD
(beam_search_op.h:94 BeamSearch, SelectTopBeamSizeItems); here the step
is one batched top-k over ``[N, B*K]`` on device (MXU-adjacent, no
host sync inside the decode loop) and ancestry is an explicit
``parent_idx`` output ([N*B] gather indices).  ``beam_search_decode``
backtracks the stacked per-step outputs on the host once, after the
loop — the only host work in the whole decode.
"""
from __future__ import annotations

import jax.lax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op

NEG_INF = -1e9


@register_op("beam_search", grad_maker=None)
def _beam_search(ctx, ins, attrs, op=None):
    """One step of beam growth.

    Inputs (shapes; N sentences x B beams flattened on dim 0):
      pre_ids     [N*B, 1] int  — previous step's chosen token per beam
      pre_scores  [N*B, 1] f32  — accumulated log-prob per beam
      ids         [N*B, K] int  — candidate token ids (topk of the step)
      scores      [N*B, K] f32  — accumulated log-prob of each candidate
    Attrs: beam_size, end_id.
    Outputs:
      selected_ids     [N*B, 1]   selected_scores [N*B, 1]
      parent_idx       [N*B] int32 — which flat beam each winner grew from
    A finished beam (pre_id == end_id) competes with its frozen score and
    re-emits end_id (reference PruneEndBeams keeps it out of growth).
    """
    pre_ids = ins["pre_ids"].reshape(-1)
    pre_scores = ins["pre_scores"].reshape(-1).astype(jnp.float32)
    ids = ins["ids"]
    scores = ins["scores"].astype(jnp.float32)
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])

    nb, k = scores.shape
    n = nb // beam_size
    finished = pre_ids == end_id  # [NB]

    # finished beams offer exactly one candidate: (end_id, frozen score)
    cand_scores = jnp.where(finished[:, None], NEG_INF, scores)
    frozen = jnp.where(
        (jnp.arange(k) == 0)[None, :] & finished[:, None],
        pre_scores[:, None], NEG_INF)
    cand_scores = jnp.maximum(cand_scores, frozen)
    cand_ids = jnp.where(finished[:, None], end_id, ids)

    flat_scores = cand_scores.reshape(n, beam_size * k)
    flat_ids = cand_ids.reshape(n, beam_size * k)
    top_scores, top_pos = jax.lax.top_k(flat_scores, beam_size)
    sel_scores = top_scores.reshape(nb, 1)
    sel_ids = jnp.take_along_axis(flat_ids, top_pos, axis=1).reshape(nb, 1)
    beam_of = top_pos // k                            # [N, B] local beam
    parent = (beam_of + jnp.arange(n)[:, None] * beam_size).reshape(nb)
    return {"selected_ids": sel_ids.astype(pre_ids.dtype),
            "selected_scores": sel_scores,
            "parent_idx": parent.astype(jnp.int32)}


@register_op("beam_search_decode", grad_maker=None)
def _beam_search_decode(ctx, ins, attrs, op=None):
    """Backtrack stacked per-step (ids, scores, parents) into full beams.

    Inputs (TensorArrays written by the decode loop):
      Ids      buffer [cap, N*B, 1] of selected_ids
      Scores   buffer [cap, N*B, 1] of selected_scores
      Parents  buffer [cap, N*B]    of parent_idx
    Attrs: beam_size, end_id.
    Outputs:
      SentenceIds    [N, B, cap] int (end_id padded), best beam first
      SentenceScores [N, B]      f32 accumulated log-prob

    Reference beam_search_decode_op.cc walks the per-step LoDs on the CPU;
    here ancestry is explicit so the backtrack is one reverse lax.scan on
    device — the decode program stays a single XLA computation.
    """
    ids_arr = ins["Ids"]
    sc_arr = ins["Scores"]
    par_arr = ins["Parents"]
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])

    cap = ids_arr.buffer.shape[0]
    nb = int(np.prod(ids_arr.buffer.shape[1:]))
    n = nb // beam_size
    buf_ids = ids_arr.buffer.reshape(cap, nb)
    buf_sc = sc_arr.buffer.reshape(cap, nb).astype(jnp.float32)
    buf_par = par_arr.buffer.reshape(cap, nb)
    size = jnp.reshape(ids_arr.size, ()).astype(jnp.int32)

    last = jnp.clip(size - 1, 0, cap - 1)
    final_scores = jnp.take(buf_sc, last, axis=0)       # [NB]

    def step(cur, t):
        valid = t < size
        out = jnp.where(valid, jnp.take(buf_ids, t, axis=0)[cur], end_id)
        nxt = jnp.where(valid,
                        jnp.take(buf_par, t, axis=0)[cur].astype(cur.dtype),
                        cur)
        return nxt, out

    _, outs = jax.lax.scan(step, jnp.arange(nb), jnp.arange(cap),
                           reverse=True)                # outs [cap, NB]
    sent = jnp.moveaxis(outs, 0, 1).reshape(n, beam_size, cap)
    scores = final_scores.reshape(n, beam_size)
    order = jnp.argsort(-scores, axis=1)
    sent = jnp.take_along_axis(sent, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return {"SentenceIds": sent.astype(buf_ids.dtype),
            "SentenceScores": scores}
