"""SPMD annotation ops.

TPU-native additions with no per-op reference analog: the reference placed
whole tensors on devices and moved data with NCCL op handles
(details/*_op_handle.cc); here placement is expressed as mesh-axis
annotations inside the compiled program and GSPMD inserts the collectives.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.registry import register_op


@register_op("sharding_constraint")
def _sharding_constraint_lower(ctx, ins, attrs, op=None):
    x = ins["X"]
    if ctx.mesh is None:
        return {"Out": x}
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = tuple(a if a and a in ctx.mesh.axis_names else None
                 for a in attrs.get("spec", ()))
    spec = spec[:x.ndim]
    sharding = NamedSharding(ctx.mesh, P(*spec))
    return {"Out": jax.lax.with_sharding_constraint(x, sharding)}


def _axis_or_none(mesh, name):
    return name if (name and mesh is not None
                    and name in mesh.axis_names
                    and dict(mesh.shape)[name] > 1) else None


@register_op("ring_attention", no_vjp_outputs=("LSE",))
def _ring_attention_lower(ctx, ins, attrs, op=None):
    """Scaled-dot-product attention, sequence-parallel when compiled under
    a mesh with the configured sp axis; dense otherwise.  Q/K/V: [B,H,S,D].
    """
    import jax.numpy as jnp

    q, k, v = ins["Q"], ins["K"], ins["V"]
    causal = bool(attrs.get("causal", True))
    # scale attr: explicit softmax scale (the attention transpiler sets
    # it when fusing a plain matmul-softmax-matmul chain whose scaling
    # differs from the 1/sqrt(D) default).  ABSENT means default; a
    # present value — including 0.0 — is used verbatim, or the fusion
    # pass would not be semantics-preserving.
    scale = attrs["scale"] if "scale" in attrs else None
    sp_axis = _axis_or_none(ctx.mesh, attrs.get("sp_axis", "sp"))
    if sp_axis is not None:
        from paddle_tpu.parallel.ring import (ring_attention,
                                              ring_attention_fwd_lse)
        axes = dict(
            batch_axis=_axis_or_none(ctx.mesh, attrs.get("batch_axis", "dp")),
            head_axis=_axis_or_none(ctx.mesh, attrs.get("head_axis", "tp")))
        if op is not None and op.outputs.get("LSE"):
            # saved-LSE contract (ISSUE 15): the ring forward's REAL
            # per-position log-sum-exp rides as the op output, so the
            # grad op replays the reverse-direction ring from it — no
            # forward re-execution inside a generic vjp (MIGRATION.md)
            out, lse = ring_attention_fwd_lse(
                q, k, v, ctx.mesh, axis_name=sp_axis, causal=causal,
                scale=scale, **axes)
            return {"Out": out, "LSE": lse}
        return {"Out": ring_attention(
            q, k, v, ctx.mesh, axis_name=sp_axis, causal=causal,
            scale=scale, **axes)}
    # dense (single-chip) path: the Pallas flash kernel on TPU (1.7x
    # XLA at T=8192, measured), same-math XLA fallback elsewhere.
    # Under a mesh the mesh's devices decide the platform (the default-
    # device pin is absent and devices()[0] may be an unrelated TPU).
    from paddle_tpu.kernels import flash_attention
    from paddle_tpu.kernels.flash_attention import flash_attention_fwd_lse
    not_tpu = (ctx.mesh is not None and
               ctx.mesh.devices.flat[0].platform != "tpu")
    if op is not None and op.outputs.get("LSE"):
        # residual form: lse rides as an op output so the grad op runs
        # the flash backward directly instead of re-executing the
        # forward inside its vjp (see ring_attention_grad)
        out, lse = flash_attention_fwd_lse(
            q, k, v, scale=scale, causal=causal, force_xla=not_tpu)
        return {"Out": out, "LSE": lse}
    return {"Out": flash_attention(q, k, v, scale=scale, causal=causal,
                                   force_xla=not_tpu)}


@register_op("moe_ffn")
def _moe_ffn_lower(ctx, ins, attrs, op=None):
    """Top-1 mixture-of-experts FFN; expert-parallel over the ep axis when
    compiled under a mesh, dense-dispatch otherwise.  X: [T, D] or
    [B, S, D] (flattened internally)."""
    import jax.numpy as jnp

    x, wg = ins["X"], ins["RouterW"]
    w1, w2 = ins["W1"], ins["W2"]
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    ep_axis = _axis_or_none(ctx.mesh, attrs.get("ep_axis", "ep"))
    if ep_axis is not None:
        from paddle_tpu.parallel.moe import moe_ffn
        out = moe_ffn(x2, wg, w1, w2, ctx.mesh, axis_name=ep_axis,
                      dp_axis=_axis_or_none(ctx.mesh,
                                            attrs.get("dp_axis", "dp")),
                      capacity_factor=float(
                          attrs.get("capacity_factor", 2.0)))
    else:
        gates = jax.nn.softmax(x2 @ wg, axis=-1)
        expert = jnp.argmax(gates, axis=-1)
        gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]
        h = jax.nn.relu(jnp.einsum("td,edf->tef", x2, w1))
        y = jnp.einsum("tef,efd->ted", h, w2)
        out = y[jnp.arange(x2.shape[0]), expert] * gate[:, None]
        # dense dispatch has no capacity drop; routing stats still feed
        # the registry so the --moe rollup works off-mesh too
        from paddle_tpu.parallel.moe import emit_router_stats
        emit_router_stats(gates, expert,
                          jnp.ones(expert.shape, jnp.bool_))
    return {"Out": out.reshape(shape)}


@register_op("ring_attention_grad", grad_maker=None)
def _ring_attention_grad_lower(ctx, ins, attrs, op=None):
    """Flash backward from the forward's saved lse (no forward
    re-execution): the reverse-direction ring under sp, the two flash
    backward kernels dense.  Falls back to the generic vjp — which
    re-runs the forward — only when the residual is absent (ops built
    without the LSE output, e.g. the inference transpiler's fused
    chains)."""
    from paddle_tpu.core import lowering as core_lowering
    from paddle_tpu.kernels.flash_attention import flash_attention_bwd

    sp_axis = _axis_or_none(ctx.mesh, attrs.get("sp_axis", "sp"))
    lse = ins.get("LSE")
    if lse is None:
        return core_lowering.generic_grad_lower(ctx, ins, attrs, op)
    if sp_axis is not None:
        from paddle_tpu.parallel.ring import ring_attention_bwd
        dq, dk, dv = ring_attention_bwd(
            ins["Q"], ins["K"], ins["V"], ins["Out"], lse,
            ins["Out@GRAD"], ctx.mesh, axis_name=sp_axis,
            causal=bool(attrs.get("causal", True)),
            scale=attrs["scale"] if "scale" in attrs else None,
            batch_axis=_axis_or_none(ctx.mesh,
                                     attrs.get("batch_axis", "dp")),
            head_axis=_axis_or_none(ctx.mesh,
                                    attrs.get("head_axis", "tp")))
        return {"Q@GRAD": dq, "K@GRAD": dk, "V@GRAD": dv}
    not_tpu = (ctx.mesh is not None and
               ctx.mesh.devices.flat[0].platform != "tpu")
    dq, dk, dv = flash_attention_bwd(
        ins["Q"], ins["K"], ins["V"], ins["Out"], lse, ins["Out@GRAD"],
        scale=attrs["scale"] if "scale" in attrs else None,
        causal=bool(attrs.get("causal", True)), force_xla=not_tpu)
    return {"Q@GRAD": dq, "K@GRAD": dk, "V@GRAD": dv}
