"""Sequence (LoD) operators on the padded-batch representation.

Parity: reference operators/sequence_*_op.cc, lstm_op.cc, gru_op.cc.  The
reference stores ragged batches packed ([sum_T, D] + offset table) and
walks them with hand-written CPU/CUDA kernels; here a ragged batch is a
padded dense [N, T, D] block plus a device-side length vector
('<name>@LEN', see core/executor_impl._prepare_lod_feeds) so every op is
a static-shape masked XLA computation — recurrences are lax.scan over the
time axis (one compiled loop on the MXU instead of per-step kernel
launches, SURVEY §5.7).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
    "": lambda x: x,
}


def _act(name):
    return _ACTS[name]


def _lens_of(ctx, op, slot):
    if op is None:
        return None
    names = op.inputs.get(slot) or []
    if names and names[0]:
        return ctx.seq_len_of(names[0])
    return None


def _mask(lens, n, t, dtype=jnp.float32):
    """[N, T] 1/0 validity mask; all-ones when lens is None."""
    if lens is None:
        return jnp.ones((n, t), dtype)
    return (jnp.arange(t)[None, :] < lens[:, None]).astype(dtype)


def _reverse_time(x, lens):
    """Reverse each sequence within its own length (padding stays put) —
    reference is_reverse semantics for packed batches."""
    if lens is None:
        return jnp.flip(x, axis=1)
    t = x.shape[1]
    tt = jnp.arange(t)[None, :]
    idx = jnp.where(tt < lens[:, None], lens[:, None] - 1 - tt, tt)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, jnp.broadcast_to(idx, x.shape), axis=1)


# ---------------------------------------------------------------------------
# Recurrent ops
# ---------------------------------------------------------------------------

def _lstm_scan(x, w, b, lens, attrs, h0=None, c0=None, w_proj=None,
               proj_act=None):
    """Shared masked-LSTM recurrence for `lstm` and `lstmp`.

    With ``w_proj`` the recurrent state is the projection
    r = proj_act(h @ w_proj) (reference lstmp_op.cc) and the sequence
    output is [N,T,P]; otherwise it is the hidden state [N,T,H].
    """
    n, t, h4 = x.shape
    h = h4 // 4
    rev = bool(attrs.get("is_reverse", False))
    peep = bool(attrs.get("use_peepholes", True)) and b is not None \
        and b.shape[-1] == 7 * h
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))

    if b is not None:
        x = x + b[..., : 4 * h].reshape(1, 1, 4 * h)
    if peep:
        ck_i, ck_f, ck_o = jnp.split(b[0, 4 * h:], 3)
    if rev:
        x = _reverse_time(x, lens)

    mask = _mask(lens, n, t, x.dtype)
    r_dim = w_proj.shape[1] if w_proj is not None else h
    r_prev = h0 if h0 is not None else jnp.zeros((n, r_dim), x.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((n, h), x.dtype)

    def step(carry, xm):
        r_prev, c_prev = carry
        xt, mt = xm                       # [N,4H], [N]
        g = xt + r_prev @ w
        cand, gi, gf, go = jnp.split(g, 4, axis=-1)
        if peep:
            gi = gi + c_prev * ck_i
            gf = gf + c_prev * ck_f
        i = gate_act(gi)
        f = gate_act(gf)
        c = f * c_prev + i * cand_act(cand)
        if peep:
            go = go + c * ck_o
        o = gate_act(go)
        hh = o * cell_act(c)
        r = proj_act(hh @ w_proj) if w_proj is not None else hh
        mt = mt[:, None]
        c = mt * c + (1 - mt) * c_prev
        r_masked = mt * r
        r_keep = r_masked + (1 - mt) * r_prev
        return (r_keep, c), (r_masked, c)

    (_, _), (rs, cs) = jax.lax.scan(
        step, (r_prev, c_prev),
        (jnp.swapaxes(x, 0, 1), jnp.swapaxes(mask, 0, 1)))
    out = jnp.swapaxes(rs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if rev:
        out = _reverse_time(out, lens)
        cell = _reverse_time(cell, lens)
    return out, cell


@register_op("lstm", no_vjp_outputs=("BatchGate", "BatchCellPreAct"))
def _lstm(ctx, ins, attrs, op=None):
    """LSTM over a padded batch (reference lstm_op.cc:180 equations).

    Input [N,T,4H] (pre-projected x), Weight [H,4H] with gate columns
    ordered [c~, i, f, o] (reference math/detail/lstm_kernel.h memory
    layout), Bias [1,4H] or [1,7H] with peephole vectors checkI/checkF/
    checkO appended (use_peepholes).  Outputs Hidden/Cell [N,T,H].
    """
    hidden, cell = _lstm_scan(
        ins["Input"], ins["Weight"], ins.get("Bias"),
        _lens_of(ctx, op, "Input"), attrs,
        h0=ins.get("H0"), c0=ins.get("C0"))
    return {"Hidden": hidden, "Cell": cell}


@register_op("gru")
def _gru(ctx, ins, attrs, op=None):
    """GRU over a padded batch (reference gru_op.cc:129-142):
    u = act_gate(x_u + h W_u), r = act_gate(x_r + h W_r),
    h~ = act(x_c + (r*h) W_c), h_t = (1-u)*h_{t-1} + u*h~.
    Input [N,T,3D]; Weight [D,3D] = [W_u | W_r | W_c]; Bias [1,3D]."""
    x = ins["Input"]
    w = ins["Weight"]
    b = ins.get("Bias")
    h0 = ins.get("H0")
    lens = _lens_of(ctx, op, "Input")
    n, t, d3 = x.shape
    d = d3 // 3
    rev = bool(attrs.get("is_reverse", False))
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    act = _act(attrs.get("activation", "tanh"))
    wu, wr, wc = w[:, :d], w[:, d: 2 * d], w[:, 2 * d:]

    if b is not None:
        x = x + b.reshape(1, 1, d3)
    if rev:
        x = _reverse_time(x, lens)
    mask = _mask(lens, n, t, x.dtype)
    h_prev = h0 if h0 is not None else jnp.zeros((n, d), x.dtype)

    def step(h_prev, xm):
        xt, mt = xm
        xu, xr, xc = jnp.split(xt, 3, axis=-1)
        u = gate_act(xu + h_prev @ wu)
        r = gate_act(xr + h_prev @ wr)
        cand = act(xc + (r * h_prev) @ wc)
        hh = (1 - u) * h_prev + u * cand
        mt = mt[:, None]
        h_keep = mt * hh + (1 - mt) * h_prev
        return h_keep, mt * hh

    _, hs = jax.lax.scan(
        step, h_prev, (jnp.swapaxes(x, 0, 1), jnp.swapaxes(mask, 0, 1)))
    hidden = jnp.swapaxes(hs, 0, 1)
    if rev:
        hidden = _reverse_time(hidden, lens)
    return {"Hidden": hidden}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs, op=None):
    """Single-step LSTM cell (reference lstm_unit_op.cc): X [N,4H] pre-
    activation gates (order [c~, i, f, o]), C_prev [N,H]."""
    x, c_prev = ins["X"], ins["C_prev"]
    forget_bias = float(attrs.get("forget_bias", 0.0))
    h = c_prev.shape[-1]
    cand, gi, gf, go = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    o = jax.nn.sigmoid(go)
    c = f * c_prev + i * jnp.tanh(cand)
    return {"C": c, "H": o * jnp.tanh(c)}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs, op=None):
    """Single-step GRU cell (reference gru_unit_op.cc)."""
    x = ins["Input"]             # [N,3D]
    h_prev = ins["HiddenPrev"]   # [N,D]
    w = ins["Weight"]            # [D,3D]
    b = ins.get("Bias")
    d = h_prev.shape[-1]
    if b is not None:
        x = x + b.reshape(1, -1)
    gate_act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        attrs.get("gate_activation", 1), "sigmoid")
        if isinstance(attrs.get("gate_activation", 1), int)
        else attrs.get("gate_activation", "sigmoid"))
    act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        attrs.get("activation", 2), "tanh")
        if isinstance(attrs.get("activation", 2), int)
        else attrs.get("activation", "tanh"))
    xu, xr, xc = jnp.split(x, 3, axis=-1)
    u = gate_act(xu + h_prev @ w[:, :d])
    r = gate_act(xr + h_prev @ w[:, d: 2 * d])
    cand = act(xc + (r * h_prev) @ w[:, 2 * d:])
    gate = jnp.concatenate([u, r, cand], axis=-1)
    hidden = (1 - u) * h_prev + u * cand
    return {"Gate": gate, "ResetHiddenPrev": r * h_prev, "Hidden": hidden}


# ---------------------------------------------------------------------------
# Sequence manipulation ops
# ---------------------------------------------------------------------------

def _inner_lens_of(ctx, op, slot):
    """DEEPEST nested lengths of a level>=2 LoD input: the largest m
    with '<name>@LEN@m' present (core/executor_impl._prepare_lod_feeds
    emits one per level), returned as (lens [N,S1,..,Sm], m); None for
    dense/level-1 inputs."""
    if op is None:
        return None
    names = op.inputs.get(slot) or []
    if not (names and names[0]):
        return None
    name, m = names[0], 0
    while (name + "@LEN@%d" % (m + 1)) in ctx.env:
        m += 1
    if m == 0:
        return None
    return ctx.env[name + "@LEN@%d" % m], m


def _fold_level2(x, inner):
    """[N, S1, .., Sm, W, ...] + [N, S1, .., Sm] ->
    ([N*S1*..*Sm, W, ...], [M]): nested data folded so a level-1 op
    body works at the FINEST level (reference sequence ops always
    operate at the finest LoD level, lod_tensor.h:58-110).  The name
    survives from the level-2-only era; it now folds any depth —
    ``inner.ndim`` leading dims collapse."""
    lead = x.shape[:inner.ndim]
    m = int(np.prod(lead))
    return (x.reshape((m,) + x.shape[inner.ndim:]),
            inner.reshape(m), lead)


def _copy_nested_lens(ctx, op, oname, upto):
    """Propagate '@LEN@1'..'@LEN@upto' from the X input to an output
    (shape-preserving ops keep every level; pooling keeps upto-1)."""
    names = op.inputs.get("X") or []
    if not (names and names[0]):
        return
    src = names[0]
    for j in range(1, upto + 1):
        v = ctx.env.get(src + "@LEN@%d" % j)
        if v is not None:
            ctx.env[oname + "@LEN@%d" % j] = v


def _pool_core(x, lens, ptype):
    """[N,T,...] -> ({Out: [N,...], MaxIndex?}, counts) masked by
    lens."""
    n, t = x.shape[:2]
    mask = _mask(lens, n, t, x.dtype)
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape)
    counts = (jnp.sum(mask, axis=1).reshape((n,) + (1,) * (x.ndim - 2))
              if lens is not None else jnp.full((n,) + (1,) * (x.ndim - 2),
                                                t, x.dtype))
    outs = {}
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(counts, 1)
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(jnp.maximum(counts, 1))
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        masked = jnp.where(m > 0, x, neg)
        # empty (all-padding) sequences pool to 0, not -inf — they only
        # exist as level-2 outer padding and get masked downstream
        out = jnp.where(counts > 0, jnp.max(masked, axis=1), 0)
        outs["MaxIndex"] = jnp.argmax(masked, axis=1).astype(jnp.int32)
    elif ptype == "LAST":
        idx = (jnp.maximum(lens - 1, 0) if lens is not None
               else jnp.full((n,), t - 1))
        out = jnp.take_along_axis(
            x, idx.reshape((n, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    outs["Out"] = out
    if "MaxIndex" not in outs:  # slot always declared by the layer
        outs["MaxIndex"] = jnp.zeros((n,) + x.shape[2:], jnp.int32)
    return outs


@register_op("sequence_pool", seq_aware=True,
             no_vjp_outputs=("MaxIndex",))
def _sequence_pool(ctx, ins, attrs, op=None):
    """Pool each sequence to one vector (reference sequence_pool_op.cc):
    SUM/AVERAGE/SQRT/MAX/LAST/FIRST.  [N,T,D] -> [N,D]; level-2 input
    [N,S,W,D] pools each INNER sub-sequence (finest level) -> [N,S,D]
    with the outer lengths carried to the output."""
    x = ins["X"]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    nested = _inner_lens_of(ctx, op, "X")
    if nested is not None:
        inner, depth = nested
        xf, lf, lead = _fold_level2(x, inner)
        outs = _pool_core(xf, lf, ptype)
        outs = {k: v.reshape(lead + v.shape[1:])
                for k, v in outs.items()}
        if op is not None and op.outputs.get("Out"):
            # pooling consumes the finest level: output LoD drops one
            # level (level-k input -> level-(k-1) output)
            oname = op.outputs["Out"][0]
            outer = _lens_of(ctx, op, "X")
            if outer is not None:
                ctx.set_seq_len(oname, outer)
            _copy_nested_lens(ctx, op, oname, depth - 1)
        return outs
    return _pool_core(x, _lens_of(ctx, op, "X"), ptype)


def _softmax_core(x, lens):
    n, t = x.shape[:2]
    mask = _mask(lens, n, t, x.dtype).reshape(
        (n, t) + (1,) * (x.ndim - 2))
    neg = jnp.finfo(x.dtype).min
    mx = jnp.max(jnp.where(mask > 0, x, neg), axis=1, keepdims=True)
    # where, not multiply: an all-padding (length-0) sequence has
    # mx=finfo.min and exp(x-mx) overflows to inf — inf*0 would be NaN
    e = jnp.where(mask > 0, jnp.exp(x - mx), 0)
    return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-20)


@register_op("sequence_softmax", seq_aware=True)
def _sequence_softmax(ctx, ins, attrs, op=None):
    """Softmax within each sequence over the time axis, masked; level-2
    input normalizes within each INNER sub-sequence (finest level)."""
    x = ins["X"]
    nested = _inner_lens_of(ctx, op, "X")
    if nested is not None:
        inner, depth = nested
        xf, lf, _lead = _fold_level2(x, inner)
        out = _softmax_core(xf, lf).reshape(x.shape)
        if op is not None and op.outputs.get("Out"):
            oname = op.outputs["Out"][0]
            outer = _lens_of(ctx, op, "X")
            if outer is not None:  # shape-preserving: all levels carry
                ctx.set_seq_len(oname, outer)
            _copy_nested_lens(ctx, op, oname, depth)
        return {"Out": out}
    lens = _lens_of(ctx, op, "X")
    out = _softmax_core(x, lens)
    if op is not None and op.outputs.get("Out") and lens is not None:
        ctx.set_seq_len(op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("sequence_expand", seq_aware=True)
def _sequence_expand(ctx, ins, attrs, op=None):
    """Broadcast per-sequence vectors over the time steps of a reference
    ragged batch (reference sequence_expand_op.cc): X [N,D] + Y [N,T,..]
    -> [N,T,D] masked by Y's lengths."""
    x, y = ins["X"], ins["Y"]
    lens = _lens_of(ctx, op, "Y")
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    m = _mask(lens, x.shape[0], t, x.dtype).reshape(
        (x.shape[0], t) + (1,) * (x.ndim - 1))
    out = out * m
    if op is not None and op.outputs.get("Out") and lens is not None:
        ctx.set_seq_len(op.outputs["Out"][0], lens)
    return {"Out": out}


def _seq_conv_core(x, lens, filt, ctx_len, ctx_start):
    n, t, d = x.shape
    m = _mask(lens, n, t, x.dtype)[..., None]
    xm = x * m
    cols = []
    for k in range(ctx_len):
        shift = ctx_start + k
        cols.append(jnp.roll(xm, -shift, axis=1) * _shift_valid(
            n, t, shift, x.dtype))
    col = jnp.concatenate(cols, axis=-1)          # [N,T,ctx*D]
    return (col @ filt) * m


@register_op("sequence_conv", seq_aware=True)
def _sequence_conv(ctx, ins, attrs, op=None):
    """Context-window convolution over time (reference
    sequence_conv_op.cc): X [N,T,D], Filter [ctx_len*D, F].  Level-2
    input convolves within each INNER sub-sequence — the window never
    crosses a sub-sequence boundary (finest-level semantics)."""
    x = ins["X"]
    filt = ins["Filter"]
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -(ctx_len // 2)))
    nested = _inner_lens_of(ctx, op, "X")
    if nested is not None:
        inner, depth = nested
        xf, lf, lead = _fold_level2(x, inner)
        out = _seq_conv_core(xf, lf, filt, ctx_len, ctx_start)
        out = out.reshape(lead + out.shape[1:])
        if op is not None and op.outputs.get("Out"):
            oname = op.outputs["Out"][0]
            outer = _lens_of(ctx, op, "X")
            if outer is not None:
                ctx.set_seq_len(oname, outer)
            _copy_nested_lens(ctx, op, oname, depth)
        return {"Out": out}
    lens = _lens_of(ctx, op, "X")
    out = _seq_conv_core(x, lens, filt, ctx_len, ctx_start)
    if op is not None and op.outputs.get("Out") and lens is not None:
        ctx.set_seq_len(op.outputs["Out"][0], lens)
    return {"Out": out}


def _shift_valid(n, t, shift, dtype):
    """Validity of positions after shifting by `shift` (zero padding
    outside [0, T))."""
    tt = jnp.arange(t)[None, :, None]
    src = tt + shift
    return ((src >= 0) & (src < t)).astype(dtype)


@register_op("sequence_erase", seq_aware=True)
def _sequence_erase(ctx, ins, attrs, op=None):
    """Remove listed tokens and compact each sequence left (reference
    sequence_erase_op.cc).  X [N,T] (or [N,T,1]) int tokens."""
    x = ins["X"]
    lens = _lens_of(ctx, op, "X")
    tokens = attrs.get("tokens", [])
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    ids = x[..., 0] if squeeze else x
    n, t = ids.shape
    valid = _mask(lens, n, t, jnp.bool_)
    keep = valid
    for tok in tokens:
        keep = keep & (ids != tok)
    # stable left-compaction: sort by (dropped, position)
    order = jnp.argsort(jnp.where(keep, 0, 1) * t + jnp.arange(t)[None, :],
                        axis=1)
    gathered = jnp.take_along_axis(ids, order, axis=1)
    new_lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    pos_ok = jnp.arange(t)[None, :] < new_lens[:, None]
    out = jnp.where(pos_ok, gathered, 0)
    if squeeze:
        out = out[..., None]
    if op is not None and op.outputs.get("Out"):
        ctx.set_seq_len(op.outputs["Out"][0], new_lens)
    return {"Out": out}


@register_op("seq_cross_attention", seq_aware=True)
def _seq_cross_attention(ctx, ins, attrs, op=None):
    """Dot-product cross attention with key-side length masking — the
    batched static-shape form of the reference's per-step attention inside
    DynamicRNN (book machine_translation: sequence_expand + sequence_
    softmax over encoder states).  Q [N,Tq,D], K/V [N,Tk,D]."""
    q, k, v = ins["Q"], ins["K"], ins["V"]
    klens = _lens_of(ctx, op, "K")
    scale = float(attrs.get("scale", 0.0)) or q.shape[-1] ** -0.5
    s = jnp.einsum("nqd,nkd->nqk", q, k) * scale
    if klens is not None:
        mask = jnp.arange(k.shape[1])[None, None, :] < klens[:, None, None]
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("nqk,nkd->nqd", w, v)
    if op is not None and op.outputs.get("Out"):
        qlens = _lens_of(ctx, op, "Q")
        if qlens is not None:
            ctx.set_seq_len(op.outputs["Out"][0], qlens)
    return {"Out": out}


@register_op("lod_reset", seq_aware=True)
def _lod_reset(ctx, ins, attrs, op=None):
    """Reassign sequence lengths from attr target_lod (offsets) or a
    second input (reference lod_reset_op.cc)."""
    x = ins["X"]
    y = ins.get("Y")
    if y is not None:
        lens = y.astype(jnp.int32)
    else:
        offs = list(attrs.get("target_lod", []))
        lens = jnp.asarray(np.diff(np.asarray(offs, np.int64)),
                           jnp.int32) if offs else None
    if op is not None and op.outputs.get("Out") and lens is not None:
        ctx.set_seq_len(op.outputs["Out"][0], lens)
    return {"Out": x}


@register_op("edit_distance", grad_maker=None, seq_aware=True)
def _edit_distance(ctx, ins, attrs, op=None):
    """Levenshtein distance per (hypothesis, reference) pair via a
    lax.scan DP (reference edit_distance_op.cc).  Hyps [N,T1], Refs
    [N,T2] int tokens with @LEN lengths."""
    hyp, ref = ins["Hyps"], ins["Refs"]
    hlens = _lens_of(ctx, op, "Hyps")
    rlens = _lens_of(ctx, op, "Refs")
    norm = bool(attrs.get("normalized", False))
    h = hyp[..., 0] if hyp.ndim == 3 else hyp
    r = ref[..., 0] if ref.ndim == 3 else ref
    n, t1 = h.shape
    t2 = r.shape[1]
    if hlens is None:
        hlens = jnp.full((n,), t1, jnp.int32)
    if rlens is None:
        rlens = jnp.full((n,), t2, jnp.int32)

    # DP rows over hypothesis tokens; mask positions beyond lengths.
    row0 = jnp.broadcast_to(jnp.arange(t2 + 1, dtype=jnp.float32)[None],
                            (n, t2 + 1))
    row0 = jnp.minimum(row0, rlens[:, None].astype(jnp.float32))

    def step(row, i):
        # row: [N, T2+1] distances for prefix length i of hyp
        sub = row[:, :-1] + (h[:, i][:, None] != r).astype(jnp.float32)
        first = row[:, 0] + 1.0

        def col(carry, j):
            prev = carry
            cand = jnp.minimum(jnp.minimum(row[:, j + 1] + 1.0, prev + 1.0),
                               sub[:, j])
            return cand, cand

        _, cols = jax.lax.scan(col, first, jnp.arange(t2))
        new = jnp.concatenate([first[:, None], jnp.swapaxes(cols, 0, 1)],
                              axis=1)
        # only advance rows that are within this hyp's length
        active = (i < hlens)[:, None]
        row = jnp.where(active, new, row)
        return row, None

    row, _ = jax.lax.scan(step, row0, jnp.arange(t1))
    dist = jnp.take_along_axis(row, rlens[:, None].astype(jnp.int32),
                               axis=1)
    # static batch count, not a traced value (reference edit_distance_op.cc
    # emits a shape-[1] int64 tensor); pick the widest int the active JAX
    # mode keeps so compiled and interpreted paths agree on dtype
    seq_num = np.asarray(
        [n], np.int64 if jax.config.jax_enable_x64 else np.int32)
    if norm:
        dist = dist / jnp.maximum(rlens[:, None].astype(jnp.float32), 1.0)
    return {"Out": dist.astype(jnp.float32), "SequenceNum": seq_num}


@register_op("sequence_concat", seq_aware=True)
def _sequence_concat(ctx, ins, attrs, op=None):
    """Per-row concatenation along time (reference
    sequence_concat_op.cc): row n of the output is the valid tokens of
    every input's row n back to back; '@LEN' = sum of input lens."""
    xs = [v for v in ins.list("X") if v is not None]
    n = xs[0].shape[0]
    t_out = sum(x.shape[1] for x in xs)
    names = (op.inputs.get("X") or []) if op is not None else []
    lens = []
    for i, x in enumerate(xs):
        l = ctx.seq_len_of(names[i]) if i < len(names) and names[i] \
            else None
        lens.append(l.astype(jnp.int32) if l is not None
                    else jnp.full((n,), x.shape[1], jnp.int32))
    out = jnp.zeros((n, t_out) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((n,), jnp.int32)
    rows = jnp.arange(n)[:, None]
    for x, l in zip(xs, lens):
        ti = x.shape[1]
        pos = jnp.arange(ti)[None, :]
        col = offset[:, None] + pos
        # invalid tokens scatter out of bounds (dropped)
        col = jnp.where(pos < l[:, None], col, t_out)
        out = out.at[rows, col].set(x)
        offset = offset + l
    if op is not None:
        for nm in (op.outputs.get("Out") or []):
            if nm:
                ctx.set_seq_len(nm, offset)
    return {"Out": out}


@register_op("sequence_reshape", seq_aware=True)
def _sequence_reshape(ctx, ins, attrs, op=None):
    """Change the token width (reference sequence_reshape_op.cc):
    [N,T,D] -> [N, T*D/nd, nd]; row lengths scale by D/nd.  Valid
    tokens are row-leading in the padded layout, so a flat reshape is
    exact."""
    x = ins["X"]
    nd = int(attrs["new_dim"])
    n, t, d = x.shape
    assert (t * d) % nd == 0, "new_dim must divide T*D"
    out = x.reshape(n, t * d // nd, nd)
    lens = _lens_of(ctx, op, "X")
    if lens is not None and op is not None:
        for nm in (op.outputs.get("Out") or []):
            if nm:
                ctx.set_seq_len(nm, (lens * d) // nd)
    return {"Out": out}


@register_op("sequence_slice", seq_aware=True)
def _sequence_slice(ctx, ins, attrs, op=None):
    """Per-sequence [offset, offset+length) slice, left-aligned
    (reference sequence_slice_op.cc); '@LEN' = Length."""
    x = ins["X"]
    off = ins["Offset"].reshape(-1).astype(jnp.int32)
    length = ins["Length"].reshape(-1).astype(jnp.int32)
    n, t = x.shape[0], x.shape[1]
    pos = jnp.arange(t)[None, :]
    src = jnp.clip(pos + off[:, None], 0, t - 1)
    rows = jnp.arange(n)[:, None]
    out = x[rows, src]
    keep = pos < length[:, None]
    out = jnp.where(keep.reshape(keep.shape + (1,) * (x.ndim - 2)),
                    out, 0)
    if op is not None:
        for nm in (op.outputs.get("Out") or []):
            if nm:
                ctx.set_seq_len(nm, length)
    return {"Out": out}


@register_op("lstmp")
def _lstmp(ctx, ins, attrs, op=None):
    """LSTM with recurrent projection (reference lstmp_op.cc): the
    recurrence feeds the projection r = proj_act(h @ ProjWeight), so
    Weight is [P, 4H] and the sequence output is the projection
    [N, T, P]."""
    proj, cell = _lstm_scan(
        ins["Input"], ins["Weight"], ins.get("Bias"),
        _lens_of(ctx, op, "Input"), attrs,
        h0=ins.get("H0"), c0=ins.get("C0"),
        w_proj=ins["ProjWeight"],
        proj_act=_act(attrs.get("proj_activation", "tanh")))
    return {"Projection": proj, "Cell": cell}


@register_op("kmax_seq_score", grad_maker=None, seq_aware=True)
def _kmax_seq_score(ctx, ins, attrs, op=None):
    """Top-k score POSITIONS within each sequence (reference
    gserver/layers/KmaxSeqScoreLayer.cpp via kmax_seq_score_layer:7191):
    X [N, T, 1] ragged scores; Out [N, k] int32 indices into the
    sequence (slots past a short sequence's k are -1)."""
    x = ins["X"]
    if x.ndim == 3:
        x = x[..., 0]
    k = int(attrs.get("beam_size", 1))
    n, t = x.shape
    lens = _lens_of(ctx, op, "X")
    if lens is None:
        lens = jnp.full((n,), t, jnp.int32)
    valid = jnp.arange(t)[None, :] < lens[:, None]
    masked = jnp.where(valid, x.astype(jnp.float32), -jnp.inf)
    kk = min(k, t)
    _, idx = jax.lax.top_k(masked, kk)                   # [N, kk]
    in_range = jnp.arange(kk)[None, :] < jnp.minimum(lens, kk)[:, None]
    idx = jnp.where(in_range, idx, -1).astype(jnp.int32)
    if kk < k:
        idx = jnp.pad(idx, ((0, 0), (0, k - kk)), constant_values=-1)
    return {"Out": idx}


@register_op("sub_nested_seq", seq_aware=True)
def _sub_nested_seq(ctx, ins, attrs, op=None):
    """Select inner sub-sequences of a level-2 input by per-sample
    index lists (reference gserver/layers/SubNestedSequenceLayer.cpp
    via sub_nested_seq_layer:7045): X level-2 [N, S, W, ...] with
    inner lens, SelectedIndices a ragged int list per sample; Out is
    the level-2 sequence keeping only the selected inner rows, in the
    given order."""
    x = ins["X"]
    sel = ins["SelectedIndices"]
    if sel.ndim == 3:
        sel = sel[..., 0]
    sel = sel.astype(jnp.int32)
    nested = _inner_lens_of(ctx, op, "X")
    if nested is None:
        raise ValueError(
            "sub_nested_seq requires a level-2 X (a level-1 input has "
            "no sub-sequences to select; use sequence_slice)")
    inner, depth = nested
    if depth != 1:
        raise NotImplementedError(
            "sub_nested_seq: only level-2 inputs are supported "
            "(level-%d given)" % (depth + 1))
    n, s = x.shape[:2]
    k = sel.shape[1]
    sel_lens = _lens_of(ctx, op, "SelectedIndices")
    if sel_lens is None:
        sel_lens = jnp.full((n,), k, jnp.int32)
    kvalid = jnp.arange(k)[None, :] < sel_lens[:, None]
    idx = jnp.where(kvalid, jnp.clip(sel, 0, s - 1), 0)
    gidx = idx.reshape((n, k) + (1,) * (x.ndim - 2))
    out = jnp.take_along_axis(x, gidx, axis=1)
    out = jnp.where(kvalid.reshape((n, k) + (1,) * (x.ndim - 2)),
                    out, jnp.zeros((), x.dtype))
    new_inner = jnp.where(kvalid,
                          jnp.take_along_axis(inner, idx, axis=1), 0)
    if op is not None and op.outputs.get("Out"):
        oname = op.outputs["Out"][0]
        ctx.set_seq_len(oname, sel_lens)
        ctx.env[oname + "@LEN@1"] = new_inner
    return {"Out": out}
