"""Distributed (parameter-server) host ops: send / recv / send_barrier /
fetch_barrier / listen_and_serv.

Parity: reference operators/{send,recv,send_barrier,fetch_barrier,
listen_and_serv}_op.cc over the gRPC service (operators/detail/).  All run
on the host at the tail/head of a block, so the device step stays ONE
compiled XLA program; parameter traffic is numpy over gRPC
(paddle_tpu/distributed/rpc.py).

Wire layout used by the transpiler (fluid/transpiler/distribute_transpiler.py):
- ``send``: X=[grad]; attrs ``epmap`` (endpoint per block), ``sections``
  (rows per block, axis 0), ``block_names``.  The host splits the grad
  and ships each slice to its pserver.
- ``recv``: Out=[param]; same attrs — fetches every slice (blocking on the
  sync round) and concatenates into the param var.
- ``listen_and_serv``: attrs ``endpoint``, ``Fanin``, ``sync_mode``,
  ``grad_to_block_id`` ("gradname:blockidx" strings); blocks serving until
  every trainer sends SendComplete.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.observability.trace import TRACER as _TRC


def _host(name):
    def deco(impl):
        register_op(name, lower=impl, host_op=True, grad_maker=None)
        return impl

    return deco


def _read(name, scope, env, raw=False):
    """``raw=True`` (the send path) keeps dense values as whatever the
    executor produced — possibly device arrays: np.asarray moves into
    the RPC client's sender threads, off the round's critical path.
    SelectedRows always materialize (the split math below is numpy)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    val = (env[name] if env is not None and name in env
           else scope.find_var(name))
    if isinstance(val, SelectedRows):
        return SelectedRows(np.asarray(val.rows), np.asarray(val.values),
                            val.height)
    return val if raw else np.asarray(val)


def _write(name, val, scope, env):
    if env is not None:
        env[name] = val
    s = scope.find_scope_of(name) or scope
    s.set(name, val)


def _sections_starts(sections):
    starts = [0]
    for s in sections:
        starts.append(starts[-1] + s)
    return starts


def _check_rpc_route(op):
    """Runtime guard on the same invariant the verifier's dist-pairing
    checker enforces statically: epmap/sections/block_names must route
    one slice each, or slices land on the wrong pserver.  (The static
    check can be off; a misrouted slice must still die loudly.)"""
    eps = op.attr("epmap") or []
    sections = op.attr("sections") or []
    names = op.attr("block_names") or []
    if not eps or not (len(eps) == len(sections) == len(names)):
        raise ValueError(
            "%s op: epmap/sections/block_names lengths disagree "
            "(%d/%d/%d) — re-run the DistributeTranspiler or lint the "
            "program (tools/lint_program.py)"
            % (op.type, len(eps), len(sections), len(names)))
    return eps, sections, names


def _watchdog(op_name, eps, client, exc):
    """Convert an exhausted RPC deadline into a WatchdogTimeout naming
    the peers every pserver is still waiting on — an indefinite
    collective hang must die loudly, not silently (reference: trainers
    blocked in a sync barrier when a peer crashed)."""
    from paddle_tpu.distributed.resilience import watchdog_error

    return watchdog_error(op_name, eps, client.barrier_status, exc)


def _merge_dup_rows(sr):
    """Sum duplicate rows of an outbound SelectedRows grad host-side.
    A power-law lookup batch repeats its head ids heavily (a 4096x16
    zipf batch is ~4x duplicates), and every duplicate costs wire bytes
    up + a scatter-add slot on the pserver; summation first is the same
    math (scatter-add is order-free up to fp rounding)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    rows = np.asarray(sr.rows)
    if rows.size < 4096:
        # small grads keep the PR 4 static-K contract verbatim (one
        # jitted shape per table, tests/test_selected_rows.py) — the
        # merge only pays at CTR-batch scale, where the serve loop's
        # power-of-2 bucket pad bounds the shape set instead
        return sr
    # sampled duplicate estimate first: on near-uniform id traffic the
    # full unique+scatter pass buys almost no bytes, and the varying
    # merged length costs a bucket-pad downstream — skip unless the
    # batch is measurably head-heavy
    probe = rows[:: max(1, rows.size // 2048)][:2048]
    if 1.0 - np.unique(probe).size / probe.size < 0.15:
        return sr
    from paddle_tpu.core.selected_rows import merge_rows_host

    uniq, merged = merge_rows_host(rows, np.asarray(sr.values))
    if uniq.size == rows.size:
        return sr                  # already distinct
    return SelectedRows(uniq, merged, sr.height)


@_host("send")
def _send(executor, op, scope, feed, env=None):
    from paddle_tpu.distributed.rpc import RPCClient

    client = RPCClient.instance()
    name = op.input("X")[0]
    sp = _TRC.begin("op.send", None, {"x": name}) if _TRC.on else None
    try:
        return _send_impl(client, op, scope, env)
    finally:
        if sp is not None:
            _TRC.end(sp)


def _send_impl(client, op, scope, env):
    name = op.input("X")[0]
    val = _read(name, scope, env, raw=True)
    eps, sections, names = _check_rpc_route(op)
    starts = _sections_starts(sections)
    from paddle_tpu.core.selected_rows import SelectedRows

    if isinstance(val, SelectedRows):
        val = _merge_dup_rows(val)

    if not isinstance(val, SelectedRows) and len(eps) > 1:
        # materialize ONCE so the per-endpoint splits below are numpy
        # VIEWS: slicing the device array instead would dispatch one
        # device copy per shard (measured ~25 ms per 52 MB slice) on
        # top of the per-slice d2h
        val = np.asarray(val)
    triples = []
    for i, (ep, bname) in enumerate(zip(eps, names)):
        if isinstance(val, SelectedRows):
            if len(eps) == 1:
                part = val
            else:
                # split_ids by row range, re-based to the block's origin
                # (reference split_selected_rows_op.cc).  K stays STATIC:
                # out-of-range slots point at the part's height (scatters
                # drop them) so the pserver's jitted optimize block sees
                # one shape per table and never recompiles per step.
                m = (val.rows >= starts[i]) & (val.rows < starts[i + 1])
                rows = np.where(m, val.rows - starts[i],
                                sections[i]).astype(np.int32)
                vals = np.where(
                    m.reshape((-1,) + (1,) * (val.values.ndim - 1)),
                    val.values, 0)
                part = SelectedRows(rows, vals, sections[i])
        else:
            part = val[starts[i]:starts[i + 1]] if len(eps) > 1 else val
        triples.append((ep, bname, part))
    client.send_vars(triples)


class _SliceAssembler:
    """Assemble a sharded param from its row-slices AS FRAMES ARRIVE:
    each get-thread copies its slice straight into the preallocated
    output (one pass, overlapped with the still-in-flight shards)
    instead of a post-hoc np.concatenate over every part."""

    def __init__(self, sections):
        self._starts = _sections_starts(sections)
        self._rows = sum(sections)
        self._lock = threading.Lock()
        self.out = None
        self._fallback = {}

    def sink(self, i):
        def _sink(arr):
            from paddle_tpu.distributed.rpc import _aligned_empty

            arr = np.asarray(arr)
            with self._lock:
                if self.out is None and arr.ndim >= 1:
                    # 64-byte aligned: the next step's compiled run
                    # stages this param ZERO-COPY (jax CPU aliases
                    # aligned numpy); np.empty would re-copy ~100 MB
                    # every step
                    self.out = _aligned_empty(
                        (self._rows,) + arr.shape[1:], arr.dtype)
            lo = self._starts[i]
            if (self.out is not None and arr.ndim >= 1
                    and arr.shape[0] == self._starts[i + 1] - lo
                    and arr.shape[1:] == self.out.shape[1:]
                    and arr.dtype == self.out.dtype):
                self.out[lo:lo + arr.shape[0]] = arr
            else:   # odd shard (shape drift): assemble by concat below
                self._fallback[i] = np.asarray(arr)
            return True
        return _sink

    def value(self, n):
        if not self._fallback and self.out is not None:
            return self.out
        parts = []
        for i in range(n):
            if i in self._fallback:
                parts.append(self._fallback[i])
            else:
                lo, hi = self._starts[i], self._starts[i + 1]
                parts.append(self.out[lo:hi])
        return np.concatenate(parts, axis=0)


@_host("recv")
def _recv(executor, op, scope, feed, env=None):
    from paddle_tpu.distributed.resilience import DeadlineExceeded
    from paddle_tpu.distributed.rpc import RPCClient

    client = RPCClient.instance()
    out = op.output("Out")[0]
    eps, sections, names = _check_rpc_route(op)
    sp = _TRC.begin("op.recv", None, {"out": out}) if _TRC.on else None
    try:
        if len(eps) == 1:
            parts = client.get_vars(list(zip(eps, names)))
            val = parts[0]
        else:
            asm = _SliceAssembler(sections)
            client.get_vars(list(zip(eps, names)),
                            sinks=[asm.sink(i) for i in range(len(eps))])
            val = asm.value(len(eps))
    except DeadlineExceeded as e:
        raise _watchdog("recv", sorted(set(eps)), client, e) from e
    finally:
        if sp is not None:
            _TRC.end(sp)
    _write(out, val, scope, env)


@_host("send_barrier")
def _send_barrier(executor, op, scope, feed, env=None):
    """Sync-round barrier.  With the transpiler's ``overlap`` attr (and
    FLAGS_pserver_overlap), the barriers are only LAUNCHED here — the
    recv ops that follow run full-duplex with them, and the trainer's
    fetch_barrier joins the acks (ack-implies-durable still gates the
    round boundary).  Without it (direct callers, startup programs,
    FLAGS off) the call blocks for the acks as before."""
    from paddle_tpu.distributed.resilience import FLAGS, DeadlineExceeded
    from paddle_tpu.distributed.rpc import RPCClient

    client = RPCClient.instance()
    eps = op.attr("endpoints")
    overlap = bool(op.attr("overlap", False)) and FLAGS.pserver_overlap
    try:
        if overlap:
            client.launch_barriers(eps)
        else:
            client.send_barrier(eps)
    except DeadlineExceeded as e:
        raise _watchdog("send_barrier", eps, client, e) from e


@_host("fetch_barrier")
def _fetch_barrier(executor, op, scope, feed, env=None):
    from paddle_tpu.distributed.resilience import DeadlineExceeded
    from paddle_tpu.distributed.rpc import RPCClient

    client = RPCClient.instance()
    eps = op.attr("endpoints")
    try:
        # join the round's overlapped barriers FIRST: their acks imply
        # the round is applied and durable on every pserver, and any
        # failure must surface before the next round's sends
        client.join_barriers()
        client.fetch_barrier(eps)
    except DeadlineExceeded as e:
        raise _watchdog("fetch_barrier", eps, client, e) from e


def _bucket_sparse_grad(scope, gname):
    """Pad a SelectedRows grad in ``scope`` to the next power-of-2 row
    count (sentinel rows = height, zero values) so downstream jitted
    scatter-updates see a bounded set of shapes.  Scatter semantics are
    unchanged: out-of-bounds rows are dropped, zero values add
    nothing."""
    from paddle_tpu.core.selected_rows import SelectedRows

    if not gname:
        return
    try:
        val = scope.find_var(gname)
    except Exception:
        return
    if not isinstance(val, SelectedRows):
        return
    rows = np.asarray(val.rows)
    k = int(rows.size)
    bucket = 1 if k == 0 else 1 << max(0, (k - 1).bit_length())
    if bucket <= k:
        return
    values = np.asarray(val.values)
    rows_p = np.full((bucket,), val.height, rows.dtype)
    rows_p[:k] = rows
    vals_p = np.zeros((bucket,) + values.shape[1:], values.dtype)
    vals_p[:k] = values
    scope.set(gname, SelectedRows(rows_p, vals_p, val.height))


@_host("listen_and_serv")
def _listen_and_serv(executor, op, scope, feed, env=None):
    """Serve until all trainers complete (reference
    listen_and_serv_op.cc:99 RunSyncLoop / :166 RunAsyncLoop).  Optimize
    sub-blocks run through a nested ExecutorCore against the server
    scope."""
    from paddle_tpu.core.executor_impl import ExecutorCore
    from paddle_tpu.distributed.resilience import FLAGS
    from paddle_tpu.distributed.rpc import VariableServer

    program = executor._current_program
    endpoint = op.attr("endpoint")
    # name this process's telemetry dumps after its serving role so the
    # merged chrome trace labels the pserver timeline
    _TRC.set_label("pserver@%s" % endpoint)
    fanin = int(op.attr("Fanin", 1))
    sync_mode = bool(op.attr("sync_mode", True))
    grad_to_block = {}
    for item in op.attr("grad_to_block_id", []):
        gname, bid = item.rsplit(":", 1)
        grad_to_block[gname] = int(bid)

    # grad -> vars its optimize block writes: the server publishes a
    # per-shard completion event the moment that block commits, so
    # streamed gathers ship a shard without gating on the whole round
    grad_params = {}
    for gname, bid in grad_to_block.items():
        try:
            outs = set()
            for opd in program.blocks[bid].ops:
                outs.update(n for n in opd.output_arg_names() if n)
            grad_params[gname] = tuple(sorted(outs))
        except Exception:
            # leave the grad UNMAPPED — () would mean "writes nothing"
            # and defeat the server's unknown-means-invalidate-all
            # reply-cache fallback
            pass

    sub_exec = ExecutorCore(executor.place)
    grad_of_block = {bid: g for g, bid in grad_to_block.items()}

    def apply_block(block_id):
        # merged/compressed sparse grads arrive with a DATA-DEPENDENT
        # row count; pad to a power-of-2 bucket so the jitted optimize
        # block compiles O(log K) times instead of once per round
        # (padding rows point at row == height — XLA scatter drops
        # out-of-bounds updates, the core merge_rows idiom)
        _bucket_sparse_grad(scope, grad_of_block.get(block_id))
        sub_exec.run(program, scope, block_id=block_id)

    # shard checkpointing (reference go/pserver/service.go:346): restart
    # resumes from the last snapshot instead of fresh init.  The op attr
    # wins; FLAGS_pserver_checkpoint_root is the env path for spawned
    # pserver processes — each endpoint gets its own subdir.
    ckpt_dir = op.attr("checkpoint_dir", "") or None
    if not ckpt_dir and FLAGS.pserver_checkpoint_root:
        ckpt_dir = os.path.join(
            FLAGS.pserver_checkpoint_root,
            endpoint.replace(":", "_").replace("/", "_"))
    ckpt_n = int(op.attr("checkpoint_every_n", 0) or 0) \
        or int(FLAGS.pserver_checkpoint_every_n)

    # bounded-staleness window (ISSUE 10): the transpiler stamps the
    # program-build-time FLAGS_dist_staleness onto the op so trainer
    # and pserver agree even if the serve process's env drifts; an
    # un-stamped (older) program falls back to this process's flag
    staleness = int(op.attr("staleness", -1))
    if staleness < 0:
        staleness = int(FLAGS.dist_staleness)

    server = VariableServer(
        scope, grad_to_block, apply_block, fanin, sync_mode,
        checkpoint_dir=ckpt_dir, checkpoint_every_n=ckpt_n,
        trainer_lease=op.attr("trainer_lease", None),
        grad_params=grad_params, staleness=staleness)
    port = server.start(endpoint)
    port_file = op.attr("port_file", "")
    if port_file:
        # reference SavePort (listen_and_serv_op.cc:86): tests discover
        # the chosen port through this file
        with open(port_file, "w") as f:
            f.write(str(port))
    server.wait()


@_host("distributed_lookup")
def _distributed_lookup(executor, op, scope, feed, env=None):
    """Embedding lookup against a pserver-sharded table (reference
    distribute_transpiler.py:611 _replace_lookup_table_op_with_prefetch
    + prefetch_op / grpc PrefetchVariable).  Ids are split by the
    table's row ranges, each shard's rows are prefetched over RPC, and
    the gathered rows reassemble in id order."""
    from paddle_tpu.distributed.rpc import RPCClient

    name = op.input("Ids")[0]
    if env is not None and name in env:
        ids = np.asarray(env[name])
    elif feed is not None and name in feed:
        ids = np.asarray(feed[name])
    else:
        ids = np.asarray(scope.find_var(name))
    sp = _TRC.begin("op.distributed_lookup", None,
                    {"n_ids": int(ids.size)}) if _TRC.on else None
    try:
        return _distributed_lookup_impl(op, scope, env, ids)
    finally:
        if sp is not None:
            _TRC.end(sp)


def _distributed_lookup_impl(op, scope, env, ids):
    from paddle_tpu.distributed.rpc import RPCClient

    eps = op.attr("epmap")
    names = op.attr("block_names")
    sections = op.attr("sections")
    padding_idx = int(op.attr("padding_idx", -1))
    starts = _sections_starts(sections)

    # same shape contract as lookup_table: a trailing ids dim of 1 is
    # squeezed before the embedding dim is appended
    id_shape = ids.shape[:-1] if ids.shape and ids.shape[-1] == 1 \
        else ids.shape
    flat = ids.reshape(-1).astype(np.int64)
    # out-of-range ids clamp, matching the local jnp.take semantics
    flat = np.clip(flat, 0, starts[-1] - 1)
    # prefetch each DISTINCT row once: power-law CTR batches repeat the
    # head ids heavily (a 4096x16 zipf batch is ~2x duplicates), and
    # every duplicate costs 8 id bytes up + an embedding row down.
    # Gather unique rows, then fan back out by the inverse index.
    uniq, inv = np.unique(flat, return_inverse=True)
    out_u = None
    triples, masks = [], []
    for i, (ep, bname) in enumerate(zip(eps, names)):
        m = (uniq >= starts[i]) & (uniq < starts[i + 1])
        triples.append((ep, bname, uniq[m] - starts[i]))
        masks.append(m)
    client = RPCClient.instance()
    for m, rows in zip(masks, client.prefetch_vars(triples)):
        if out_u is None:
            out_u = np.zeros((uniq.shape[0], rows.shape[-1]),
                             rows.dtype)
        if rows.size:
            out_u[m] = rows
    out = out_u[inv]
    if padding_idx != -1:
        out[flat == padding_idx] = 0.0   # local lookup_table parity
    out = out.reshape(tuple(id_shape) + (out.shape[-1],))
    _write(op.output("Out")[0], out, scope, env)
