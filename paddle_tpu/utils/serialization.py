"""Tensor file format used by save/load ops and checkpoints.

Parity: reference framework/tensor_util.cc TensorToStream (version header +
dtype + dims + raw data).  Format (little-endian):

  magic  b"PTPU"
  u32    version (=1)
  u32    proto dtype
  u32    ndim
  i64[n] dims
  bytes  raw row-major data
"""
from __future__ import annotations

import struct

import numpy as np

from paddle_tpu.core.types import np_dtype_to_proto, proto_to_np_dtype

_MAGIC = b"PTPU"
_VERSION = 1


def tensor_to_bytes(arr):
    arr = np.ascontiguousarray(np.asarray(arr))
    header = struct.pack("<4sII", _MAGIC, _VERSION,
                         np_dtype_to_proto(arr.dtype))
    dims = struct.pack("<I", arr.ndim) + struct.pack(
        "<%dq" % arr.ndim, *arr.shape)
    return header + dims + arr.tobytes()


def tensor_from_bytes(buf, offset=0):
    magic, version, dtype = struct.unpack_from("<4sII", buf, offset)
    if magic != _MAGIC:
        raise ValueError("bad tensor magic %r" % magic)
    if version != _VERSION:
        raise ValueError("unsupported tensor version %d" % version)
    offset += 12
    (ndim,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    dims = struct.unpack_from("<%dq" % ndim, buf, offset)
    offset += 8 * ndim
    np_dtype = proto_to_np_dtype(dtype)
    count = int(np.prod(dims)) if ndim else 1
    arr = np.frombuffer(buf, dtype=np_dtype, count=count,
                        offset=offset).reshape(dims)
    offset += arr.nbytes
    return arr.copy(), offset


def save_tensor(path, arr):
    with open(path, "wb") as f:
        f.write(tensor_to_bytes(arr))


def load_tensor(path):
    with open(path, "rb") as f:
        arr, _ = tensor_from_bytes(f.read())
    return arr


def save_combined(path, names_arrays):
    with open(path, "wb") as f:
        f.write(struct.pack("<I", len(names_arrays)))
        for name, arr in names_arrays:
            nb = name.encode("utf-8")
            f.write(struct.pack("<I", len(nb)) + nb)
            f.write(tensor_to_bytes(arr))


def load_combined(path):
    with open(path, "rb") as f:
        buf = f.read()
    (n,) = struct.unpack_from("<I", buf, 0)
    offset = 4
    result = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", buf, offset)
        offset += 4
        name = buf[offset:offset + ln].decode("utf-8")
        offset += ln
        arr, offset = tensor_from_bytes(buf, offset)
        result.append((name, arr))
    return result
