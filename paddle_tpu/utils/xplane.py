"""XPlane trace reader: aggregate per-op device time from a
``jax.profiler.trace`` capture (SURVEY §5.1 — the device-tracer half of
the profiling story; fluid/profiler.py covers the host half).

``jax.profiler.trace(dir)`` writes
``<dir>/plugins/profile/<run>/*.xplane.pb``; the usual viewer
(tensorboard-plugin-profile) needs a working TF protobuf stack, which
this environment lacks.  This module reads the XSpace container with a
minimal protobuf wire-format walker — no generated code, no
tensorflow — and reduces the "XLA Ops" line to per-op totals, which is
what perf work actually consumes (it found the flash-attention backward
and block-size wins).

Wire schema (public tensorflow/core/profiler/protobuf/xplane.proto):
XSpace.planes=1; XPlane{name=2, lines=3, event_metadata=4(map)};
XLine{name=2, events=4}; XEvent{metadata_id=1, duration_ps=3};
XEventMetadata{id=1, name=2}.
"""
from __future__ import annotations

import collections
import glob
import os
import re

__all__ = ["read_xspace", "op_totals", "print_op_profile"]


def _varint(buf, i):
    x = s = 0
    n = len(buf)
    while True:
        if i >= n:
            raise ValueError("truncated protobuf (varint past buffer)")
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer;
    length-delimited values come back as memoryview slices.  Raises
    ValueError on truncation instead of silently under-reading — a
    half-written capture must not produce quietly-wrong totals."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            v, i = _varint(buf, i)
        elif wt == 1:                    # fixed64
            if i + 8 > n:
                raise ValueError("truncated protobuf (fixed64)")
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:                    # length-delimited
            ln, i = _varint(buf, i)
            if ln > n - i:
                raise ValueError(
                    "truncated protobuf (field of %d bytes, %d left)"
                    % (ln, n - i))
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # fixed32
            if i + 4 > n:
                raise ValueError("truncated protobuf (fixed32)")
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        yield fno, wt, v


def _parse_event(buf):
    meta_id = 0
    dur_ps = 0
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            meta_id = v
        elif fno == 3 and wt == 0:
            dur_ps = v
    return meta_id, dur_ps


def _parse_line(buf):
    name = ""
    events = []
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            events.append(_parse_event(v))
    return name, events


def _parse_metadata_entry(buf):
    """map<int64, XEventMetadata> entry: key=1, value=2."""
    key = 0
    name = ""
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            key = v
        elif fno == 2 and wt == 2:
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


def _parse_plane(buf):
    name = ""
    lines = []
    metadata = {}
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lines.append(_parse_line(v))
        elif fno == 4 and wt == 2:
            k, nm = _parse_metadata_entry(v)
            metadata[k] = nm
    return {"name": name, "lines": lines, "event_metadata": metadata}


def read_xspace(path):
    """Parse .xplane.pb file(s) into [{name, lines: [(line_name,
    [(metadata_id, duration_ps)])], event_metadata: {id: name}}].

    Given a trace DIR, reads every host's .xplane.pb in the most
    recently modified run directory (multi-host captures write one file
    per host into the same plugins/profile/<run>/)."""
    if os.path.isdir(path):
        runs = glob.glob(os.path.join(path, "plugins", "profile", "*"))
        runs = [r for r in runs
                if glob.glob(os.path.join(r, "*.xplane.pb"))]
        if not runs:
            raise FileNotFoundError(
                "no .xplane.pb under %s (pass a jax.profiler.trace "
                "output dir)" % path)
        run = max(runs, key=os.path.getmtime)
        files = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
    else:
        files = [path]
    planes = []
    for f in files:
        buf = memoryview(open(f, "rb").read())
        try:
            for fno, wt, v in _fields(buf):
                if fno == 1 and wt == 2:
                    planes.append(_parse_plane(v))
        except ValueError as e:
            raise ValueError("%s: %s" % (f, e))
    return planes


def op_totals(path, plane_re=r"/device:", line_name="XLA Ops",
              strip_suffix=True):
    """{op_name: total_duration_ps} summed over EVERY matching plane's
    op line (all chips of a multi-device trace).  ``strip_suffix``
    folds '%fusion.123' into '%fusion' families."""
    agg = collections.Counter()
    for plane in read_xspace(path):
        if not re.search(plane_re, plane["name"]):
            continue
        md = plane["event_metadata"]
        for lname, events in plane["lines"]:
            if lname != line_name:
                continue
            for meta_id, dur in events:
                name = md.get(meta_id, "#%d" % meta_id)
                name = name.split(" = ")[0]
                if strip_suffix:
                    name = re.sub(r"\.\d+$", "", name)
                agg[name] += dur
    return dict(agg)


def print_op_profile(path, top=20, **kwargs):
    """Top-N op families by device time, with shares — the quick look
    that drives kernel work."""
    agg = op_totals(path, **kwargs)
    total = sum(agg.values()) or 1
    print("%-50s %10s %7s" % ("op", "ms", "share"))
    for name, ps in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
        print("%-50s %10.3f %6.2f%%"
              % (name[:50], ps / 1e9, 100.0 * ps / total))
    return agg
