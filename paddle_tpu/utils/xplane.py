"""XPlane trace reader: aggregate per-op device time from a
``jax.profiler.trace`` capture (SURVEY §5.1 — the device-tracer half of
the profiling story; fluid/profiler.py covers the host half).

``jax.profiler.trace(dir)`` writes
``<dir>/plugins/profile/<run>/*.xplane.pb``; the usual viewer
(tensorboard-plugin-profile) needs a working TF protobuf stack, which
this environment lacks.  This module reads the XSpace container with a
minimal protobuf wire-format walker — no generated code, no
tensorflow — and reduces the "XLA Ops" line to per-op totals, which is
what perf work actually consumes (it found the flash-attention backward
and block-size wins).

Wire schema (public tensorflow/core/profiler/protobuf/xplane.proto):
XSpace.planes=1; XPlane{name=2, lines=3, event_metadata=4(map)};
XLine{name=2, events=4}; XEvent{metadata_id=1, duration_ps=3};
XEventMetadata{id=1, name=2}.
"""
from __future__ import annotations

import collections
import glob
import os
import re

__all__ = ["read_xspace", "op_totals", "print_op_profile",
           "op_profile", "category_profile", "print_category_profile",
           "kernel_profile", "print_kernel_profile",
           "device_trace_events"]


def _varint(buf, i):
    x = s = 0
    n = len(buf)
    while True:
        if i >= n:
            raise ValueError("truncated protobuf (varint past buffer)")
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer;
    length-delimited values come back as memoryview slices.  Raises
    ValueError on truncation instead of silently under-reading — a
    half-written capture must not produce quietly-wrong totals."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            v, i = _varint(buf, i)
        elif wt == 1:                    # fixed64
            if i + 8 > n:
                raise ValueError("truncated protobuf (fixed64)")
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:                    # length-delimited
            ln, i = _varint(buf, i)
            if ln > n - i:
                raise ValueError(
                    "truncated protobuf (field of %d bytes, %d left)"
                    % (ln, n - i))
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # fixed32
            if i + 4 > n:
                raise ValueError("truncated protobuf (fixed32)")
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        yield fno, wt, v


def _parse_event(buf):
    """-> (metadata_id, duration_ps, offset_ps).  offset_ps (XEvent
    field 2) positions the event within its line's timeline — the
    chrome-trace export needs it; the aggregate profiles ignore it."""
    meta_id = 0
    dur_ps = 0
    off_ps = 0
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            meta_id = v
        elif fno == 2 and wt == 0:
            off_ps = v
        elif fno == 3 and wt == 0:
            dur_ps = v
    return meta_id, dur_ps, off_ps


def _parse_line(buf):
    """-> (name, [(meta_id, dur_ps, off_ps)], timestamp_ns).
    XLine.timestamp_ns (field 3) is the line's start in unix-epoch ns,
    which is what lets device events merge onto the host spans'
    wall-clock timeline (observability/export.py)."""
    name = ""
    events = []
    ts_ns = 0
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 3 and wt == 0:
            ts_ns = v
        elif fno == 4 and wt == 2:
            events.append(_parse_event(v))
    return name, events, ts_ns


def _parse_stat(buf):
    """XStat: metadata_id=1, value oneof {double=2, uint64=3, int64=4,
    str=5, bytes=6, ref=7}.  ref values point at an XStatMetadata entry
    whose *name* holds the (deduplicated) string — the caller resolves
    them through the plane's stat-metadata table, so is_ref rides along.
    """
    mid = 0
    val = None
    is_ref = False
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            mid = v
        elif fno in (3, 4) and wt == 0:
            val = v
        elif fno == 7 and wt == 0:
            val = v
            is_ref = True
        elif fno == 2 and wt == 1:
            import struct
            val = struct.unpack("<d", v.to_bytes(8, "little"))[0]
        elif fno in (5, 6) and wt == 2:
            val = bytes(v).decode("utf-8", "replace")
    return mid, val, is_ref


def _parse_metadata_entry(buf):
    """map<int64, XEventMetadata> entry: key=1, value=2.
    XEventMetadata: id=1, name=2, display_name=4, stats=5."""
    key = 0
    name = ""
    stats = []
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            key = v
        elif fno == 2 and wt == 2:
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
                elif f2 == 5 and w2 == 2:
                    stats.append(_parse_stat(v2))
    return key, name, stats


def _parse_stat_metadata_entry(buf):
    """map<int64, XStatMetadata> entry: key=1, value=2{id=1, name=2}."""
    key = 0
    name = ""
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            key = v
        elif fno == 2 and wt == 2:
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


class _Plane(dict):
    """Plane dict whose legacy ``lines`` view — (name, [(meta_id,
    dur_ps)]) tuples — is derived from ``xlines`` on first access, so
    parsing doesn't materialize every event twice for consumers that
    never read it."""

    def __missing__(self, key):
        if key == "lines":
            v = [(ln["name"], [(m, d) for m, d, _ in ln["events"]])
                 for ln in self["xlines"]]
            self["lines"] = v
            return v
        raise KeyError(key)


def _parse_plane(buf):
    name = ""
    xlines = []     # timestamped: {name, timestamp_ns, events 3-tuples}
    metadata = {}
    stats_by_id = {}
    stat_names = {}
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lname, events, ts_ns = _parse_line(v)
            xlines.append({"name": lname, "timestamp_ns": ts_ns,
                           "events": events})
        elif fno == 4 and wt == 2:
            k, nm, stats = _parse_metadata_entry(v)
            metadata[k] = nm
            if stats:
                stats_by_id[k] = stats
        elif fno == 5 and wt == 2:
            k, nm = _parse_stat_metadata_entry(v)
            stat_names[k] = nm
    # resolve stat metadata_ids to names (and ref values to the
    # stat-metadata entry's name, the dedup convention for strings):
    # {event_metadata_id: {stat: value}}
    event_stats = {}
    for k, stats in stats_by_id.items():
        event_stats[k] = {
            stat_names.get(mid, "#%d" % mid):
                (stat_names.get(val, "#%d" % val) if is_ref else val)
            for mid, val, is_ref in stats}
    return _Plane(name=name, xlines=xlines,
                  event_metadata=metadata, event_stats=event_stats)


def read_xspace(path):
    """Parse .xplane.pb file(s) into [{name, lines: [(line_name,
    [(metadata_id, duration_ps)])], event_metadata: {id: name}}].

    Given a trace DIR, reads every host's .xplane.pb in the most
    recently modified run directory (multi-host captures write one file
    per host into the same plugins/profile/<run>/)."""
    if os.path.isdir(path):
        runs = glob.glob(os.path.join(path, "plugins", "profile", "*"))
        runs = [r for r in runs
                if glob.glob(os.path.join(r, "*.xplane.pb"))]
        if not runs:
            raise FileNotFoundError(
                "no .xplane.pb under %s (pass a jax.profiler.trace "
                "output dir)" % path)
        run = max(runs, key=os.path.getmtime)
        files = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
    else:
        files = [path]
    planes = []
    for f in files:
        buf = memoryview(open(f, "rb").read())
        try:
            for fno, wt, v in _fields(buf):
                if fno == 1 and wt == 2:
                    planes.append(_parse_plane(v))
        except ValueError as e:
            raise ValueError("%s: %s" % (f, e))
    return planes


def op_totals(path, plane_re=r"/device:", line_name="XLA Ops",
              strip_suffix=True):
    """{op_name: total_duration_ps} summed over EVERY matching plane's
    op line (all chips of a multi-device trace).  ``strip_suffix``
    folds '%fusion.123' into '%fusion' families."""
    agg = collections.Counter()
    for plane in read_xspace(path):
        if not re.search(plane_re, plane["name"]):
            continue
        md = plane["event_metadata"]
        for line in plane["xlines"]:
            if line["name"] != line_name:
                continue
            for meta_id, dur, _ in line["events"]:
                name = md.get(meta_id, "#%d" % meta_id)
                name = name.split(" = ")[0]
                if strip_suffix:
                    name = re.sub(r"\.\d+$", "", name)
                agg[name] += dur
    return dict(agg)


def print_op_profile(path, top=20, **kwargs):
    """Top-N op families by device time, with shares — the quick look
    that drives kernel work."""
    agg = op_totals(path, **kwargs)
    total = sum(agg.values()) or 1
    print("%-50s %10s %7s" % ("op", "ms", "share"))
    for name, ps in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
        print("%-50s %10.3f %6.2f%%"
              % (name[:50], ps / 1e9, 100.0 * ps / total))
    return agg


def op_profile(path, plane_re=r"/device:", line_name="XLA Ops"):
    """Per-op roofline rows from the device trace:
    [{name, category, time_ps, count, flops, bytes, source}] — the
    hlo_category / flops / bytes_accessed stats the XLA runtime attaches
    to each op's event metadata (what tensorboard's op_profile view
    shows, without the TF stack).  flops/bytes are per-execution; time_ps
    is summed over all executions in the capture."""
    rows = {}
    for plane in read_xspace(path):
        if not re.search(plane_re, plane["name"]):
            continue
        md = plane["event_metadata"]
        st = plane.get("event_stats", {})
        for line in plane["xlines"]:
            if line["name"] != line_name:
                continue
            for meta_id, dur, _ in line["events"]:
                name = md.get(meta_id, "#%d" % meta_id).split(" = ")[0]
                r = rows.get(name)
                if r is None:
                    s = st.get(meta_id, {})
                    rows[name] = r = {
                        "name": name,
                        "category": s.get("hlo_category", "?"),
                        "time_ps": 0, "count": 0,
                        "flops": s.get("flops", 0) or 0,
                        "bytes": s.get("bytes_accessed", 0) or 0,
                        "source": s.get("source", "")}
                r["time_ps"] += dur
                r["count"] += 1
    return sorted(rows.values(), key=lambda r: -r["time_ps"])


def category_profile(path, peak_tflops=197.0, peak_gbps=819.0, **kwargs):
    """Aggregate ``op_profile`` rows by hlo_category with achieved
    TFLOP/s and GB/s against the given chip peaks (defaults: TPU v5e
    bf16 / HBM).  The first stop for 'where did my step time go'."""
    cats = {}
    for r in op_profile(path, **kwargs):
        c = cats.setdefault(r["category"], {
            "category": r["category"], "time_ps": 0, "flops": 0,
            "bytes": 0, "count": 0})
        c["time_ps"] += r["time_ps"]
        c["flops"] += r["flops"] * r["count"]
        c["bytes"] += r["bytes"] * r["count"]
        c["count"] += r["count"]
    out = sorted(cats.values(), key=lambda c: -c["time_ps"])
    for c in out:
        secs = c["time_ps"] / 1e12 or 1e-12
        c["tflops_per_s"] = c["flops"] / secs / 1e12
        c["gbps"] = c["bytes"] / secs / 1e9
        c["mxu_util"] = c["tflops_per_s"] / peak_tflops
        c["hbm_util"] = c["gbps"] / peak_gbps
    return out


def print_category_profile(path, top=12, **kwargs):
    cats = category_profile(path, **kwargs)
    total = sum(c["time_ps"] for c in cats) or 1
    print("%-28s %9s %7s %9s %8s %9s %8s" % (
        "category", "ms", "share", "TFLOP/s", "mxu", "GB/s", "hbm"))
    for c in cats[:top]:
        print("%-28s %9.3f %6.2f%% %9.1f %7.1f%% %9.0f %7.1f%%" % (
            c["category"][:28], c["time_ps"] / 1e9,
            100.0 * c["time_ps"] / total, c["tflops_per_s"],
            100.0 * c["mxu_util"], c["gbps"], 100.0 * c["hbm_util"]))
    return cats


def kernel_profile(path, name_re=r".", plane_re=r"/device:",
                   line_name="XLA Ops", _all_rows=None):
    """Per-KERNEL rows (not categories) for ops matching ``name_re`` —
    the attribution ``category_profile`` cannot give for custom-calls:
    XLA's flop counter is blank inside them (Pallas kernels), so their
    achieved TFLOP/s must come from caller-supplied analytic FLOPs.
    Returns [{name, time_ps, count, ms_per_exec}] sorted by total time;
    pair with analytic per-exec FLOPs to get MXU utilization."""
    all_rows = _all_rows if _all_rows is not None else op_profile(
        path, plane_re=plane_re, line_name=line_name)
    rows = [r for r in all_rows if re.search(name_re, r["name"])]
    for r in rows:
        r["ms_per_exec"] = r["time_ps"] / 1e9 / max(r["count"], 1)
    return rows


def print_kernel_profile(path, name_re=r".", top=15, flops_per_exec=None,
                         peak_tflops=197.0, **kwargs):
    """Print per-kernel rows; ``flops_per_exec`` maps a regex to the
    analytic FLOPs of ONE execution (e.g. flash-attention tile math) to
    report achieved TFLOP/s / MXU fraction for custom-calls."""
    all_rows = op_profile(path, **kwargs)   # parse the capture ONCE
    rows = kernel_profile(path, name_re=name_re, _all_rows=all_rows,
                          **kwargs)
    total = sum(r["time_ps"] for r in all_rows) or 1
    print("%-46s %9s %6s %7s %9s %7s" % (
        "kernel", "ms", "count", "share", "TFLOP/s", "mxu"))
    for r in rows[:top]:
        tf = mxu = None
        if flops_per_exec:
            for pat, fl in flops_per_exec.items():
                if re.search(pat, r["name"]):
                    secs = r["time_ps"] / 1e12 or 1e-12
                    tf = fl * r["count"] / secs / 1e12
                    mxu = tf / peak_tflops
                    break
        print("%-46s %9.2f %6d %6.2f%% %9s %7s" % (
            r["name"][:46], r["time_ps"] / 1e9, r["count"],
            100.0 * r["time_ps"] / total,
            "%.1f" % tf if tf is not None else "-",
            "%.1f%%" % (100 * mxu) if mxu is not None else "-"))
    return rows


def device_trace_events(path, plane_re=r"/device:", line_re=r".",
                        max_events=200000):
    """Chrome-trace events (ph 'X', absolute wall µs) from the device
    planes of an xplane capture — the device half of a merged telemetry
    timeline (observability/export.py feeds these next to the host
    spans; XLine.timestamp_ns is unix-epoch based, matching the
    tracer's wall-clock anchor).  Each device plane becomes one chrome
    pid; each XLine one tid."""
    events = []
    n_planes = 0
    for plane in read_xspace(path):
        if not re.search(plane_re, plane["name"]):
            continue
        md = plane["event_metadata"]
        # one distinct chrome pid per plane, based above any real OS
        # pid (kernel.pid_max tops out at 4194304) so device tracks
        # can't collide with the host dumps' genuine pids
        pid = 10_000_000 + n_planes
        n_planes += 1
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": plane["name"]}})
        for tid, line in enumerate(plane.get("xlines", [])):
            if not re.search(line_re, line["name"]):
                continue
            base_us = line["timestamp_ns"] / 1e3
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": line["name"]}})
            for meta_id, dur_ps, off_ps in line["events"]:
                if len(events) >= max_events:
                    # no silent cap: a marker event names the drop so a
                    # merged timeline's empty tail reads as truncation,
                    # not as the device going idle
                    events.append({
                        "name": "XPLANE EVENTS TRUNCATED (max_events="
                                "%d reached; later lines/planes "
                                "dropped)" % max_events,
                        "ph": "I", "pid": pid, "tid": tid,
                        "ts": base_us + off_ps / 1e6, "s": "g",
                        "cat": "device"})
                    return events
                name = md.get(meta_id, "#%d" % meta_id).split(" = ")[0]
                events.append({
                    "name": name, "ph": "X", "pid": pid, "tid": tid,
                    "ts": base_us + off_ps / 1e6,
                    "dur": dur_ps / 1e6, "cat": "device"})
    return events
