"""Dataset adapters (parity: python/paddle/dataset/__init__.py).

Each module exposes ``train()``/``test()`` reader creators.  With no
network egress, modules parse the real files when cached under
``common.DATA_HOME`` and otherwise fall back to deterministic synthetic
data of the same shapes/dtypes (``<module>.is_synthetic()`` tells)."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import flowers  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import sentiment  # noqa: F401
from . import mq2007  # noqa: F401
from . import voc2012  # noqa: F401
from . import image  # noqa: F401

__all__ = ["common", "mnist", "cifar", "uci_housing", "flowers",
           "imdb", "imikolov", "movielens", "conll05", "wmt14", "wmt16",
           "sentiment", "mq2007", "voc2012", "image"]
