"""WMT-16 English<->German translation (parity:
python/paddle/dataset/wmt16.py — BPE-tokenized corpus with per-language
dict sizes, train/test/validation readers yielding (src ids, trg ids
with <s>, shifted trg ids), get_dict(lang, dict_size)).

Parses the real preprocessed tarball when cached; otherwise the same
deterministic permutation-cipher synthetic corpus as wmt14 (distinct
seed), so seq2seq models genuinely learn alignment.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "validation", "get_dict", "fetch",
           "is_synthetic"]

DATA_URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
DATA_MD5 = "0c38be43600334966403524a40dcd81e"

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"

_SYN = {"train": (400, 7), "test": (60, 11), "val": (60, 13)}


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(DATA_URL, "wmt16", DATA_MD5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def _syn_vocab(dict_size):
    words = [START_MARK, END_MARK, UNK_MARK] + [
        "tok%05d" % i for i in range(dict_size - 3)]
    return {w: i for i, w in enumerate(words)}


def _synthetic_reader(src_dict_size, trg_dict_size, split):
    n_sents, seed = _SYN[split]
    content = min(src_dict_size, trg_dict_size) - 3

    def reader():
        rng = np.random.RandomState(seed)
        perm = np.random.RandomState(17).permutation(content)
        for _ in range(n_sents):
            length = int(rng.randint(3, 12))
            src = rng.randint(0, content, length)
            trg = perm[src]
            src_ids = [0] + (src + 3).tolist() + [1]
            trg_core = (trg + 3).tolist()
            yield src_ids, [0] + trg_core, trg_core + [1]

    return reader


def _build_dict_from_tar(tar_path, lang, dict_size):
    # word frequencies over the train split's `lang` column
    word_freq = {}
    col = 0 if lang == "en" else 1
    with tarfile.open(tar_path) as f:
        for line in f.extractfile("wmt16/train"):
            fields = line.decode("utf-8").strip().split("\t")
            if len(fields) != 2:
                continue
            for w in fields[col].split():
                word_freq[w] = word_freq.get(w, 0) + 1
    words = [w for w, _ in sorted(word_freq.items(),
                                  key=lambda x: (-x[1], x[0]))]
    words = [START_MARK, END_MARK, UNK_MARK] + words[:dict_size - 3]
    return {w: i for i, w in enumerate(words)}


def get_dict(lang, dict_size, reverse=False):
    """word dict for ``lang`` ('en'|'de'); id->word when ``reverse``."""
    dict_size = min(dict_size, TOTAL_EN_WORDS if lang == "en"
                    else TOTAL_DE_WORDS)
    if is_synthetic():
        d = _syn_vocab(dict_size)
    else:
        d = _build_dict_from_tar(
            common.download(DATA_URL, "wmt16", DATA_MD5), lang, dict_size)
    if reverse:
        d = {v: k for k, v in d.items()}
    return d


def _real_reader(split, src_dict_size, trg_dict_size, src_lang):
    tar_path = common.download(DATA_URL, "wmt16", DATA_MD5)
    src_dict = get_dict(src_lang, src_dict_size)
    trg_lang = "de" if src_lang == "en" else "en"
    trg_dict = get_dict(trg_lang, trg_dict_size)
    src_col = 0 if src_lang == "en" else 1

    def reader():
        unk_s, unk_t = src_dict[UNK_MARK], trg_dict[UNK_MARK]
        with tarfile.open(tar_path) as f:
            for line in f.extractfile(os.path.join("wmt16", split)):
                fields = line.decode("utf-8").strip().split("\t")
                if len(fields) != 2:
                    continue
                src_words = fields[src_col].split()
                trg_words = fields[1 - src_col].split()
                src_ids = ([src_dict[START_MARK]]
                           + [src_dict.get(w, unk_s) for w in src_words]
                           + [src_dict[END_MARK]])
                trg_ids = [trg_dict.get(w, unk_t) for w in trg_words]
                yield (src_ids, [trg_dict[START_MARK]] + trg_ids,
                       trg_ids + [trg_dict[END_MARK]])

    return reader


def _creator(split):
    def make(src_dict_size, trg_dict_size, src_lang="en"):
        if src_lang not in ("en", "de"):
            raise ValueError("src_lang must be 'en' or 'de'")
        if is_synthetic():
            return _synthetic_reader(src_dict_size, trg_dict_size, split)
        return _real_reader(split, src_dict_size, trg_dict_size, src_lang)

    return make


train = _creator("train")
test = _creator("test")
validation = _creator("val")


def fetch():
    common.download(DATA_URL, "wmt16", DATA_MD5)
