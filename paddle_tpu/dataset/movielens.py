"""MovieLens-1M ratings (parity: python/paddle/dataset/movielens.py —
MovieInfo/UserInfo metadata, train()/test() yielding
[user_id, gender_id, age_index, job_id, movie_id, category_ids,
title_ids, [rating]] with rating rescaled to [-5, 5]).

Parses the real ml-1m zip when cached; otherwise a deterministic
synthetic catalog + latent-factor rating generator (ratings follow a
low-rank user x movie model), so the recommender genuinely converges.
"""
from __future__ import annotations

import random
import re
import zipfile

import numpy as np

from . import common

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id", "max_user_id",
    "max_job_id", "age_table", "movie_categories", "user_info", "movie_info",
    "MovieInfo", "UserInfo", "is_synthetic",
]

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

age_table = [1, 18, 25, 35, 45, 50, 56]

_SYN_USERS = 120
_SYN_MOVIES = 180
_SYN_CATEGORIES = ["Action", "Comedy", "Drama", "Horror", "Romance",
                   "SciFi", "Thriller", "Animation"]
_SYN_TITLE_VOCAB = 60
_SYN_RATINGS = 2400
_SYN_JOBS = 8


class MovieInfo(object):
    """Movie metadata (reference movielens.py:44)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        """[movie_id, category ids, lower-cased title word ids]."""
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __str__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)

    __repr__ = __str__


class UserInfo(object):
    """User metadata (reference movielens.py:71)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        """[user_id, gender id, age bucket index, job id]."""
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __str__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)

    __repr__ = __str__


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None
_RATINGS = None  # list of (uid, mov_id, rating); synthetic path only


def _init_synthetic():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, _RATINGS
    if MOVIE_INFO is not None:
        return
    CATEGORIES_DICT = {c: i for i, c in enumerate(_SYN_CATEGORIES)}
    MOVIE_TITLE_DICT = {"t%02d" % i: i for i in range(_SYN_TITLE_VOCAB)}
    rng = np.random.RandomState(31)
    MOVIE_INFO = {}
    for mid in range(1, _SYN_MOVIES + 1):
        n_cat = int(rng.randint(1, 4))
        cats = [_SYN_CATEGORIES[i] for i in
                rng.choice(len(_SYN_CATEGORIES), n_cat, replace=False)]
        n_tw = int(rng.randint(1, 5))
        title = " ".join("t%02d" % w for w in
                         rng.randint(0, _SYN_TITLE_VOCAB, n_tw))
        MOVIE_INFO[mid] = MovieInfo(index=mid, categories=cats, title=title)
    USER_INFO = {}
    for uid in range(1, _SYN_USERS + 1):
        USER_INFO[uid] = UserInfo(
            index=uid, gender="M" if rng.rand() < 0.5 else "F",
            age=age_table[int(rng.randint(0, len(age_table)))],
            job_id=int(rng.randint(0, _SYN_JOBS)))
    # latent-angle preference model: rating tracks the cosine between a
    # user vector and a movie vector — the same functional form the
    # book's dual-tower cos_sim recommender predicts, so it can fit it
    k = 4
    uvec = rng.randn(_SYN_USERS + 1, k)
    mvec = rng.randn(_SYN_MOVIES + 1, k)
    uvec /= np.linalg.norm(uvec, axis=1, keepdims=True)
    mvec /= np.linalg.norm(mvec, axis=1, keepdims=True)
    _RATINGS = []
    for _ in range(_SYN_RATINGS):
        uid = int(rng.randint(1, _SYN_USERS + 1))
        mid = int(rng.randint(1, _SYN_MOVIES + 1))
        cos = float(uvec[uid] @ mvec[mid])
        raw = 3.0 + 2.5 * cos + float(rng.randn()) * 0.15
        _RATINGS.append((uid, mid, min(5.0, max(1.0, round(raw)))))


def _init_real():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO
    fn = common.download(URL, "movielens", MD5)
    if MOVIE_INFO is None:
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        with zipfile.ZipFile(file=fn) as package:
            MOVIE_INFO = {}
            title_word_set, categories_set = set(), set()
            with package.open("ml-1m/movies.dat") as movie_file:
                for line in movie_file:
                    line = line.decode("latin-1")
                    movie_id, title, categories = line.strip().split("::")
                    categories = categories.split("|")
                    categories_set.update(categories)
                    title = pattern.match(title).group(1)
                    MOVIE_INFO[int(movie_id)] = MovieInfo(
                        index=movie_id, categories=categories, title=title)
                    title_word_set.update(
                        w.lower() for w in title.split())
            MOVIE_TITLE_DICT = {w: i for i, w in enumerate(title_word_set)}
            CATEGORIES_DICT = {c: i for i, c in enumerate(categories_set)}
            USER_INFO = {}
            with package.open("ml-1m/users.dat") as user_file:
                for line in user_file:
                    line = line.decode("latin-1")
                    uid, gender, age, job, _ = line.strip().split("::")
                    USER_INFO[int(uid)] = UserInfo(
                        index=uid, gender=gender, age=age, job_id=job)
    return fn


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(URL, "movielens", MD5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def _initialize():
    if is_synthetic():
        _init_synthetic()
        return None
    return _init_real()


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    fn = _initialize()
    rand = random.Random(x=rand_seed)
    if fn is None:  # synthetic
        for uid, mid, rating in _RATINGS:
            if (rand.random() < test_ratio) == is_test:
                yield (USER_INFO[uid].value() + MOVIE_INFO[mid].value()
                       + [[rating * 2 - 5.0]])
        return
    with zipfile.ZipFile(file=fn) as package:
        with package.open("ml-1m/ratings.dat") as rating_file:
            for line in rating_file:
                line = line.decode("latin-1")
                if (rand.random() < test_ratio) == is_test:
                    uid, mov_id, rating, _ = line.strip().split("::")
                    uid, mov_id = int(uid), int(mov_id)
                    rating = float(rating) * 2 - 5.0
                    yield (USER_INFO[uid].value()
                           + MOVIE_INFO[mov_id].value() + [[rating]])


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


def train():
    return __reader_creator__(is_test=False)


def test():
    return __reader_creator__(is_test=True)


def get_movie_title_dict():
    _initialize()
    return MOVIE_TITLE_DICT


def max_movie_id():
    _initialize()
    return max(MOVIE_INFO.keys())


def max_user_id():
    _initialize()
    return max(USER_INFO.keys())


def max_job_id():
    _initialize()
    return max(u.job_id for u in USER_INFO.values())


def movie_categories():
    _initialize()
    return CATEGORIES_DICT


def user_info():
    _initialize()
    return USER_INFO


def movie_info():
    _initialize()
    return MOVIE_INFO
