"""Dataset plumbing (parity: python/paddle/dataset/common.py — DATA_HOME,
download-with-md5 cache, cluster file splitting).

This environment has no network egress, so ``download`` only resolves
already-cached files; when a dataset is absent each dataset module falls
back to a DETERMINISTIC synthetic generator with the real shapes/dtypes
(clearly flagged via ``is_synthetic``), keeping pipelines and tests
runnable offline.  Drop the real files into DATA_HOME to use them.
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "download", "md5file", "split", "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def _ensure_dir(d):
    os.makedirs(d, exist_ok=True)
    return d


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum=None):
    """Return the cached path for ``url`` (reference common.py:56).  No
    egress: raises FileNotFoundError when the file is not already cached
    (callers catch it and synthesize)."""
    dirname = _ensure_dir(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(dirname, url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise IOError("md5 mismatch for cached %s" % filename)
        return filename
    raise FileNotFoundError(
        "%s is not cached under %s and this environment has no network "
        "access; the dataset module will fall back to synthetic data" %
        (url, dirname))


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickled part-files of ``line_count``
    samples (reference common.py:118)."""
    import pickle

    dumper = dumper or pickle.dump
    lines = []
    idx = 0
    for sample in reader():
        lines.append(sample)
        if len(lines) >= line_count:
            with open(suffix % idx, "wb") as f:
                dumper(lines, f)
            lines = []
            idx += 1
    if lines:
        with open(suffix % idx, "wb") as f:
            dumper(lines, f)
        idx += 1
    return idx


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over this trainer's shard of part-files (reference
    common.py:149): file i belongs to trainer ``i % trainer_count``."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample

    return reader
