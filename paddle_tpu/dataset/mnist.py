"""MNIST (parity: python/paddle/dataset/mnist.py — train()/test() readers
yielding (image[784] float32 in [-1,1], label int)).

Reads the real idx-ubyte .gz files when cached under DATA_HOME/mnist;
otherwise serves a deterministic synthetic set with identical
shapes/dtypes (``is_synthetic()`` reports which)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test", "is_synthetic"]

URL_PREFIX = "http://yann.lecun.com/exdb/mnist/"
TRAIN_IMAGE = "train-images-idx3-ubyte.gz"
TRAIN_LABEL = "train-labels-idx1-ubyte.gz"
TEST_IMAGE = "t10k-images-idx3-ubyte.gz"
TEST_LABEL = "t10k-labels-idx1-ubyte.gz"

_SYN_TRAIN = 2048
_SYN_TEST = 512


def is_synthetic():
    try:
        common.download(URL_PREFIX + TRAIN_IMAGE, "mnist")
        return False
    except FileNotFoundError:
        return True


def _idx_reader(image_gz, label_gz):
    def reader():
        with gzip.open(image_gz, "rb") as fi, gzip.open(label_gz,
                                                        "rb") as fl:
            magic, n, rows, cols = struct.unpack(">4I", fi.read(16))
            assert magic == 2051, "bad idx image magic"
            magic, nl = struct.unpack(">2I", fl.read(8))
            assert magic == 2049 and nl == n, "bad idx label file"
            per = rows * cols
            for _ in range(n):
                img = np.frombuffer(fi.read(per), np.uint8)
                lab = fl.read(1)[0]
                yield (img.astype(np.float32) / 127.5 - 1.0, int(lab))

    return reader


def _synthetic_reader(n, seed):
    """Deterministic stand-in: class-dependent blob images so models can
    actually fit it (same (784,) float32 in [-1,1] + int label API)."""

    def reader():
        rng = np.random.RandomState(seed)
        centers = np.random.RandomState(7).rand(10, 784).astype(
            np.float32)
        for _ in range(n):
            lab = int(rng.randint(0, 10))
            img = centers[lab] + rng.randn(784).astype(np.float32) * 0.3
            yield (np.clip(img, 0, 1) * 2.0 - 1.0, lab)

    return reader


def _creator(image_name, label_name, n_syn, seed):
    try:
        img = common.download(URL_PREFIX + image_name, "mnist")
        lab = common.download(URL_PREFIX + label_name, "mnist")
        return _idx_reader(img, lab)
    except FileNotFoundError:
        return _synthetic_reader(n_syn, seed)


def train():
    return _creator(TRAIN_IMAGE, TRAIN_LABEL, _SYN_TRAIN, 0)


def test():
    return _creator(TEST_IMAGE, TEST_LABEL, _SYN_TEST, 1)
