"""CoNLL-2005 semantic-role-labeling dataset (parity:
python/paddle/dataset/conll05.py — get_dict() returning word/verb/label
dicts, test() yielding the db_lstm 9-tuple: word ids, 5 context-window
feature id lists, predicate ids, mark flags, label ids).

Parses the real conll05st test split when cached; otherwise a
deterministic synthetic corpus whose labels correlate with word identity
and distance to the predicate, so the SRL model genuinely learns.
"""
from __future__ import annotations

import gzip

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test", "is_synthetic"]

DATA_URL = ("http://www.cs.upc.edu/~srlconll/conll05st-tests.tar.gz")
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st%2FwordDict.txt")
VERBDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st%2FverbDict.txt")
TRGDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st%2FtargetDict.txt")
EMB_URL = "http://paddlemodels.bj.bcebos.com/conll05st%2Femb"

UNK_IDX = 0

_SYN_WORDS = 300
_SYN_VERBS = 30
_SYN_ROLES = ["A0", "A1", "A2", "AM-TMP", "AM-LOC"]
_SYN_SENTS = 400


_IS_SYNTHETIC = None


def is_synthetic():
    """True unless EVERY required file (three dicts + the test tarball)
    is cached — a partial cache must still fall back, not crash."""
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            for url, md5 in ((WORDDICT_URL, None), (VERBDICT_URL, None),
                             (TRGDICT_URL, None), (DATA_URL, DATA_MD5)):
                common.download(url, "conll05st", md5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def _synthetic_dicts():
    word_dict = {"w%03d" % i: i for i in range(_SYN_WORDS)}
    word_dict["bos"] = _SYN_WORDS
    word_dict["eos"] = _SYN_WORDS + 1
    verb_dict = {"v%02d" % i: i for i in range(_SYN_VERBS)}
    labels = ["O", "B-V", "I-V"]
    for r in _SYN_ROLES:
        labels += ["B-" + r, "I-" + r]
    label_dict = {l: i for i, l in enumerate(labels)}
    return word_dict, verb_dict, label_dict


def load_label_dict(filename):
    d = {}
    tag_dict = set()
    with open(filename, "r") as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-"):
                tag_dict.add(line[2:])
            elif line.startswith("I-"):
                tag_dict.add(line[2:])
        # reference id layout (conll05.py:44-61): tag ids first from 0,
        # "O" LAST — artifacts trained against the published dicts
        # (embeddings, CRF transitions) depend on it.  Deviation: tags
        # are sorted here (the reference iterates a set, whose order is
        # itself unstable across interpreter runs).
        index = 0
        for tag in sorted(tag_dict):
            d["B-" + tag] = index
            index += 1
            d["I-" + tag] = index
            index += 1
        d["O"] = index
    return d


def load_dict(filename):
    d = {}
    with open(filename, "r") as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def get_dict():
    """(word_dict, verb_dict, label_dict) (reference conll05.py:201)."""
    if is_synthetic():
        return _synthetic_dicts()
    word_dict = load_dict(common.download(WORDDICT_URL, "conll05st"))
    verb_dict = load_dict(common.download(VERBDICT_URL, "conll05st"))
    label_dict = load_label_dict(common.download(TRGDICT_URL, "conll05st"))
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Path of the pre-trained word embedding file."""
    return common.download(EMB_URL, "conll05st")


def _synthetic_corpus():
    """(sentence words, predicate, BIO labels) triples.  The label of a
    word depends on its id parity and signed distance to the predicate —
    enough structure for the CRF to beat the trivial all-O guess."""
    rng = np.random.RandomState(47)
    for _ in range(_SYN_SENTS):
        length = int(rng.randint(5, 18))
        words = ["w%03d" % int(i) for i in rng.randint(0, _SYN_WORDS, length)]
        vi = int(rng.randint(0, length))
        verb = "v%02d" % int(rng.randint(0, _SYN_VERBS))
        labels = []
        for i in range(length):
            if i == vi:
                labels.append("B-V")
                continue
            role = _SYN_ROLES[int(words[i][1:]) % len(_SYN_ROLES)]
            prev_same = (labels and labels[-1].endswith(role)
                         and labels[-1] != "B-V")
            labels.append(("I-" if prev_same else "B-") + role)
        yield words, verb, labels


def _props_column_to_bio(column):
    """One predicate's props column (CoNLL-2005 span notation: ``(A0*``,
    ``*``, ``*)``, ``(V*)``) -> a BIO tag sequence."""
    bio = []
    open_tag = None
    for cell in column:
        starts = cell.startswith("(")
        ends = cell.endswith(")")
        if starts:
            open_tag = cell[1:cell.index("*")]
            bio.append("B-" + open_tag)
        elif open_tag is not None:
            bio.append("I-" + open_tag)
        else:
            bio.append("O")
        if ends:
            open_tag = None
    return bio


def corpus_reader(data_path=None, words_name=None, props_name=None):
    """Real-path corpus reader over the conll05st tarball (reference
    conll05.py:72) — yields (sentence words, predicate, BIO labels), one
    item per predicate column in the props file."""
    import tarfile

    def flush(words, prop_rows):
        if not prop_rows:
            return
        verbs = [v for v in (r[0] for r in prop_rows) if v != "-"]
        n_preds = len(prop_rows[0]) - 1
        for k in range(n_preds):
            column = [r[k + 1] for r in prop_rows]
            yield words, verbs[k], _props_column_to_bio(column)

    def reader():
        with tarfile.open(data_path) as tf:
            wf = gzip.GzipFile(fileobj=tf.extractfile(words_name))
            pf = gzip.GzipFile(fileobj=tf.extractfile(props_name))
            words, prop_rows = [], []
            # plain zip: the files are parallel by format; stopping at
            # the shorter one beats crashing on a padded None
            for wline, pline in zip(wf, pf):
                pcells = pline.strip().decode("utf-8").split()
                if not pcells:  # blank line = sentence boundary
                    yield from flush(words, prop_rows)
                    words, prop_rows = [], []
                    continue
                words.append(wline.strip().decode("utf-8"))
                prop_rows.append(pcells)
            # no trailing blank line: don't drop the last sentence
            yield from flush(words, prop_rows)

    return reader


def reader_creator(corpus_reader, word_dict=None, predicate_dict=None,
                   label_dict=None):
    """db_lstm feature extraction (reference conll05.py:146): context
    windows around the predicate, mark flags, id lookups."""

    def reader():
        for sentence, predicate, labels in corpus_reader():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * len(labels)
            if verb_index > 0:
                mark[verb_index - 1] = 1
                ctx_n1 = sentence[verb_index - 1]
            else:
                ctx_n1 = "bos"
            if verb_index > 1:
                mark[verb_index - 2] = 1
                ctx_n2 = sentence[verb_index - 2]
            else:
                ctx_n2 = "bos"
            mark[verb_index] = 1
            ctx_0 = sentence[verb_index]
            if verb_index < len(labels) - 1:
                mark[verb_index + 1] = 1
                ctx_p1 = sentence[verb_index + 1]
            else:
                ctx_p1 = "eos"
            if verb_index < len(labels) - 2:
                mark[verb_index + 2] = 1
                ctx_p2 = sentence[verb_index + 2]
            else:
                ctx_p2 = "eos"

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctx_n2_idx = [word_dict.get(ctx_n2, UNK_IDX)] * sen_len
            ctx_n1_idx = [word_dict.get(ctx_n1, UNK_IDX)] * sen_len
            ctx_0_idx = [word_dict.get(ctx_0, UNK_IDX)] * sen_len
            ctx_p1_idx = [word_dict.get(ctx_p1, UNK_IDX)] * sen_len
            ctx_p2_idx = [word_dict.get(ctx_p2, UNK_IDX)] * sen_len
            pred_idx = [predicate_dict.get(predicate, 0)] * sen_len
            # unknown labels fall back to "O" (its id is LAST in the
            # reference layout, not 0 — 0 is the first B- tag)
            o_id = label_dict.get("O", 0)
            label_idx = [label_dict.get(w, o_id) for w in labels]

            yield (word_idx, ctx_n2_idx, ctx_n1_idx, ctx_0_idx, ctx_p1_idx,
                   ctx_p2_idx, pred_idx, mark, label_idx)

    return reader


def test():
    word_dict, verb_dict, label_dict = get_dict()
    if is_synthetic():
        return reader_creator(_synthetic_corpus, word_dict=word_dict,
                              predicate_dict=verb_dict,
                              label_dict=label_dict)
    reader = corpus_reader(
        common.download(DATA_URL, "conll05st", DATA_MD5),
        words_name="conll05st-release/test.wsj/words/test.wsj.words.gz",
        props_name="conll05st-release/test.wsj/props/test.wsj.props.gz")
    return reader_creator(reader, word_dict=word_dict,
                          predicate_dict=verb_dict, label_dict=label_dict)
