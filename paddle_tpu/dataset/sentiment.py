"""NLTK movie-reviews polarity dataset (parity:
python/paddle/dataset/sentiment.py — get_word_dict() over the corpus,
train()/test() yielding (token ids, 0/1 polarity); NUM_TRAINING_INSTANCES
split).

The reference pulls the corpus through NLTK; with no egress this module
reads an nltk-format movie_reviews directory when cached under
DATA_HOME/sentiment (pos/ and neg/ subdirs of .txt files) and otherwise
serves the same class-conditional synthetic corpus recipe as
dataset.imdb (distinct seed/vocab).
"""
from __future__ import annotations

import glob
import os

import numpy as np

from . import common

__all__ = ["get_word_dict", "train", "test", "is_synthetic"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_SYN_VOCAB = 800
_DATA_DIR = os.path.join(common.DATA_HOME, "sentiment", "movie_reviews")


def is_synthetic():
    return not (os.path.isdir(os.path.join(_DATA_DIR, "pos"))
                and os.path.isdir(os.path.join(_DATA_DIR, "neg")))


def _read_corpus():
    """[(words, polarity)] — 0 = negative, 1 = positive, interleaved
    like the reference's sort_files()."""
    docs = {"neg": [], "pos": []}
    for pol in ("neg", "pos"):
        for path in sorted(glob.glob(os.path.join(_DATA_DIR, pol,
                                                  "*.txt"))):
            with open(path, "r", errors="ignore") as f:
                docs[pol].append(f.read().lower().split())
    out = []
    for neg, pos in zip(docs["neg"], docs["pos"]):
        out.append((pos, 1))
        out.append((neg, 0))
    return out


def _synthetic_corpus():
    rng = np.random.RandomState(29)
    half = _SYN_VOCAB // 2
    out = []
    for _ in range(NUM_TOTAL_INSTANCES):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 50))
        biased = rng.randint(0, half, length) + (0 if label else half)
        uniform = rng.randint(0, _SYN_VOCAB, length)
        take = rng.rand(length) < 0.75
        words = ["s%04d" % w for w in np.where(take, biased, uniform)]
        out.append((words, label))
    return out


_CORPUS = None


def _corpus():
    global _CORPUS
    if _CORPUS is None:
        _CORPUS = (_synthetic_corpus() if is_synthetic()
                   else _read_corpus())
    return _CORPUS


def _word_dict_of(corpus):
    freq = {}
    for words, _ in corpus:
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    return sorted(freq.items(), key=lambda x: (-x[1], x[0]))


def get_word_dict():
    """[(word, freq)] sorted by descending frequency — the reference
    returns this list form; index in the list is the word id."""
    return _word_dict_of(_corpus())


def _ids(corpus):
    # one corpus read serves both the dict and the id conversion
    word_idx = {w: i for i, (w, _) in enumerate(_word_dict_of(corpus))}
    return [([word_idx[w] for w in words], label)
            for words, label in corpus]


def reader_creator(data):
    def reader():
        for doc, label in data:
            yield doc, label

    return reader


def train():
    return reader_creator(_ids(_corpus())[:NUM_TRAINING_INSTANCES])


def test():
    return reader_creator(_ids(_corpus())[NUM_TRAINING_INSTANCES:])
