"""Flowers-102 (parity: python/paddle/dataset/flowers.py — train()/test()
yielding (image[3,224,224] float32, label int)).  The real dataset needs
network access; offline we serve deterministic synthetic 224x224 images
— the shape/dtype contract bench.py and ResNet training rely on."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "is_synthetic"]

URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
       "102flowers.tgz")
CLASS_DIM = 102
_SYN_TRAIN = 1024
_SYN_TEST = 128


def is_synthetic():
    try:
        common.download(URL, "flowers")
        return False
    except FileNotFoundError:
        return True


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(0, CLASS_DIM))
            # cheap deterministic texture, avoids storing n full images
            base = rng.rand(3, 14, 14).astype(np.float32)
            img = np.kron(base, np.ones((16, 16), np.float32))
            yield (img, lab)

    return reader


def _creator(n_syn, seed):
    try:
        common.download(URL, "flowers")
        raise NotImplementedError(
            "real flowers parsing requires scipy.io loadmat of the "
            "labels; cache the extracted arrays instead")
    except FileNotFoundError:
        return _synthetic_reader(n_syn, seed)


def train():
    return _creator(_SYN_TRAIN, 0)


def test():
    return _creator(_SYN_TEST, 1)
