"""CIFAR-10/100 (parity: python/paddle/dataset/cifar.py — train10/test10/
train100/test100 yielding (image[3072] float32 in [0,1], label int)).

Parses the real python-pickle tarballs when cached under
DATA_HOME/cifar; otherwise deterministic synthetic data."""
from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "is_synthetic"]

URL10 = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
URL100 = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"

_SYN_TRAIN = 2048
_SYN_TEST = 512


def is_synthetic():
    try:
        common.download(URL10, "cifar")
        return False
    except FileNotFoundError:
        return True


def _tar_reader(tar_path, sub_name):
    """Yield samples from members whose name contains sub_name
    (reference cifar.py:46)."""

    def reader():
        with tarfile.open(tar_path, "r:gz") as tf:
            names = [n for n in tf.getnames() if sub_name in n]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name),
                                    encoding="latin1")
                labels = batch.get("labels") or batch.get("fine_labels")
                for img, lab in zip(batch["data"], labels):
                    yield (np.asarray(img, np.float32) / 255.0, int(lab))

    return reader


def _synthetic_reader(n, n_classes, seed):
    def reader():
        rng = np.random.RandomState(seed)
        centers = np.random.RandomState(13).rand(
            n_classes, 3072).astype(np.float32)
        for _ in range(n):
            lab = int(rng.randint(0, n_classes))
            img = centers[lab] + rng.randn(3072).astype(np.float32) * 0.15
            yield (np.clip(img, 0.0, 1.0), lab)

    return reader


def _creator(url, sub_name, n_classes, n_syn, seed):
    try:
        return _tar_reader(common.download(url, "cifar"), sub_name)
    except FileNotFoundError:
        return _synthetic_reader(n_syn, n_classes, seed)


def train10():
    return _creator(URL10, "data_batch", 10, _SYN_TRAIN, 0)


def test10():
    return _creator(URL10, "test_batch", 10, _SYN_TEST, 1)


def train100():
    return _creator(URL100, "train", 100, _SYN_TRAIN, 2)


def test100():
    return _creator(URL100, "test", 100, _SYN_TEST, 3)
