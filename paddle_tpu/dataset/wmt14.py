"""WMT-14 French->English translation (parity:
python/paddle/dataset/wmt14.py — train(dict_size)/test(dict_size)
yielding (src ids with <s>/<e>, trg ids with <s>, shifted trg ids),
get_dict(dict_size) returning id->word maps).

Parses the real preprocessed tarball when cached; otherwise a
deterministic synthetic parallel corpus where the target is a fixed
token-level permutation-cipher of the source, so attention/seq2seq
models genuinely learn alignment.
"""
from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict", "START", "END", "UNK", "UNK_IDX",
           "is_synthetic"]

URL_TRAIN = ("http://paddlepaddle.cdn.bcebos.com/demo/wmt_shrinked_data/"
             "wmt14.tgz")
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

_SYN_SENTS_TRAIN = 400
_SYN_SENTS_TEST = 60


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def _syn_vocab(dict_size):
    # ids 0/1/2 are reserved exactly like the real dicts
    words = [START, END, UNK] + ["tok%04d" % i for i in range(dict_size - 3)]
    return {w: i for i, w in enumerate(words)}


def _synthetic_reader(dict_size, n_sents, seed):
    """Target = source mapped through a fixed permutation of the vocab
    (a learnable word-for-word 'translation')."""
    def reader():
        rng = np.random.RandomState(seed)
        content = dict_size - 3  # non-reserved ids
        perm = np.random.RandomState(9).permutation(content)
        for _ in range(n_sents):
            length = int(rng.randint(3, 12))
            src = rng.randint(0, content, length)
            trg = perm[src]
            src_ids = [0] + (src + 3).tolist() + [1]
            trg_core = (trg + 3).tolist()
            yield src_ids, [0] + trg_core, trg_core + [1]

    return reader


def __read_to_dict(tar_file, dict_size):
    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.strip().decode("utf-8")] = i
        return out

    with tarfile.open(tar_file) as f:
        names = [n for n in f.getnames() if n.endswith("src.dict")]
        assert len(names) == 1
        src_dict = to_dict(f.extractfile(names[0]), dict_size)
        names = [n for n in f.getnames() if n.endswith("trg.dict")]
        assert len(names) == 1
        trg_dict = to_dict(f.extractfile(names[0]), dict_size)
    return src_dict, trg_dict


def reader_creator(tar_file, file_name, dict_size):
    def reader():
        src_dict, trg_dict = __read_to_dict(tar_file, dict_size)
        with tarfile.open(tar_file) as f:
            names = [n for n in f.getnames() if n.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    line_split = line.strip().decode("utf-8").split("\t")
                    if len(line_split) != 2:
                        continue
                    src_words = line_split[0].split()
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in [START] + src_words + [END]]
                    trg_words = line_split[1].split()
                    trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
                    trg_ids_next = trg_ids + [trg_dict[END]]
                    trg_ids = [trg_dict[START]] + trg_ids
                    yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size):
    if is_synthetic():
        return _synthetic_reader(dict_size, _SYN_SENTS_TRAIN, seed=3)
    return reader_creator(common.download(URL_TRAIN, "wmt14", MD5_TRAIN),
                          "train/train", dict_size)


def test(dict_size):
    if is_synthetic():
        return _synthetic_reader(dict_size, _SYN_SENTS_TEST, seed=5)
    return reader_creator(common.download(URL_TRAIN, "wmt14", MD5_TRAIN),
                          "test/test", dict_size)


def get_dict(dict_size, reverse=True):
    """(src, trg) dicts; id->word when ``reverse`` (the decoder's view)."""
    if is_synthetic():
        src_dict = trg_dict = _syn_vocab(dict_size)
    else:
        tar_file = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
        src_dict, trg_dict = __read_to_dict(tar_file, dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
