"""PTB language-model dataset (parity: python/paddle/dataset/imikolov.py
— build_dict over ptb.train.txt, train/test readers in NGRAM mode
(word2vec's 5-gram tuples) or SEQ mode ((src, trg) shifted id lists)).

Parses the real simple-examples tarball when cached; otherwise a
deterministic synthetic corpus from a sparse first-order Markov chain,
so n-gram models have real structure to fit.
"""
from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test", "DataType", "is_synthetic"]

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"

_SYN_VOCAB = 200
_SYN_TRAIN_SENT = 500
_SYN_TEST_SENT = 80


class DataType(object):
    NGRAM = 1
    SEQ = 2


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(URL, "imikolov", MD5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = {}
    for line in f:
        if isinstance(line, bytes):
            line = line.decode("utf-8")
        for w in line.strip().split():
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq["<s>"] = word_freq.get("<s>", 0) + 1
        word_freq["<e>"] = word_freq.get("<e>", 0) + 1
    return word_freq


def _synthetic_sentences(n_sent, seed):
    """Markov-chain sentences: each word strongly prefers a fixed set of
    successors, so 5-gram context is predictive."""
    rng = np.random.RandomState(seed)
    succ = np.random.RandomState(3).randint(0, _SYN_VOCAB, (_SYN_VOCAB, 4))
    for _ in range(n_sent):
        length = int(rng.randint(5, 25))
        w = int(rng.randint(0, _SYN_VOCAB))
        sent = [w]
        for _ in range(length - 1):
            if rng.rand() < 0.8:
                w = int(succ[w, rng.randint(0, 4)])
            else:
                w = int(rng.randint(0, _SYN_VOCAB))
            sent.append(w)
        yield ["w%03d" % i for i in sent]


def build_dict(min_word_freq=50):
    """word -> id, most-frequent first, '<unk>' last (reference
    imikolov.py:49)."""
    if is_synthetic():
        d = {"w%03d" % i: i for i in range(_SYN_VOCAB)}
        d["<s>"] = _SYN_VOCAB
        d["<e>"] = _SYN_VOCAB + 1
        d["<unk>"] = _SYN_VOCAB + 2
        return d
    path = common.download(URL, "imikolov", MD5)
    with tarfile.open(path) as tf:
        trainf = tf.extractfile("./simple-examples/data/ptb.train.txt")
        word_freq = word_count(trainf)
    if "<unk>" in word_freq:
        word_freq.pop("<unk>")
    word_freq = [x for x in word_freq.items() if x[1] > min_word_freq]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary))
    word_idx = dict(list(zip(words, list(range(len(words))))))
    word_idx["<unk>"] = len(words)
    return word_idx


def _sentence_source(is_test):
    if is_synthetic():
        return list(_synthetic_sentences(
            _SYN_TEST_SENT if is_test else _SYN_TRAIN_SENT,
            seed=23 if is_test else 19))
    path = common.download(URL, "imikolov", MD5)
    name = ("./simple-examples/data/ptb.valid.txt" if is_test
            else "./simple-examples/data/ptb.train.txt")
    with tarfile.open(path) as tf:
        f = tf.extractfile(name)
        return [line.decode("utf-8").strip().split() for line in f]


def reader_creator(word_idx, n, data_type, is_test):
    def reader():
        unk = word_idx["<unk>"]
        for sent in _sentence_source(is_test):
            if DataType.NGRAM == data_type:
                assert n > -1, "Invalid gram length"
                ids = (["<s>"] + sent + ["<e>"])
                ids = [word_idx.get(w, unk) for w in ids]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])
            elif DataType.SEQ == data_type:
                ids = [word_idx.get(w, unk) for w in sent]
                src_seq = [word_idx["<s>"]] + ids
                trg_seq = ids + [word_idx["<e>"]]
                if n > 0 and len(src_seq) > n:
                    continue
                yield src_seq, trg_seq
            else:
                assert False, "Unknown data type"

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(word_idx, n, data_type, is_test=False)


def test(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(word_idx, n, data_type, is_test=True)
