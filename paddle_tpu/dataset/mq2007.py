"""LETOR MQ2007 learning-to-rank dataset (parity:
python/paddle/dataset/mq2007.py — Query/QueryList containers parsed
from the LETOR text format, and the pointwise/pairwise/listwise reader
generators gen_point/gen_pair/gen_list behind train()/test()).

Reads the real extracted MQ2007 fold when cached under
DATA_HOME/MQ2007/<Fold>/<split>.txt (the reference's .rar needs an
unrar the image lacks — drop the extracted text files in); otherwise a
deterministic synthetic ranking problem whose relevance is a noisy
linear function of the 46-dim feature vector.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test", "Query", "QueryList", "gen_point", "gen_pair",
           "gen_list", "query_filter", "load_from_text", "is_synthetic"]

FEATURE_DIM = 46
_SYN_QUERIES_TRAIN = 80
_SYN_QUERIES_TEST = 20
_SYN_DOCS_PER_QUERY = 12


class Query(object):
    """One query-document pair: relevance score + dense features
    (reference mq2007.py:48)."""

    def __init__(self, query_id=-1, relevance_score=-1,
                 feature_vector=None, description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []
        self.description = description

    def __str__(self):
        return "%s %s %s" % (self.relevance_score, self.query_id,
                             " ".join(str(f) for f in self.feature_vector))

    def _parse_(self, text):
        """Parse a LETOR line: '<rel> qid:<id> 1:<v> 2:<v> ... # doc'."""
        comment_position = text.find("#")
        comment = ""
        if comment_position != -1:
            comment = text[comment_position + 1:].strip()
            text = text[:comment_position]
        parts = text.strip().split()
        if len(parts) < 2:
            return None
        self.relevance_score = int(parts[0])
        self.query_id = int(parts[1].split(":")[1])
        self.feature_vector = [float(p.split(":")[1]) for p in parts[2:]]
        self.description = comment
        return self


class QueryList(object):
    """All documents of one query (reference mq2007.py:109)."""

    def __init__(self, querylist=None):
        self.query_id = -1
        self.querylist = querylist or []
        if self.querylist:
            self.query_id = self.querylist[0].query_id

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda q: -q.relevance_score)

    def _add_query(self, query):
        if self.query_id == -1:
            self.query_id = query.query_id
        elif self.query_id != query.query_id:
            raise ValueError("query in list must be of the same query_id")
        self.querylist.append(query)


def is_synthetic():
    return not os.path.isdir(os.path.join(common.DATA_HOME, "MQ2007"))


def load_from_text(filepath, shuffle=False, fill_missing=-1):
    """Parse a LETOR text file into QueryLists (reference
    mq2007.py:267)."""
    path = os.path.join(common.DATA_HOME, "MQ2007", filepath)
    querylists, querylist, prev = [], None, None
    with open(path) as f:
        for line in f:
            q = Query()._parse_(line)
            if q is None:
                continue
            if q.query_id != prev:
                if querylist is not None:
                    querylists.append(querylist)
                querylist = QueryList()
                prev = q.query_id
            querylist._add_query(q)
    if querylist is not None:
        querylists.append(querylist)
    return querylists


def _synthetic_querylists(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(21).randn(FEATURE_DIM)
    out = []
    for qid in range(n_queries):
        ql = QueryList()
        for _ in range(_SYN_DOCS_PER_QUERY):
            fv = rng.rand(FEATURE_DIM)
            raw = fv @ w + rng.randn() * 0.3
            rel = int(np.clip(np.digitize(raw, [2.0, 3.5]), 0, 2))
            ql._add_query(Query(query_id=qid, relevance_score=rel,
                                feature_vector=fv.tolist()))
        out.append(ql)
    return out


def gen_point(querylist):
    """Pointwise view: (relevance, feature vector) per document."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    for query in querylist:
        yield query.relevance_score, np.array(query.feature_vector)


def gen_pair(querylist, partial_order="full"):
    """Pairwise view: (label=+1, better_doc, worse_doc) for every
    relevance-ordered pair."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    pairs = []
    for i, query_left in enumerate(querylist):
        for query_right in querylist[i + 1:]:
            if query_left.relevance_score > query_right.relevance_score:
                pairs.append((np.array(query_left.feature_vector),
                              np.array(query_right.feature_vector)))
    for a, b in pairs:
        yield np.array([1.0]), a, b


def gen_list(querylist):
    """Listwise view: (all relevances, all feature vectors) per query."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    querylist._correct_ranking_()
    relevance = [q.relevance_score for q in querylist]
    features = [q.feature_vector for q in querylist]
    yield np.array(relevance), np.array(features)


def query_filter(querylists):
    """Drop queries whose documents are all irrelevant (reference
    mq2007.py:249)."""
    return [ql for ql in querylists
            if sum(q.relevance_score for q in ql) != 0]


_GEN = {"pointwise": gen_point, "pairwise": gen_pair,
        "listwise": gen_list}


def _creator(split, n_queries, seed):
    def make(format="pairwise"):
        gen = _GEN[format]

        def reader():
            if is_synthetic():
                querylists = _synthetic_querylists(n_queries, seed)
            else:
                querylists = load_from_text(
                    os.path.join("Fold1", split + ".txt"))
            for ql in query_filter(querylists):
                for sample in gen(ql):
                    yield sample

        return reader

    return make


train = _creator("train", _SYN_QUERIES_TRAIN, 37)
test = _creator("test", _SYN_QUERIES_TEST, 41)
