"""Image preprocessing utilities (parity:
python/paddle/dataset/image.py — load/resize/crop/flip/transform
helpers the image datasets compose).  PIL replaces the reference's cv2
(not in this image); all functions keep the reference's HWC-uint8
in / out convention with to_chw as the final CHW conversion.
"""
from __future__ import annotations

import io

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform",
]


def _pil():
    from PIL import Image
    return Image


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded image buffer to an HWC uint8 array."""
    img = _pil().open(io.BytesIO(bytes_))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file, is_color=True):
    img = _pil().open(file)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def resize_short(im, size):
    """Resize so the SHORTER edge is ``size``, keeping aspect ratio
    (reference image.py:163)."""
    h, w = im.shape[:2]
    h_new, w_new = size, size
    if h > w:
        h_new = size * h // w
    else:
        w_new = size * w // h
    pil_im = _pil().fromarray(im)
    pil_im = pil_im.resize((w_new, h_new), _pil().Resampling.LANCZOS)
    return np.asarray(pil_im)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference image.py:189)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def left_right_flip(im, is_color=True):
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> (random|center) crop (+ random flip when
    training) -> CHW float32, optionally mean-subtracted (reference
    image.py:291)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color, mean)
