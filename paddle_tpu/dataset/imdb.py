"""IMDB movie-review sentiment (parity: python/paddle/dataset/imdb.py —
build_dict/word_dict over the aclImdb tarball, train(word_idx)/
test(word_idx) yielding (token-id list, 0/1 label)).

Parses the real aclImdb tarball when cached under DATA_HOME; otherwise a
deterministic synthetic corpus with class-conditional word distributions
(positive reviews oversample the low word ids, negative the high ones),
so sentiment models genuinely learn from it.
"""
from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "word_dict", "train", "test", "is_synthetic"]

URL = ("http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz")
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

_SYN_VOCAB = 1000
_SYN_TRAIN = 600
_SYN_TEST = 120
_SYN_MAXLEN = 60


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(URL, "imdb", MD5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def tokenize(pattern):
    """Yield each matching file in the cached tarball as a token list
    (reference imdb.py:35)."""
    path = common.download(URL, "imdb", MD5)
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                data = tarf.extractfile(tf).read().decode("latin-1")
                data = data.rstrip("\n\r").translate(
                    str.maketrans("", "", string.punctuation)).lower()
                yield data.split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """word -> id by descending frequency, words rarer than ``cutoff``
    dropped, '<unk>' appended last (reference imdb.py:54)."""
    word_freq = {}
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] = word_freq.get(word, 0) + 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary))
    word_idx = dict(list(zip(words, list(range(len(words))))))
    word_idx["<unk>"] = len(words)
    return word_idx


def _synthetic_word_dict():
    d = {"w%04d" % i: i for i in range(_SYN_VOCAB)}
    d["<unk>"] = _SYN_VOCAB
    return d


def word_dict():
    try:
        return build_dict(
            re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
            150)
    except (FileNotFoundError, IOError):
        return _synthetic_word_dict()


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        half = _SYN_VOCAB // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, _SYN_MAXLEN))
            # positive (label 0) docs skew to ids [0, half), negative
            # (label 1) to [half, V)
            biased = rng.randint(0, half, length) + (half if label else 0)
            uniform = rng.randint(0, _SYN_VOCAB, length)
            take = rng.rand(length) < 0.75
            doc = np.where(take, biased, uniform).astype(np.int64)
            yield doc.tolist(), label

    return reader


def _real_reader(pos_pattern, neg_pattern, word_idx):
    unk = word_idx["<unk>"]

    def load(pattern, out, label):
        for doc in tokenize(pattern):
            out.append(([word_idx.get(w, unk) for w in doc], label))

    def reader():
        data = []
        load(pos_pattern, data, 0)
        load(neg_pattern, data, 1)
        for doc, label in data:
            yield doc, label

    return reader


def train(word_idx):
    """(token ids, label) per review; label 0 = positive like the
    reference (reference imdb.py:92)."""
    if is_synthetic():
        return _synthetic_reader(_SYN_TRAIN, seed=11)
    return _real_reader(re.compile(r"aclImdb/train/pos/.*\.txt$"),
                        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    if is_synthetic():
        return _synthetic_reader(_SYN_TEST, seed=13)
    return _real_reader(re.compile(r"aclImdb/test/pos/.*\.txt$"),
                        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)
