"""Pascal VOC2012 segmentation dataset (parity:
python/paddle/dataset/voc2012.py — train()/test()/val() yielding
(image HWC uint8, segmentation mask HW uint8) pairs from the
VOCtrainval tarball).

Reads the real tarball when cached; otherwise deterministic synthetic
scenes — random rectangles of the 20 VOC classes painted onto both the
image and the mask, so segmentation models have consistent
pixel-labeled structure to fit.
"""
from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val", "is_synthetic"]

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"
SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
CACHE_DIR = "voc2012"

N_CLASSES = 21  # background + 20 object classes
_SYN = {"trainval": (80, 53), "train": (60, 59), "val": (20, 61)}
_SYN_HW = 96


_IS_SYNTHETIC = None


def is_synthetic():
    global _IS_SYNTHETIC
    if _IS_SYNTHETIC is None:
        try:
            common.download(VOC_URL, CACHE_DIR, VOC_MD5)
            _IS_SYNTHETIC = False
        except (FileNotFoundError, IOError):
            _IS_SYNTHETIC = True
    return _IS_SYNTHETIC


def _synthetic_reader(sub_name):
    n, seed = _SYN[sub_name]

    def reader():
        rng = np.random.RandomState(seed)
        palette = np.random.RandomState(2).randint(
            40, 255, (N_CLASSES, 3)).astype(np.uint8)
        for _ in range(n):
            img = rng.randint(0, 40, (_SYN_HW, _SYN_HW, 3)).astype(
                np.uint8)
            mask = np.zeros((_SYN_HW, _SYN_HW), np.uint8)
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, N_CLASSES))
                h0, w0 = rng.randint(0, _SYN_HW - 16, 2)
                h1 = h0 + int(rng.randint(12, 40))
                w1 = w0 + int(rng.randint(12, 40))
                img[h0:h1, w0:w1] = palette[cls]
                mask[h0:h1, w0:w1] = cls
            yield img, mask

    return reader


def reader_creator(filename, sub_name):
    from PIL import Image

    tarobject = tarfile.open(filename)
    name2mem = {ele.name: ele for ele in tarobject.getmembers()}

    def reader():
        sets = tarobject.extractfile(name2mem[SET_FILE.format(sub_name)])
        for line in sets:
            line = line.strip().decode("utf-8")
            data = tarobject.extractfile(
                name2mem[DATA_FILE.format(line)]).read()
            label = tarobject.extractfile(
                name2mem[LABEL_FILE.format(line)]).read()
            yield (np.array(Image.open(io.BytesIO(data))),
                   np.array(Image.open(io.BytesIO(label))))

    return reader


def _creator(sub_name):
    def make():
        if is_synthetic():
            return _synthetic_reader(sub_name)
        return reader_creator(
            common.download(VOC_URL, CACHE_DIR, VOC_MD5), sub_name)

    return make


train = _creator("trainval")
test = _creator("train")
val = _creator("val")
