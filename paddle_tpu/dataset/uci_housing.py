"""UCI housing (parity: python/paddle/dataset/uci_housing.py —
train()/test() yielding (features[13] float32 normalized, price[1])).

Parses the real whitespace table when cached; otherwise a deterministic
synthetic linear-model dataset (so fit_a_line actually fits)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "is_synthetic"]

URL = ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
       "housing/housing.data")
FEATURE_DIM = 13
_TRAIN_RATIO = 0.8
_SYN_N = 640


def is_synthetic():
    try:
        common.download(URL, "uci_housing")
        return False
    except FileNotFoundError:
        return True


def _load_real():
    path = common.download(URL, "uci_housing")
    data = np.loadtxt(path).astype(np.float32)
    feats = data[:, :-1]
    # feature-wise normalize like reference feature_range()
    mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
    feats = (feats - avg) / np.where(mx > mn, mx - mn, 1.0)
    return np.concatenate([feats, data[:, -1:]], axis=1)


def _load_synthetic():
    rng = np.random.RandomState(42)
    x = rng.randn(_SYN_N, FEATURE_DIM).astype(np.float32)
    w = np.random.RandomState(7).randn(FEATURE_DIM, 1).astype(np.float32)
    y = x @ w + 3.0 + rng.randn(_SYN_N, 1).astype(np.float32) * 0.1
    return np.concatenate([x, y], axis=1)


def _data():
    try:
        return _load_real()
    except FileNotFoundError:
        return _load_synthetic()


def _creator(start_frac, end_frac):
    def reader():
        d = _data()
        n = d.shape[0]
        for row in d[int(n * start_frac):int(n * end_frac)]:
            yield (row[:-1], row[-1:])

    return reader


def train():
    return _creator(0.0, _TRAIN_RATIO)


def test():
    return _creator(_TRAIN_RATIO, 1.0)
