"""Host-side distributed runtime: the parameter-server RPC path.

Parity: reference operators/detail/ (grpc_client.h:168, grpc_server.cc,
send_recv.proto SendRecvService) — the gRPC transport between trainers and
parameter servers.  Device-side collectives (the "nccl2 mode" analog) are
XLA/GSPMD collectives over the mesh instead (paddle_tpu/parallel/).
"""
from .rpc import RPCClient, VariableServer  # noqa: F401
