"""Multi-host collective bootstrap — the "nccl2 mode" analog.

Parity: reference operators/gen_nccl_id_op.cc + platform/nccl_helper.h:81
(NCCLContextMap) and trainer.py:_transpile_nccl2_dist's env contract:
PADDLE_TRAINER_IPS / PADDLE_PSERVER_PORT / PADDLE_TRAINER_ID elect a
root that broadcasts the NCCL unique id, then every process joins one
flat communicator.

TPU-native redesign: `jax.distributed.initialize` plays the
gen_nccl_id role — process 0 is the coordinator, every host connects,
and afterwards `jax.devices()` spans ALL hosts so one Mesh covers the
whole slice and GSPMD lays collectives onto ICI/DCN (there is no
rank-to-device map to manage; that was NCCLContextMap's job).

This module translates the reference's env contract (and the newer
PADDLE_TRAINER_ENDPOINTS form) into the initialize() call.  On a
single-host run with no env set it is a no-op, so code can call
``init_collective_env`` unconditionally.
"""
from __future__ import annotations

import os

__all__ = ["init_collective_env", "collective_env", "global_mesh",
           "is_initialized"]


def is_initialized():
    """True once this process has joined a jax.distributed world."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        return False


def collective_env(environ=None):
    """Parse the reference env contract -> (coordinator, num_processes,
    process_id) or None when not configured.

    Supported forms:
      PADDLE_TRAINER_ENDPOINTS=ip1:p,ip2:p + PADDLE_CURRENT_ENDPOINT
      PADDLE_TRAINER_IPS=ip1,ip2 + PADDLE_PSERVER_PORT + POD_IP
    plus PADDLE_TRAINER_ID in both (reference trainer.py:199-214).
    """
    env = environ if environ is not None else os.environ
    eps = env.get("PADDLE_TRAINER_ENDPOINTS")
    if not eps:
        ips = env.get("PADDLE_TRAINER_IPS")
        port = env.get("PADDLE_PSERVER_PORT")
        if not ips or not port:
            return None
        eps = ",".join(ip + ":" + port for ip in ips.split(","))
    endpoints = [e.strip() for e in eps.split(",") if e.strip()]
    if not endpoints:
        return None
    tid = env.get("PADDLE_TRAINER_ID")
    if tid is None:
        cur = env.get("PADDLE_CURRENT_ENDPOINT") or (
            (env.get("POD_IP", "") + ":" +
             env.get("PADDLE_PSERVER_PORT", "")))
        if cur not in endpoints:
            # fail FAST: silently degrading to single-host would leave
            # every other host blocked in jax.distributed.initialize
            raise ValueError(
                "collective endpoints %r are configured but this host's "
                "endpoint %r is not among them (check "
                "PADDLE_CURRENT_ENDPOINT / POD_IP)" % (endpoints, cur))
        tid = endpoints.index(cur)
    return endpoints[0], len(endpoints), int(tid)


def init_collective_env(environ=None, **kwargs):
    """Join the multi-host collective if the env contract is present.

    Returns (num_processes, process_id); (1, 0) when unconfigured (the
    single-host no-op).  After a successful join, jax.devices() spans
    every host: build the global Mesh with parallel.make_mesh as usual.
    """
    parsed = collective_env(environ)
    if parsed is None:
        return 1, 0
    coordinator, num_processes, process_id = parsed
    if num_processes == 1:
        return 1, 0
    if is_initialized():  # idempotent: the caller may have joined already
        return num_processes, process_id
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id, **kwargs)
    return num_processes, process_id


def global_mesh(axes=None):
    """Mesh over every device of every joined host.  Default: one 'dp'
    axis spanning the slice (the reference's flat nccl2 world)."""
    import jax

    from paddle_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    return make_mesh(axes or {"dp": n})
