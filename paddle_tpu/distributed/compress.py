"""Gradient compression codecs for the pserver wire (ISSUE 10).

The fastwire data plane already ships raw numpy payloads with a
per-tensor (round, sender, seq) identity; this module supplies the
negotiated per-frame codecs layered on top (Lin et al., ICLR'18 "Deep
Gradient Compression"; Li et al., OSDI'14 bounded-staleness PS):

  fp16   dense f32 -> half precision.  Bit-exact on fp16-representable
         values; stateless (no error feedback).
  int8   per-chunk symmetric linear quantization (scale = absmax/127
         over CHUNK-element chunks).  The trainer keeps the
         quantization residual per (endpoint, grad) and folds it into
         the NEXT round's grad (error feedback), so the rounding bias
         cancels instead of compounding.
  topk   top-k magnitude sparsification of a dense grad: int32 indices
         + values of the largest-|g| entries; everything else stays in
         the error-feedback residual (DGC's 100-600x regime at
         ratio=0.001-0.01).
  rows   SelectedRows: per-row int8 values + DELTA-encoded int32 row
         ids (ids are sorted; consecutive deltas of power-law CTR
         batches are small).  Applied to sparse grads under any
         non-empty FLAGS_dist_compress.

Decompression happens server-side at frame-decode time
(rpc._dec_tensor), BEFORE aggregation — dedup/replay/durable-barrier
semantics operate on decoded tensors exactly as on raw frames, and a
replay ships the cached Compressed object so retried bytes are
bit-identical.

A ``Compressed`` travels on the wire as frame kind 2 (wire-format v2;
see rpc.py).  Old servers never see one: the client probes WireVersion
per endpoint and falls back to raw frames (MIGRATION.md).
"""
from __future__ import annotations

import numpy as np

__all__ = ["Compressed", "compress", "decompress", "wire_nbytes",
           "CODECS", "MIN_COMPRESS_ELEMS", "quantize_symmetric"]

# codec ids (wire bytes — append-only, never renumber)
FP16, INT8, TOPK, ROWS, ROWS16 = 1, 2, 3, 4, 5
CODECS = {"fp16": FP16, "int8": INT8, "topk": TOPK, "rows": ROWS,
          "rows16": ROWS16}
_NAMES = {v: k for k, v in CODECS.items()}

# tensors below this element count ship raw: codec headers + scales
# would GROW a bias vector, and the win lives in the big shards
MIN_COMPRESS_ELEMS = 512

# int8 quantization granularity: one f32 scale per CHUNK elements
# (0.2% overhead) — coarse enough to stay cheap, fine enough that one
# outlier element cannot flatten a 100 MB grad's resolution
CHUNK = 2048


class Compressed:
    """A codec'd tensor payload: codec id, reconstruction metadata,
    and the codec's numpy arrays (shipped zero-copy like any payload).
    ``height >= 0`` marks a SelectedRows reconstruction."""

    __slots__ = ("codec", "param", "dtype", "shape", "height", "arrays")

    def __init__(self, codec, param, dtype, shape, height, arrays):
        self.codec = int(codec)
        self.param = int(param)
        self.dtype = np.dtype(dtype)
        self.shape = tuple(int(d) for d in shape)
        self.height = int(height)
        self.arrays = list(arrays)

    @property
    def nbytes(self):
        """Compressed payload bytes (the wire-effectiveness number the
        wire_bytes_compressed_total counter reports)."""
        return sum(a.nbytes for a in self.arrays)

    def __repr__(self):
        return "Compressed(%s, shape=%s, %d bytes)" % (
            _NAMES.get(self.codec, self.codec), self.shape, self.nbytes)


def wire_nbytes(value):
    """Raw payload bytes of a to-be-sent value (dense, SelectedRows, or
    Compressed) — the numerator of the effective compression ratio."""
    from paddle_tpu.core.selected_rows import SelectedRows

    if isinstance(value, Compressed):
        return value.nbytes
    if isinstance(value, SelectedRows):
        return (np.asarray(value.rows).nbytes
                + np.asarray(value.values).nbytes)
    return np.asarray(value).nbytes


def _compressible(arr):
    return (arr.dtype in (np.float32, np.float64)
            and arr.size >= MIN_COMPRESS_ELEMS)


def _fp16(arr):
    return Compressed(FP16, 0, arr.dtype, arr.shape, -1,
                      [np.ascontiguousarray(arr, np.float16)])


def quantize_symmetric(chunks):
    """Per-chunk symmetric int8 quantization of ``chunks`` [n, chunk]:
    scale = absmax/127 per row (1.0 for all-zero rows so dequant stays
    exact zeros).  Returns (q int8 [n, chunk], scales f32 [n]).  The ONE
    quantizer definition shared by the wire codecs below and the
    serving tier's int8 weight-quantized matmuls
    (kernels/matmul_fused.quantize_weight) — keep the rounding rule in
    one place so a wire-parity bound proven here transfers there."""
    chunks = np.ascontiguousarray(chunks, np.float32)
    absmax = np.abs(chunks).max(axis=1) if chunks.shape[0] else \
        np.zeros(0, np.float32)
    scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(chunks / scales[:, None]), -127, 127) \
        .astype(np.int8)
    return q, scales


def _int8(arr):
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    n = flat.size
    nchunks = -(-n // CHUNK)
    padded = np.zeros(nchunks * CHUNK, np.float32)
    padded[:n] = flat
    q, scales = quantize_symmetric(padded.reshape(nchunks, CHUNK))
    return Compressed(INT8, CHUNK, arr.dtype, arr.shape, -1,
                      [q.reshape(-1)[:n], scales])


def _topk(arr, ratio):
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    k = max(1, int(round(float(ratio) * flat.size)))
    idx = np.argpartition(np.abs(flat), flat.size - k)[flat.size - k:]
    idx = np.sort(idx).astype(np.int32)
    return Compressed(TOPK, k, arr.dtype, arr.shape, -1,
                      [idx, flat[idx]])


def _delta_ids(rows):
    """(sort order, int32 deltas of the sorted ids) — the ONE id
    encoding shared by every SelectedRows codec: stable sort, first
    delta = first id, unsafe-cast consecutive differences (the int32
    range guard lives in compress())."""
    rows = np.asarray(rows, np.int64)
    order = np.argsort(rows, kind="stable")
    rows = rows[order]
    deltas = np.empty(rows.shape, np.int32)
    if rows.size:
        deltas[0] = rows[0]
        np.subtract(rows[1:], rows[:-1], out=deltas[1:],
                    casting="unsafe")
    return order, deltas


def _rows(sr):
    """SelectedRows: sorted delta-encoded int32 ids + per-row int8
    values.  Sorting permutes (rows, values) TOGETHER; scatter-add
    aggregation is permutation-invariant up to fp rounding order."""
    values = np.ascontiguousarray(np.asarray(sr.values), np.float32)
    order, deltas = _delta_ids(sr.rows)
    values = values[order] if values.ndim else values
    n = order.size
    vflat = values.reshape(n, -1) if n else values.reshape(0, -1)
    q, scales = quantize_symmetric(vflat)
    return Compressed(ROWS, 0, np.asarray(sr.values).dtype,
                      np.asarray(sr.values).shape, sr.height,
                      [deltas, scales, q])


def _rows16(sr):
    """SelectedRows under the fp16 mode: delta-encoded int32 ids +
    half-precision values — a 10x-cheaper encode than the per-row int8
    quantization, for rigs where codec CPU, not wire bytes, bounds the
    round (the CTR leader's upload path)."""
    order, deltas = _delta_ids(sr.rows)
    values = np.ascontiguousarray(
        np.asarray(sr.values)[order], np.float16)
    return Compressed(ROWS16, 0, np.asarray(sr.values).dtype,
                      np.asarray(sr.values).shape, sr.height,
                      [deltas, values])


def compress(value, mode, topk_ratio=0.01):
    """Encode ``value`` under codec ``mode`` ('fp16'/'int8'/'topk').
    Returns the original value untouched when the codec does not apply
    (non-float, tiny, or int64 row ids past int32 range) — the frame
    then ships raw, which every server accepts."""
    from paddle_tpu.core.selected_rows import SelectedRows

    if isinstance(value, Compressed):
        return value
    if isinstance(value, SelectedRows):
        if (not mode or np.asarray(value.values).dtype
                not in (np.float32, np.float64)
                or value.height >= (1 << 31) or
                np.asarray(value.rows).size == 0):
            return value
        return _rows16(value) if mode == "fp16" else _rows(value)
    arr = np.asarray(value)
    if not mode or not _compressible(arr):
        return value
    if mode == "fp16":
        return _fp16(arr)
    if mode == "int8":
        return _int8(arr)
    if mode == "topk":
        return _topk(arr, topk_ratio)
    raise ValueError("unknown FLAGS_dist_compress mode %r "
                     "(want ''/fp16/int8/topk)" % mode)


def decompress(c):
    """Compressed -> dense ndarray or SelectedRows (the server-side
    half; also used trainer-side to form the error-feedback residual)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    if c.codec == FP16:
        return np.ascontiguousarray(c.arrays[0], c.dtype) \
            .reshape(c.shape)
    if c.codec == INT8:
        q, scales = c.arrays
        n = int(np.prod(c.shape)) if c.shape else 1
        chunk = c.param or CHUNK
        nchunks = len(scales)
        padded = np.zeros(nchunks * chunk, np.float32)
        padded[:n] = np.asarray(q, np.float32)
        out = (padded.reshape(nchunks, chunk)
               * np.asarray(scales)[:, None]).reshape(-1)[:n]
        return np.ascontiguousarray(out, c.dtype).reshape(c.shape)
    if c.codec == TOPK:
        idx, vals = c.arrays
        out = np.zeros(int(np.prod(c.shape)) if c.shape else 1,
                       np.float32)
        out[np.asarray(idx, np.int64)] = vals
        return np.ascontiguousarray(out, c.dtype).reshape(c.shape)
    if c.codec == ROWS:
        deltas, scales, q = c.arrays
        rows = np.cumsum(np.asarray(deltas, np.int64))
        vals = (np.asarray(q, np.float32)
                * np.asarray(scales)[:, None])
        vals = np.ascontiguousarray(vals, c.dtype).reshape(
            (rows.size,) + tuple(c.shape[1:]))
        return SelectedRows(rows, vals, c.height)
    if c.codec == ROWS16:
        deltas, vals16 = c.arrays
        rows = np.cumsum(np.asarray(deltas, np.int64))
        vals = np.ascontiguousarray(vals16, c.dtype).reshape(
            (rows.size,) + tuple(c.shape[1:]))
        return SelectedRows(rows, vals, c.height)
    raise ValueError("unknown codec id %d on the wire (a newer peer? "
                     "negotiation should have prevented this)" % c.codec)
