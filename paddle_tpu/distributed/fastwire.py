"""fastwire: native raw-socket data plane for the pserver.

Role parity: reference paddle/pserver/LightNetwork.cpp (zero-copy
socket channels under ParameterServer2).  The Python gRPC transport
measured 0.33 dense rounds/s on a 104.9 MB parameter
(PSERVER_BENCH.json round 4, transport-bound); this module moves the
BULK frames (SendVariable / GetVariable payloads) onto raw TCP driven
by a ~100-line C library (fastwire.c, self-built like
recordio/recordio.cc) whose send/recv loops run with the GIL released,
so concurrent shard streams actually overlap.  Control traffic
(barriers, profile toggles, completion) stays on gRPC — the classic
control-plane/data-plane split.

Protocol per message (little-endian):
    magic 'FW1\\n' (once per connection, both directions)
    u8 method  (1=SendVariable, 2=GetVariable)
    u64 payload length | payload   (the rpc.py _enc_tensor/_enc_msg frame)
    reply: u64 length | payload
The server dispatches to the SAME ParameterServer handlers as gRPC.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

__all__ = ["native_available", "FastServer", "FastConnPool"]

MAGIC = b"FW1\n"
METHODS = {"SendVariable": 1, "GetVariable": 2}

_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "fastwire.c")
    so = os.path.join(here, "libfastwire.so")
    try:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            # per-pid tmp: a trainer and a pserver starting on one host
            # both self-build — a shared tmp name could interleave the
            # two compilers' writes and install a torn .so; distinct
            # tmps + atomic os.replace means last-writer-wins with a
            # whole file either way
            tmp = "%s.tmp.%d" % (so, os.getpid())
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        lib = ctypes.CDLL(so)
        lib.fw_listen.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_int]
        lib.fw_listen.restype = ctypes.c_int
        lib.fw_accept.argtypes = [ctypes.c_int]
        lib.fw_accept.restype = ctypes.c_int
        lib.fw_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fw_connect.restype = ctypes.c_int
        lib.fw_send.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_longlong]
        lib.fw_send.restype = ctypes.c_longlong
        lib.fw_recv.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                ctypes.c_longlong]  # addr via addressof
        lib.fw_recv.restype = ctypes.c_longlong
        lib.fw_close.argtypes = [ctypes.c_int]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def native_available():
    return _load() is not None


def _send_bytes(lib, fd, parts):
    """Small heads join; a large payload part goes out ZERO-COPY via
    its own fw_send (ctypes passes the bytes pointer straight through;
    TCP_NODELAY + a final big write keeps syscall count low)."""
    for p in parts:
        b = p if isinstance(p, (bytes, bytearray)) else bytes(p)
        if lib.fw_send(fd, b, len(b)) != len(b):
            raise ConnectionError("fastwire send failed")


def _recv_exact(lib, fd, n):
    """Receive exactly n bytes into a fresh buffer; returns a
    memoryview over it (no trailing copy — .raw would double the
    payload memory traffic)."""
    buf = bytearray(n)
    c = (ctypes.c_char * n).from_buffer(buf)
    got = lib.fw_recv(fd, ctypes.addressof(c), n)
    del c
    if got != n:
        raise ConnectionError("fastwire recv failed (%d of %d)" % (got, n))
    return memoryview(buf)


class FastServer:
    """Accept loop + per-connection dispatch threads.  ``handlers`` is
    {method_name: fn(payload_bytes) -> reply_bytes} — the pserver's
    existing gRPC handler functions, unchanged."""

    def __init__(self, port, handlers, addr="0.0.0.0"):
        lib = _load()
        if lib is None:
            raise RuntimeError("fastwire native library unavailable")
        self._lib = lib
        self._handlers = {METHODS[k]: v for k, v in handlers.items()}
        self._lfd = lib.fw_listen(addr.encode(), int(port), 64)
        if self._lfd < 0:
            raise OSError("fastwire listen failed on %s:%d (%d)"
                          % (addr, port, self._lfd))
        self.port = int(port)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            fd = self._lib.fw_accept(self._lfd)
            if fd < 0:
                break
            threading.Thread(target=self._serve_conn, args=(fd,),
                             daemon=True).start()

    def _serve_conn(self, fd):
        lib = self._lib
        try:
            if bytes(_recv_exact(lib, fd, len(MAGIC))) != MAGIC:
                return
            _send_bytes(lib, fd, [MAGIC])
            while not self._stop.is_set():
                hdr = ctypes.create_string_buffer(9)
                head = lib.fw_recv(fd, ctypes.addressof(hdr), 9)
                if head == 0:
                    return                # orderly close between messages
                if head != 9:
                    return
                method, ln = struct.unpack("<BQ", hdr.raw)
                payload = _recv_exact(lib, fd, ln)
                fn = self._handlers.get(method)
                if fn is None:
                    return
                reply = fn(payload) or b""
                _send_bytes(lib, fd,
                            [struct.pack("<Q", len(reply)), reply])
        except ConnectionError:
            pass
        finally:
            lib.fw_close(fd)

    def stop(self):
        self._stop.set()
        self._lib.fw_close(self._lfd)


class _Conn:
    def __init__(self, lib, fd):
        self.lib = lib
        self.fd = fd

    def call(self, method, payload):
        """One round-trip.  A ConnectionError raised BEFORE the payload
        went out carries .sent_payload=False (safe to retry on a fresh
        connection — a stale pooled socket); after it, True: the server
        may have consumed and APPLIED the frame, so the caller must NOT
        resend (a duplicated SendVariable gradient would silently skew
        the sync average)."""
        head = struct.pack("<BQ", METHODS[method], len(payload))
        try:
            _send_bytes(self.lib, self.fd, [head])
        except ConnectionError as e:
            e.sent_payload = False
            raise
        try:
            _send_bytes(self.lib, self.fd, [payload])
            (ln,) = struct.unpack("<Q",
                                  _recv_exact(self.lib, self.fd, 8))
            return _recv_exact(self.lib, self.fd, ln)
        except ConnectionError as e:
            e.sent_payload = True
            raise

    def close(self):
        self.lib.fw_close(self.fd)


class FastConnPool:
    """Client side: per-endpoint connection pool.  Endpoints that fail
    the magic handshake (an old server, a foreign listener) are marked
    dead and the caller falls back to gRPC for good."""

    def __init__(self, port_offset=2000):
        self.port_offset = int(port_offset)
        self._idle = {}
        self._dead = set()
        self._lock = threading.Lock()

    def _connect(self, ep):
        """Returns a _Conn, None (transient: connect refused — retry
        next round, the pserver may still be binding), or "foreign"
        (a listener answered but failed the magic — permanently not a
        fastwire endpoint)."""
        lib = _load()
        if lib is None:
            return "foreign"
        host, port = ep.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        fd = lib.fw_connect(host.encode(), int(port) + self.port_offset)
        if fd < 0:
            return None
        try:
            _send_bytes(lib, fd, [MAGIC])
            if bytes(_recv_exact(lib, fd, len(MAGIC))) != MAGIC:
                lib.fw_close(fd)
                return "foreign"
        except ConnectionError:
            lib.fw_close(fd)
            return "foreign"   # answered, then hung up mid-handshake
        return _Conn(lib, fd)

    def checkout(self, ep):
        """A ready connection, or None when the endpoint has no fast
        data plane right now (caller uses gRPC for this round).  Only a
        listener that FAILS the magic handshake is marked permanently
        dead; a refused connect is the pserver/trainer startup race and
        retries next round."""
        with self._lock:
            if ep in self._dead:
                return None
            conns = self._idle.get(ep)
            if conns:
                return conns.pop()
        conn = self._connect(ep)
        if conn == "foreign":
            with self._lock:
                self._dead.add(ep)
            return None
        return conn

    def checkin(self, ep, conn):
        with self._lock:
            self._idle.setdefault(ep, []).append(conn)

    def discard(self, conn):
        try:
            conn.close()
        except Exception:
            pass

    def close(self):
        with self._lock:
            for conns in self._idle.values():
                for c in conns:
                    self.discard(c)
            self._idle.clear()
