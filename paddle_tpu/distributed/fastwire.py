"""fastwire: native raw-socket data plane for the pserver.

Role parity: reference paddle/pserver/LightNetwork.cpp (zero-copy
socket channels under ParameterServer2).  The Python gRPC transport
measured 0.33 dense rounds/s on a 104.9 MB parameter
(PSERVER_BENCH.json round 4, transport-bound); this module moves the
BULK frames (SendVariable / GetVariable payloads) onto raw TCP driven
by a ~100-line C library (fastwire.c, self-built like
recordio/recordio.cc) whose send/recv loops run with the GIL released,
so concurrent shard streams actually overlap.  Control traffic
(barriers, profile toggles, completion) stays on gRPC — the classic
control-plane/data-plane split.

Protocol per message (little-endian):
    magic 'FW1\\n' (once per connection, both directions)
    u8 method  (1=SendVariable, 2=GetVariable, 3=SendVariables,
                4=GetVariables)
    u64 payload length | payload   (the rpc.py _enc_tensor/_enc_msg frame)
    reply: u64 length | payload
The server dispatches to the SAME ParameterServer handlers as gRPC.

Batched extensions (the PSERVER_BENCH send->apply->get round):
- Requests may be handed over as a PARTS LIST (bytes heads + numpy
  payload arrays); they go out in one vectored fw_sendv without ever
  being joined into a Python-level buffer (the reference's zero-copy
  LightNetwork sends).
- A STREAM-mode server handler (GetVariables) writes its reply as a
  sequence of length-prefixed frames, each emitted the moment that
  shard is ready, instead of one gated reply; the client consumes them
  with ``call_stream``.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

from paddle_tpu.core import sanitizer as _san

__all__ = ["native_available", "FastServer", "FastConnPool"]

from paddle_tpu.observability import metrics as _obs_metrics

# always-on wire byte counters, incremented per FRAME (never per byte):
# the "bytes on wire" half of the telemetry metrics next to rpc.py's
# payload counters
_M_TX = _obs_metrics.counter(
    "fastwire_bytes_sent_total", "bytes written to fastwire sockets")
_M_RX = _obs_metrics.counter(
    "fastwire_bytes_recv_total", "bytes read from fastwire sockets")
# socket-population ledger (ISSUE 12): every accepted fastwire
# connection holds one server thread for its lifetime, so the live
# connection count IS the server's socket backlog resource — at 256
# trainers it is the thread bill the scale lab charts.  In-flight
# counts dispatches currently inside a handler (queue depth behind
# the server lock).  Tracked as ABSOLUTE module counts and .set()
# into the gauges (delta inc/dec would stick negative after any
# mid-run metrics.zero_all() — the kv_cache.py:BlockPool lesson);
# per-connection / per-frame cadence, same budget class as the byte
# counters above.
_M_CONNS = _obs_metrics.gauge(
    "fastwire_server_conns",
    "live accepted fastwire connections (one server thread each)")
_M_INFLIGHT = _obs_metrics.gauge(
    "fastwire_inflight_requests",
    "fastwire frames currently inside a server handler")
_live_lock = threading.Lock()  # rawlock: ok - process-wide metrics registry, pre-import of sanitizer modes
_live = {"conns": 0, "inflight": 0}


def _live_adj(key, delta, gauge):
    with _live_lock:
        _live[key] += delta
        gauge.set(_live[key])


from paddle_tpu.observability import ledger as _ledger

_ledger.register("fastwire", lambda: {
    "fastwire_server_conns": _live["conns"],
    "fastwire_inflight_requests": _live["inflight"],
})

MAGIC = b"FW1\n"
METHODS = {"SendVariable": 1, "GetVariable": 2,
           "SendVariables": 3, "GetVariables": 4,
           # serving tier (paddle_tpu/serving/wire.py): inference
           # requests ride the same framing — magic, u8 method,
           # u64 len | payload, reply u64 len | payload — so a native
           # FastServer/FastConnPool peer interoperates with the
           # Python predict endpoint byte-for-byte
           "Predict": 5,
           # host-local hierarchical aggregation (distributed/
           # hierarchy.py): follower -> group-leader grad frames,
           # round barriers, and job completion over loopback
           "HierSend": 6, "HierBarrier": 7, "HierComplete": 8,
           # sharded-table row prefetch (distributed_lookup): tens of
           # MB of embedding rows per CTR step — bulk data, so it
           # belongs on the data plane with the scatters/gathers
           "PrefetchVariable": 9,
           # disaggregated serving fleet (paddle_tpu/serving/fleet.py):
           # MigrateKV ships a finished prompt's KV pages from a
           # prefill worker straight into a decode worker's BlockPool
           # (block-table header + raw page payloads — bulk data, the
           # serving tier's SendVariables); FleetCall is the fleet's
           # control method (prefill/generate/wait/ping/drain/status
           # as a json head).  Frame format: MIGRATION.md "MigrateKV
           # wire contract".  An old peer that predates these methods
           # closes the connection on the unknown kind byte (the
           # raw-v1 behavior) — the sender falls back to carrying the
           # request whole and re-prefilling at the destination.
           "MigrateKV": 10, "FleetCall": 11}

_lib = None
_lib_tried = False
_lib_lock = threading.Lock()  # rawlock: ok - guards ctypes lib load, must exist before flags parse


def _load():
    """Thread-safe load-or-build.  The lock matters: concurrent callers
    (per-endpoint scatter/gather threads) racing the one-time g++
    self-build used to observe ``_lib_tried=True, _lib=None`` and
    conclude 'no native library' — permanently blacklisting their
    endpoint's data plane and silently degrading it to gRPC."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib = _build_and_bind()
        _lib_tried = True
    return _lib


def _build_and_bind():
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "fastwire.c")
    so = os.path.join(here, "libfastwire.so")
    try:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            # per-pid tmp: a trainer and a pserver starting on one host
            # both self-build — a shared tmp name could interleave the
            # two compilers' writes and install a torn .so; distinct
            # tmps + atomic os.replace means last-writer-wins with a
            # whole file either way
            tmp = "%s.tmp.%d" % (so, os.getpid())
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        lib = ctypes.CDLL(so)
        lib.fw_listen.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_int]
        lib.fw_listen.restype = ctypes.c_int
        lib.fw_accept.argtypes = [ctypes.c_int]
        lib.fw_accept.restype = ctypes.c_int
        lib.fw_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fw_connect.restype = ctypes.c_int
        lib.fw_send.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_longlong]
        lib.fw_send.restype = ctypes.c_longlong
        lib.fw_sendv.argtypes = [ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_longlong),
                                 ctypes.c_int]
        lib.fw_sendv.restype = ctypes.c_longlong
        lib.fw_recv.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                ctypes.c_longlong]  # addr via addressof
        lib.fw_recv.restype = ctypes.c_longlong
        if hasattr(lib, "fw_recv_timeout"):
            lib.fw_recv_timeout.argtypes = [ctypes.c_int,
                                            ctypes.c_void_p,
                                            ctypes.c_longlong,
                                            ctypes.c_int]
            lib.fw_recv_timeout.restype = ctypes.c_longlong
        lib.fw_close.argtypes = [ctypes.c_int]
        return lib
    except Exception:
        return None


def native_available():
    return _load() is not None


def _send_bytes(lib, fd, parts):
    """Small heads join; a large payload part goes out ZERO-COPY via
    its own fw_send (ctypes passes the bytes pointer straight through;
    TCP_NODELAY + a final big write keeps syscall count low)."""
    for p in parts:
        b = p if isinstance(p, (bytes, bytearray)) else bytes(p)
        if lib.fw_send(fd, b, len(b)) != len(b):
            raise ConnectionError("fastwire send failed")
        _M_TX.inc(len(b))


def _parts_len(parts):
    """Total byte length of a parts list (bytes heads + ndarray
    payloads) without materializing anything."""
    total = 0
    for p in parts:
        total += p.nbytes if hasattr(p, "nbytes") else len(p)
    return total


def _send_parts(lib, fd, parts):
    """One vectored send of a parts list: bytes go in as-is, numpy
    arrays by their buffer address — no join, no copy.  The caller owns
    the parts' lifetimes for the duration of the call (ctypes arrays
    hold raw pointers, not references)."""
    import numpy as np

    n = len(parts)
    bufs = (ctypes.c_char_p * n)()
    lens = (ctypes.c_longlong * n)()
    keep = []   # pin converted buffers until fw_sendv returns
    total = 0
    for i, p in enumerate(parts):
        if isinstance(p, (bytes, bytearray)):
            b = bytes(p)
            keep.append(b)
            bufs[i] = b
            lens[i] = len(b)
        else:
            arr = p if isinstance(p, np.ndarray) \
                else np.frombuffer(p, dtype=np.uint8)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
            keep.append(arr)
            bufs[i] = ctypes.cast(ctypes.c_void_p(arr.ctypes.data),
                                  ctypes.c_char_p)
            lens[i] = arr.nbytes
        total += lens[i]
    if lib.fw_sendv(fd, bufs, lens, n) != total:
        raise ConnectionError("fastwire vectored send failed")
    _M_TX.inc(total)
    del keep


def _recv_exact_timeout(lib, fd, n, timeout_ms):
    """Bounded receive for the connection handshake: a listener that
    accepts and then goes silent must fail the handshake within
    ``timeout_ms`` instead of pinning the caller's thread — the caller
    then falls back to gRPC.  Degrades to the unbounded read when the
    native library predates fw_recv_timeout."""
    import numpy as np

    if not hasattr(lib, "fw_recv_timeout"):
        return _recv_exact(lib, fd, n)
    buf = np.empty(n, np.uint8)
    got = lib.fw_recv_timeout(fd, buf.ctypes.data, n, int(timeout_ms))
    if got != n:
        # -3 = deadline expired: the peer ANSWERED the connect but is
        # slow (mid-compile, GC pause) — transient, NOT a foreign
        # listener; the caller must retry next round, never blacklist
        e = ConnectionError(
            "fastwire handshake recv failed (%d of %d)" % (got, n))
        e.handshake_timeout = (got == -3)
        raise e
    _M_RX.inc(n)
    buf.flags.writeable = False
    return memoryview(buf)


def _recv_exact(lib, fd, n):
    """Receive exactly n bytes into a fresh buffer; returns a
    memoryview over it (no trailing copy — .raw would double the
    payload memory traffic).  np.empty, NOT bytearray: bytearray(n)
    zeroes its memory, a full extra pass over every 50 MB payload."""
    import numpy as np

    buf = np.empty(n, np.uint8)
    got = lib.fw_recv(fd, buf.ctypes.data, n)
    if got != n:
        raise ConnectionError("fastwire recv failed (%d of %d)" % (got, n))
    _M_RX.inc(n)
    # preserve the wire contract: decoded tensors are READ-ONLY views
    # (a consumer that wants to mutate must .copy())
    buf.flags.writeable = False
    return memoryview(buf)


class FastServer:
    """Accept loop + per-connection dispatch threads.  ``handlers`` is
    {method_name: fn(payload_bytes) -> reply_bytes} — the pserver's
    existing gRPC handler functions, unchanged.  A value may also be
    ``(fn, "stream")``: fn(payload, write) writes its own reply as a
    sequence of parts lists (each a length-prefixed frame) and the
    serve loop sends no envelope — the per-shard streaming gather."""

    def __init__(self, port, handlers, addr="0.0.0.0"):
        lib = _load()
        if lib is None:
            raise RuntimeError("fastwire native library unavailable")
        self._lib = lib
        self._handlers = {}
        for k, v in handlers.items():
            fn, mode = v if isinstance(v, tuple) else (v, "unary")
            self._handlers[METHODS[k]] = (fn, mode)
        self._lfd = lib.fw_listen(addr.encode(), int(port), 64)
        if self._lfd < 0:
            raise OSError("fastwire listen failed on %s:%d (%d)"
                          % (addr, port, self._lfd))
        self.port = int(port)
        self._stop = _san.make_event("fastwire.server.stop")
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            fd = self._lib.fw_accept(self._lfd)
            if fd < 0:
                break
            threading.Thread(target=self._serve_conn, args=(fd,),
                             daemon=True).start()

    def _serve_conn(self, fd):
        lib = self._lib
        _live_adj("conns", 1, _M_CONNS)
        try:
            if bytes(_recv_exact(lib, fd, len(MAGIC))) != MAGIC:
                return
            _send_bytes(lib, fd, [MAGIC])
            while not self._stop.is_set():
                hdr = ctypes.create_string_buffer(9)
                head = lib.fw_recv(fd, ctypes.addressof(hdr), 9)
                if head == 0:
                    return                # orderly close between messages
                if head != 9:
                    return
                method, ln = struct.unpack("<BQ", hdr.raw)
                payload = _recv_exact(lib, fd, ln)
                ent = self._handlers.get(method)
                if ent is None:
                    return
                fn, mode = ent
                _live_adj("inflight", 1, _M_INFLIGHT)
                try:
                    if mode == "stream":
                        # the handler writes length-prefixed frames
                        # itself, each the moment its shard is ready
                        fn(payload,
                           lambda parts: _send_parts(lib, fd, parts))
                    else:
                        reply = fn(payload) or b""
                        _send_bytes(
                            lib, fd,
                            [struct.pack("<Q", len(reply)), reply])
                finally:
                    _live_adj("inflight", -1, _M_INFLIGHT)
        except ConnectionError:
            pass
        finally:
            _live_adj("conns", -1, _M_CONNS)
            lib.fw_close(fd)

    def stop(self):
        self._stop.set()
        self._lib.fw_close(self._lfd)


class _Conn:
    def __init__(self, lib, fd):
        self.lib = lib
        self.fd = fd

    def _send_request(self, method, payload):
        """Header + payload; payload may be bytes or a PARTS list (one
        vectored send, no join).  sent_payload annotation as in call."""
        parts = payload if isinstance(payload, (list, tuple)) \
            else [payload]
        head = struct.pack("<BQ", METHODS[method], _parts_len(parts))
        try:
            _send_bytes(self.lib, self.fd, [head])
        except ConnectionError as e:
            e.sent_payload = False
            raise
        try:
            _send_parts(self.lib, self.fd, list(parts))
        except ConnectionError as e:
            e.sent_payload = True
            raise

    def call(self, method, payload):
        """One round-trip.  A ConnectionError raised BEFORE the payload
        went out carries .sent_payload=False (safe to retry on a fresh
        connection — a stale pooled socket); after it, True: the server
        may have consumed and APPLIED the frame, so the caller must NOT
        resend (a duplicated SendVariable gradient would silently skew
        the sync average).  ``payload`` may be bytes or a parts list."""
        self._send_request(method, payload)
        try:
            (ln,) = struct.unpack("<Q",
                                  _recv_exact(self.lib, self.fd, 8))
            return _recv_exact(self.lib, self.fd, ln)
        except ConnectionError as e:
            e.sent_payload = True
            raise

    def call_stream(self, method, payload, n_frames, on_frame):
        """Streamed gather round-trip: send the request, then consume
        ``n_frames`` length-prefixed reply frames, invoking
        ``on_frame(view)`` on each AS IT ARRIVES (the server emits a
        frame the moment that shard is ready — the client overlaps its
        own decode/copy with the still-applying shards)."""
        self._send_request(method, payload)
        try:
            for _ in range(n_frames):
                (ln,) = struct.unpack("<Q",
                                      _recv_exact(self.lib, self.fd, 8))
                on_frame(_recv_exact(self.lib, self.fd, ln))
        except ConnectionError as e:
            e.sent_payload = True
            raise

    def close(self):
        self.lib.fw_close(self.fd)


class FastConnPool:
    """Client side: per-endpoint connection pool.  Endpoints that fail
    the magic handshake (an old server, a foreign listener) are marked
    dead and the caller falls back to gRPC for good."""

    def __init__(self, port_offset=2000):
        self.port_offset = int(port_offset)
        self._idle = {}
        self._dead = set()
        self._lock = _san.make_lock("fastwire.pool")

    def _connect(self, ep):
        """Returns a _Conn, None (transient: connect refused — retry
        next round, the pserver may still be binding), or "foreign"
        (a listener answered but failed the magic — permanently not a
        fastwire endpoint)."""
        lib = _load()
        if lib is None:
            return "foreign"
        host, port = ep.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        fd = lib.fw_connect(host.encode(), int(port) + self.port_offset)
        if fd < 0:
            return None
        try:
            _send_bytes(lib, fd, [MAGIC])
            if bytes(_recv_exact_timeout(lib, fd, len(MAGIC),
                                         5000)) != MAGIC:
                lib.fw_close(fd)
                return "foreign"
        except ConnectionError as e:
            lib.fw_close(fd)
            if getattr(e, "handshake_timeout", False):
                return None    # slow peer: retry next round
            return "foreign"   # answered, then hung up mid-handshake
        return _Conn(lib, fd)

    def checkout(self, ep):
        """A ready connection, or None when the endpoint has no fast
        data plane right now (caller uses gRPC for this round).  Only a
        listener that FAILS the magic handshake is marked permanently
        dead; a refused connect is the pserver/trainer startup race and
        retries next round."""
        with self._lock:
            if ep in self._dead:
                return None
            conns = self._idle.get(ep)
            if conns:
                return conns.pop()
        conn = self._connect(ep)
        if conn == "foreign":
            with self._lock:
                self._dead.add(ep)
            return None
        return conn

    def checkin(self, ep, conn):
        with self._lock:
            self._idle.setdefault(ep, []).append(conn)

    def discard(self, conn):
        try:
            conn.close()
        except Exception:
            pass

    def close(self):
        with self._lock:
            for conns in self._idle.values():
                for c in conns:
                    self.discard(c)
            self._idle.clear()
