"""Host-local hierarchical gradient aggregation (ISSUE 10 tentpole b).

Trainers on one host pre-reduce their grads through a LOCAL aggregator
before one upload per host hits the pservers — tree fan-in that cuts
pserver ingress (and sync fanin) by the trainers-per-host factor, the
reference's multi-level ParameterServer topology (Li et al., OSDI'14).

Topology (FLAGS_dist_hier_local = L trainers per host group):
- trainer ids are grouped contiguously: group g = trainer_id // L; the
  group's LEADER is its lowest id (trainer_id % L == 0).
- Followers ship their grads to the leader over a loopback fastwire
  channel (HierSend frames: the normal rpc frame with the target
  pserver endpoint folded into the name, '<ep>\\x00<name>'), signal
  round completion with HierBarrier, and job completion with
  HierComplete.  They keep READING params directly from the pservers
  (reads are stateless) and their recv naturally blocks until the
  leader's round lands.
- The leader stashes its own grads in-process; at barrier time it
  waits for every follower's HierBarrier, computes the group-local
  mean per (endpoint, grad) with the same add-then-scale the server
  uses, and makes ONE (optionally compressed) upload + ONE barrier to
  the pservers under its own (round, sender, seq) identity — PR 1's
  replay/dedup machinery covers the upload verbatim.
- The pserver therefore sees fanin = number of GROUPS (the transpiler
  sets listen_and_serv Fanin accordingly), and mean-over-groups of
  equal-size group means equals the flat mean over trainers.

Contract notes: followers trust the leader for round durability (the
pserver's durable ack lands at the leader); group sizes must be equal
(transpile() enforces trainers % L == 0); aggregation order within a
group is follower-arrival order — commutative for the 2-trainer rig,
documented fp-rounding freedom beyond it.
"""
from __future__ import annotations

import os
import threading

from paddle_tpu.core import sanitizer as _san
import time

import numpy as np

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import ledger as _ledger
from paddle_tpu.observability import metrics as _obs_metrics

__all__ = ["enabled", "role", "Role", "HostAggregator", "reset"]

_M_LOCAL_FRAMES = _obs_metrics.counter(
    "hier_local_frames_total",
    "grad frames received by host-local aggregators")
_M_UPLOADS = _obs_metrics.counter(
    "hier_uploads_total",
    "pre-reduced (endpoint, grad) uploads shipped by group leaders")

_SEP = "\x00"   # folds the target pserver endpoint into the frame name

# leader-side sparse merge pays a full sort; below this sampled
# cross-member row overlap the dedup saves too few bytes to buy it
_MERGE_MIN_OVERLAP = 0.25


def _overlap_worth_merging(row_sets, sample=2048):
    """Cheap overlap estimate across members' row-id sets: sampled
    membership of the first set in the second (the common 2-member
    case; wider groups always merge — overlap compounds)."""
    if len(row_sets) != 2:
        return True
    a, b = row_sets
    if a.size == 0 or b.size == 0:
        return False
    probe = a[:: max(1, a.size // sample)][:sample]
    return float(np.isin(probe, b).mean()) >= _MERGE_MIN_OVERLAP


def enabled():
    return int(FLAGS.dist_hier_local or 0) > 1


class Role:
    __slots__ = ("trainer_id", "n_local", "group", "leader", "port")

    def __init__(self, trainer_id, n_local):
        self.trainer_id = int(trainer_id)
        self.n_local = int(n_local)
        self.group = self.trainer_id // self.n_local
        self.leader = self.trainer_id % self.n_local == 0
        self.port = int(FLAGS.dist_hier_port) + self.group


def role():
    tid = os.environ.get("PADDLE_TRAINER_ID")
    if tid is None:
        raise RuntimeError(
            "FLAGS_dist_hier_local is set but PADDLE_TRAINER_ID is not "
            "in the environment — hierarchical aggregation needs the "
            "trainer id to elect the group leader")
    return Role(int(tid), int(FLAGS.dist_hier_local))


# ---------------------------------------------------------------------------
# leader side
# ---------------------------------------------------------------------------

class HostAggregator:
    """Leader-side state: follower contributions per round, barrier and
    completion accounting, and the group-mean flush."""

    def __init__(self, n_local, port, upload=None):
        from . import fastwire

        if not fastwire.native_available():
            raise RuntimeError(
                "hierarchical aggregation needs the fastwire native "
                "library (g++ self-build failed?)")
        self.n_local = int(n_local)
        # EAGER upload hook: callable([(ep, name, group-mean)]).  When
        # set, a grad whose n_local-th contribution just landed is
        # aggregated and shipped IMMEDIATELY (on the arrival thread) —
        # uploads overlap the rest of the round instead of bunching at
        # the barrier.  flush() then only settles the stragglers.
        self._upload = upload
        self._cv = _san.make_condition("hier.agg.cv")
        self._grads = {}      # round -> {(ep, name): {sender: arr}}
        self._order = {}      # round -> [(ep, name)] first-seen order
        self._shipped = {}    # round -> {(ep, name)} already uploaded
        self._barriers = {}   # round -> set(follower senders)
        self._completed = set()
        self._inflight = 0    # eager uploads currently on the wire
        self._errs = []       # eager-upload failures, surfaced at flush
        # fan-in buffer ledger (ISSUE 12): bytes/entries of follower
        # contributions held by this leader, maintained at stash/pop
        # sites and sampled by the observability ledger collector
        self._buf_bytes = 0
        self._buf_entries = 0
        self._ledger_handle = _ledger.register(
            "hier", HostAggregator._ledger_probe, owner=self)
        self._server = fastwire.FastServer(
            port, {"HierSend": self._h_send,
                   "HierBarrier": self._h_barrier,
                   "HierComplete": self._h_complete},
            addr="127.0.0.1")

    # -- wire handlers (follower -> leader) --
    def _h_send(self, req, ctx=None):
        from .rpc import _dec_tensor, _iter_batch, _unpack_round_sender

        ready = []
        with self._cv:
            for frame in _iter_batch(req):
                wname, arr, extra = _dec_tensor(frame)
                round_, sender, _ = _unpack_round_sender(extra)
                ep, name = wname.split(_SEP, 1)
                ready += self._stash_locked(round_, ep, name, arr,
                                            sender)
                _M_LOCAL_FRAMES.inc()
            self._cv.notify_all()
        self._ship_async(ready)
        return b""

    def _h_barrier(self, req, ctx=None):
        from .rpc import _dec_msg, _unpack_round_sender

        _, extra = _dec_msg(req)
        round_, sender, _ = _unpack_round_sender(extra)
        with self._cv:
            self._barriers.setdefault(round_, set()).add(sender)
            self._cv.notify_all()
        return b""

    def _h_complete(self, req, ctx=None):
        from .rpc import _dec_msg, _unpack_round_sender

        _, extra = _dec_msg(req)
        _, sender, _ = _unpack_round_sender(extra)
        with self._cv:
            self._completed.add(sender)
            self._cv.notify_all()
        return b""

    # -- leader-local API --
    def _stash_locked(self, round_, ep, name, arr, sender):
        """One contribution (lock held).  Sender-keyed: a follower's
        retried frame OVERWRITES its previous value — idempotent, like
        the pserver's (round, sender) dedup.  Returns the [(ep, name,
        contributions)] entries the caller must SHIP (outside the
        lock): with an eager-upload hook installed, a grad completes
        the moment its n_local-th contribution lands."""
        key = (ep, name)
        if key in self._shipped.get(round_, ()):
            # a retried frame for an entry the eager path already
            # uploaded: its value is in the shipped mean — dropping the
            # duplicate keeps the retry idempotent (re-creating the
            # entry would make flush upload a 1-contribution "mean"
            # over the true group mean)
            return []
        r = self._grads.setdefault(round_, {})
        if key not in r:
            r[key] = {}
            self._order.setdefault(round_, []).append(key)
        old = r[key].get(sender)
        if old is not None:
            self._buf_bytes -= _ledger.value_nbytes(old)
        else:
            self._buf_entries += 1
        self._buf_bytes += _ledger.value_nbytes(arr)
        r[key][sender] = arr
        if self._upload is not None and len(r[key]) >= self.n_local:
            self._order[round_].remove(key)
            self._shipped.setdefault(round_, set()).add(key)
            self._inflight += 1
            contrib = r.pop(key)
            self._buf_drop_locked(contrib)
            return [(key[0], key[1], contrib)]
        return []

    def _buf_drop_locked(self, contrib):
        """One contribution dict leaves the fan-in buffer (lock held)."""
        for v in contrib.values():
            self._buf_bytes -= _ledger.value_nbytes(v)
            self._buf_entries -= 1

    def _ledger_probe(self):
        """Leader fan-in resource ledger: buffered follower
        contributions awaiting their group's completion, plus eager
        uploads still on the wire."""
        return {"hier_fanin_bytes": self._buf_bytes,
                "hier_fanin_entries": self._buf_entries,
                "hier_inflight_uploads": self._inflight}

    def _ship_async(self, ready):
        """Run _ship off the caller's thread: the LEADER's own send op
        frequently completes an entry (its contribution arrives last),
        and merging + codec + upload of a multi-MB grad on that thread
        would serialize straight into the leader's training step.  The
        flush()-time inflight accounting already covers the handoff —
        _inflight was incremented under the lock in _stash_locked."""
        if ready:
            threading.Thread(target=self._ship, args=(ready,),
                             daemon=True).start()

    def _ship(self, ready):
        """Aggregate + upload completed entries (no lock held); eager
        counterpart of flush()'s straggler pass."""
        if not ready:
            return
        try:
            triples = [(ep, name, self._aggregate(contrib))
                       for ep, name, contrib in ready]
            for _ in triples:
                _M_UPLOADS.inc()
            self._upload(triples)
        except Exception as e:
            with self._cv:
                self._errs.append(e)
        finally:
            with self._cv:
                self._inflight -= len(ready)
                self._cv.notify_all()

    def stash(self, round_, ep, name, arr, sender):
        with self._cv:
            ready = self._stash_locked(round_, ep, name, arr, sender)
            self._cv.notify_all()
        self._ship_async(ready)

    def _wait(self, pred, deadline, what):
        end = time.monotonic() + deadline
        while not pred():
            left = end - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    "hierarchical aggregation: leader timed out waiting "
                    "for %s (followers dead or mis-grouped? "
                    "FLAGS_dist_hier_local=%d)" % (what, self.n_local))
            self._cv.wait(timeout=min(left, 0.25))

    @staticmethod
    def _aggregate(contrib):
        """Group-mean of one grad's {sender: value} contributions."""
        from paddle_tpu.core.selected_rows import SelectedRows
        from .rpc import _aligned_empty

        vals = list(contrib.values())
        n = len(vals)
        if any(isinstance(v, SelectedRows) for v in vals):
            # group-mean of sparse grads: concatenate, then MERGE
            # duplicate rows by summation (scatter-add equivalent —
            # tree fan-in cuts sparse ingress when the members' row
            # sets OVERLAP, i.e. head-heavy traffic).  The merge
            # itself costs a sort over every row, so estimate the
            # overlap first from a sample and skip when the tail
            # dominates — concatenation is the same math either way.
            from paddle_tpu.core.selected_rows import merge_rows_host

            rows = np.concatenate([np.asarray(v.rows) for v in vals])
            values = np.concatenate(
                [np.asarray(v.values) for v in vals]) / n
            if _overlap_worth_merging(
                    [np.asarray(v.rows) for v in vals]):
                uniq, merged = merge_rows_host(rows, values)
                return SelectedRows(uniq, merged, vals[0].height)
            return SelectedRows(rows, values, vals[0].height)
        if n == 1:
            return np.asarray(vals[0])
        # same add-then-scale the pserver's aggregate uses
        v0 = np.asarray(vals[0])
        agg = _aligned_empty(v0.shape, v0.dtype)
        np.add(v0, vals[1], out=agg)
        for v in vals[2:]:
            agg += v
        agg *= 1.0 / n
        return agg

    def flush(self, round_, deadline=300.0):
        """Wait for every follower's HierBarrier of ``round_`` and for
        the eager uploads in flight, surface any eager-upload failure,
        then return the STRAGGLER [(ep, name, group-mean)] entries
        (everything not already shipped eagerly) and drop the round's
        state.  Follower sends precede their barrier on one FIFO
        connection, so a complete barrier set implies complete grads."""
        with self._cv:
            self._wait(
                lambda: (len(self._barriers.get(round_, ())) >=
                         self.n_local - 1 and self._inflight == 0),
                deadline, "round %d follower barriers" % round_)
            if self._errs:
                raise self._errs.pop(0)
            grads = self._grads.pop(round_, {})
            order = self._order.pop(round_, [])
            self._barriers.pop(round_, None)
            self._shipped.pop(round_, None)
            for contrib in grads.values():
                self._buf_drop_locked(contrib)
        out = []
        for key in order:
            out.append((key[0], key[1], self._aggregate(grads[key])))
            _M_UPLOADS.inc()
        return out

    def wait_complete(self, deadline=300.0):
        with self._cv:
            self._wait(lambda: len(self._completed) >= self.n_local - 1,
                       deadline, "follower completions")

    def stop(self):
        _ledger.unregister(self._ledger_handle)
        try:
            self._server.stop()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# follower side
# ---------------------------------------------------------------------------

class _FollowerLink:
    """One persistent loopback connection to the group leader.  FIFO
    per connection: a follower's HierBarrier can never overtake its
    grads.  Retries reconnect freely — the aggregator's sender-keyed
    stash makes duplicate frames idempotent."""

    def __init__(self, port):
        from . import fastwire

        self._fw = fastwire
        self._ep = "127.0.0.1:%d" % int(port)
        self._pool = fastwire.FastConnPool(0)
        self._lock = _san.make_lock("hier.link")

    def call(self, method, payload, deadline=300.0):
        end = time.monotonic() + deadline
        last = None
        with self._lock:
            while time.monotonic() < end:
                conn = self._pool.checkout(self._ep)
                if conn is None:
                    # leader not listening yet (startup race) — the
                    # loopback connect is cheap, poll it
                    time.sleep(0.05)
                    continue
                try:
                    conn.call(method, payload)
                    self._pool.checkin(self._ep, conn)
                    return
                except ConnectionError as e:
                    last = e
                    self._pool.discard(conn)
                    time.sleep(0.05)
        raise TimeoutError(
            "hierarchical aggregation: follower could not reach its "
            "group leader at %s (%s)" % (self._ep, last))


# ---------------------------------------------------------------------------
# process-wide wiring (used by rpc.RPCClient)
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()  # rawlock: ok - module singleton wiring, set up before any mode flip
_agg = None
_link = None


def _aggregator(r, client=None):
    global _agg
    with _state_lock:
        if _agg is None:
            upload = None
            if client is not None:
                # eager-upload hook: ship a completed grad through the
                # leader's normal (compressed, replay-recorded) wire
                # path the moment the whole group contributed
                upload = client._send_vars_wire
            _agg = HostAggregator(r.n_local, r.port, upload=upload)
        elif client is not None and _agg._upload is None:
            _agg._upload = client._send_vars_wire
        return _agg


def _follower_link(r):
    global _link
    with _state_lock:
        if _link is None:
            _link = _FollowerLink(r.port)
        return _link


def reset():
    """Tear down the process's aggregator/link (tests, RPCClient.reset)."""
    global _agg, _link
    with _state_lock:
        if _agg is not None:
            _agg.stop()
        _agg = None
        _link = None


def leader_stash(client, triples):
    """The leader's own send op: contributions go straight into the
    in-process aggregator (host-materialized; the wire codec runs on
    the aggregated upload)."""
    agg = _aggregator(role(), client)
    for ep, name, arr in triples:
        agg.stash(client.step, ep, name, client._to_host(arr),
                  client.sender)


def follower_send(client, triples):
    from .rpc import _enc_batch_parts, _enc_tensor_parts, \
        _pack_round_sender

    r = role()
    frames = []
    for ep, name, arr in triples:
        arr = client._to_host(arr)
        seq = client._next_seq()
        frames.append(_enc_tensor_parts(
            "%s%s%s" % (ep, _SEP, name), arr,
            _pack_round_sender(client.step, client.sender, seq)))
    _follower_link(r).call("HierSend", _enc_batch_parts(frames),
                           deadline=client.retry.deadline)


def follower_barrier(client):
    from .rpc import _enc_msg, _pack_round_sender

    r = role()
    _follower_link(r).call(
        "HierBarrier",
        _enc_msg(client.label,
                 _pack_round_sender(client.step, client.sender)),
        deadline=client.retry.deadline)


def follower_complete(client):
    from .rpc import _enc_msg, _pack_round_sender

    r = role()
    _follower_link(r).call(
        "HierComplete",
        _enc_msg(client.label,
                 _pack_round_sender(client.step, client.sender)),
        deadline=min(30.0, client.retry.deadline))


def leader_flush(client):
    """Barrier-time settle: wait for the group's followers (and any
    eager uploads in flight), return the straggler [(ep, name,
    group-mean)] upload list for the current round."""
    agg = _aggregator(role(), client)
    return agg.flush(client.step, deadline=client.retry.deadline)


def leader_wait_complete(client):
    agg = _aggregator(role())
    agg.wait_complete(deadline=min(60.0, client.retry.deadline))
