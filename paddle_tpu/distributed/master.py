"""Fault-tolerant task-queue master.

Parity: reference go/master/service.go — the Go master that shards a
dataset into tasks and hands them to trainers with at-least-once
dispatch / exactly-once completion semantics:
  - todo/pending/done/failed queues     (service.go:280 GetTask,
    :313 TaskFinished, :341 TaskFailed)
  - lease timeout re-queues a dead trainer's task  (:368 checkTimeout)
  - retry cap moves a poisoned task to failed      (failureMax)
  - state snapshot for master recovery             (:411 snapshot —
    etcd there, a JSON file here)
  - epoch rollover: when todo and pending drain, done refills todo
    (:455 processTask pass accounting)

Served over the same gRPC generic-handler transport as the pserver
(rpc.py); payloads are JSON (tasks are metadata — file paths / chunk
ranges — not tensor data).
"""
from __future__ import annotations

import json
import os
import threading

from paddle_tpu.core import sanitizer as _san
import time
from concurrent import futures

__all__ = ["Task", "Master", "MasterServer", "MasterClient",
           "master_reader"]

MASTER_SERVICE = "paddle_tpu.Master"
DEFAULT_LEASE = 15.0
DEFAULT_MAX_RETRY = 3


class Task:
    __slots__ = ("task_id", "payload", "retries")

    def __init__(self, task_id, payload, retries=0):
        self.task_id = task_id
        self.payload = payload
        self.retries = retries

    def to_dict(self):
        return {"task_id": self.task_id, "payload": self.payload,
                "retries": self.retries}

    @staticmethod
    def from_dict(d):
        return Task(d["task_id"], d["payload"], d.get("retries", 0))


class Master:
    """In-process queue core (the gRPC server wraps this)."""

    def __init__(self, lease_timeout=DEFAULT_LEASE,
                 max_retry=DEFAULT_MAX_RETRY, snapshot_path=None,
                 num_epochs=1):
        self._lock = _san.make_lock("master.state")
        self._todo = []          # [Task]
        self._pending = {}       # id -> (Task, deadline)
        self._done = []          # [Task]
        self._failed = []        # [Task]
        self._epoch = 0
        self._num_epochs = num_epochs
        self._lease = lease_timeout
        self._max_retry = max_retry
        self._snapshot_path = snapshot_path
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset --
    def set_dataset(self, payloads):
        """Idempotent (reference NewDataset): only loads once."""
        with self._lock:
            if self._todo or self._pending or self._done or self._failed:
                return
            self._todo = [Task(i, p) for i, p in enumerate(payloads)]
            self._snapshot()

    # -- trainer API --
    def get_task(self):
        """-> Task, or ("wait", secs) when all leased, or None when the
        dataset is finished (every epoch completed)."""
        with self._lock:
            self._check_timeouts()
            if not self._todo and not self._pending:
                if self._done and self._epoch + 1 < self._num_epochs:
                    self._epoch += 1
                    self._todo, self._done = self._done, []
                else:
                    return None
            if not self._todo:
                return ("wait", self._nearest_deadline())
            task = self._todo.pop(0)
            self._pending[task.task_id] = (task,
                                           time.time() + self._lease)
            self._snapshot()
            return task

    def task_finished(self, task_id):
        with self._lock:
            ent = self._pending.pop(task_id, None)
            if ent is None:
                return False  # stale lease: someone else finished it
            task = ent[0]
            task.retries = 0
            self._done.append(task)
            self._snapshot()
            return True

    def task_failed(self, task_id):
        with self._lock:
            ent = self._pending.pop(task_id, None)
            if ent is None:
                return False
            self._requeue(ent[0])
            self._snapshot()
            return True

    # -- introspection --
    def counts(self):
        with self._lock:
            self._check_timeouts()
            return {"todo": len(self._todo), "pending": len(self._pending),
                    "done": len(self._done), "failed": len(self._failed),
                    "epoch": self._epoch}

    # -- internals (lock held) --
    def _requeue(self, task):
        task.retries += 1
        if task.retries > self._max_retry:
            self._failed.append(task)   # poisoned: give up (failureMax)
        else:
            self._todo.append(task)

    def _check_timeouts(self):
        now = time.time()
        expired = [tid for tid, (_, dl) in self._pending.items()
                   if dl <= now]
        for tid in expired:
            task, _ = self._pending.pop(tid)
            self._requeue(task)
        if expired:
            self._snapshot()

    def _nearest_deadline(self):
        if not self._pending:
            return 0.1
        return max(0.05, min(dl for _, dl in self._pending.values())
                   - time.time())

    def _snapshot(self):
        if not self._snapshot_path:
            return
        state = {
            "todo": [t.to_dict() for t in self._todo],
            # pending snapshots as todo: after a master restart every
            # lease is void and the task must be re-dispatched
            "pending": [t.to_dict() for t, _ in self._pending.values()],
            "done": [t.to_dict() for t in self._done],
            "failed": [t.to_dict() for t in self._failed],
            "epoch": self._epoch,
        }
        # atomic commit: a crash mid-write must never leave a truncated
        # JSON at the live path (it would poison _recover); the previous
        # good snapshot rotates to .bak so a crash landing between the
        # two renames still leaves one loadable state
        from paddle_tpu.core.fsutil import atomic_write

        atomic_write(self._snapshot_path, json.dumps(state),
                     backup_suffix=".bak")

    def _recover(self):
        """Load the snapshot; a corrupt/truncated main file falls back
        to the .bak rotated by _snapshot.  With neither loadable the
        master starts empty — task dispatch is at-least-once, so a
        re-run of the dataset is safe, while refusing to start is not."""
        state = None
        for cand in (self._snapshot_path, self._snapshot_path + ".bak"):
            try:
                with open(cand) as f:
                    state = json.load(f)
                break
            except (OSError, ValueError):
                continue
        if state is None:
            import warnings
            warnings.warn("master snapshot %r unreadable (and no .bak); "
                          "starting with an empty queue"
                          % self._snapshot_path)
            return
        self._todo = [Task.from_dict(d)
                      for d in state["todo"] + state["pending"]]
        self._done = [Task.from_dict(d) for d in state["done"]]
        self._failed = [Task.from_dict(d) for d in state["failed"]]
        self._epoch = state["epoch"]


class MasterServer:
    """gRPC front of a Master (generic handlers, JSON payloads)."""

    def __init__(self, master):
        import grpc

        self.master = master
        handlers = {
            "SetDataset": self._h(self._set_dataset),
            "GetTask": self._h(self._get_task),
            "TaskFinished": self._h(self._task_finished),
            "TaskFailed": self._h(self._task_failed),
            "Counts": self._h(self._counts),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(MASTER_SERVICE,
                                                 handlers),))

    @staticmethod
    def _h(fn):
        import grpc

        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(json.loads(req.decode() or "null")))

    def start(self, endpoint):
        port = self._server.add_insecure_port(endpoint)
        self._server.start()
        return port

    def stop(self):
        self._server.stop(grace=0.5).wait()

    def _set_dataset(self, req):
        self.master.set_dataset(req)
        return b"{}"

    def _get_task(self, req):
        t = self.master.get_task()
        if t is None:
            resp = {"status": "finished"}
        elif isinstance(t, tuple):
            resp = {"status": "wait", "secs": t[1]}
        else:
            resp = {"status": "ok", "task": t.to_dict()}
        return json.dumps(resp).encode()

    def _task_finished(self, req):
        ok = self.master.task_finished(req)
        return json.dumps({"ok": ok}).encode()

    def _task_failed(self, req):
        ok = self.master.task_failed(req)
        return json.dumps({"ok": ok}).encode()

    def _counts(self, req):
        return json.dumps(self.master.counts()).encode()


class MasterClient:
    """Client with per-call deadlines + retry (resilience.RetryPolicy):
    an RPC to a dead/restarting master fails fast and retries with
    backoff instead of hanging forever.  Every master op is idempotent
    or lease-guarded server-side (a stale TaskFinished after the lease
    was re-dispatched returns ok=False), so retry is safe."""

    def __init__(self, endpoint, retry=None):
        import grpc

        from .resilience import RetryPolicy

        self._endpoint = endpoint
        self._ch = grpc.insecure_channel(endpoint)
        self.retry = retry if retry is not None else RetryPolicy.from_env()

    def _call(self, method, payload):
        from .resilience import fault_point

        def attempt():
            fault_point("master_rpc")
            fn = self._ch.unary_unary(
                "/%s/%s" % (MASTER_SERVICE, method))
            return json.loads(
                fn(json.dumps(payload).encode(), wait_for_ready=True,
                   timeout=self.retry.call_timeout).decode())

        return self.retry.run(
            attempt,
            describe="Master.%s(%s)" % (method, self._endpoint))

    def set_dataset(self, payloads):
        self._call("SetDataset", list(payloads))

    def get_task(self, block=True):
        """-> Task or None (finished).  block=True sleeps through 'wait'
        responses until a lease frees up."""
        while True:
            resp = self._call("GetTask", None)
            if resp["status"] == "ok":
                return Task.from_dict(resp["task"])
            if resp["status"] == "finished":
                return None
            if not block:
                return ("wait", resp["secs"])
            time.sleep(min(resp["secs"], 1.0))

    def task_finished(self, task_id):
        return self._call("TaskFinished", task_id)["ok"]

    def task_failed(self, task_id):
        return self._call("TaskFailed", task_id)["ok"]

    def counts(self):
        return self._call("Counts", None)


def master_reader(endpoint, deserializer=None):
    """Reader creator over master-dispatched recordio chunks (reference
    go/master/client.go NextRecord feeding the Python v2 reader):
    each task payload is a recordio path (or [path, ...]); records of a
    task are yielded then the task is marked finished, so a crashed
    worker's unfinished task is re-dispatched to a healthy one."""
    from paddle_tpu import recordio

    def reader():
        client = MasterClient(endpoint)
        while True:
            task = client.get_task()
            if task is None:
                return
            paths = (task.payload if isinstance(task.payload, list)
                     else [task.payload])
            try:
                for p in paths:
                    for rec in recordio.read_records(p):
                        yield (deserializer(rec) if deserializer
                               else rec)
            except Exception:
                client.task_failed(task.task_id)
                raise
            client.task_finished(task.task_id)

    return reader
