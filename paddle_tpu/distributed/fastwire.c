/* fastwire: raw-socket bulk transfer for the pserver data plane.
 *
 * Role parity: reference paddle/pserver/LightNetwork.cpp — the C++
 * ParameterServer2 moved parameter blocks over raw sockets precisely
 * because a Python/RPC layer cannot feed large dense models.  This is
 * the minimal native half: blocking full-length send/recv loops over
 * TCP (TCP_NODELAY), called through ctypes so the GIL is released for
 * the whole transfer and shard streams overlap across threads.
 * Framing stays in Python (distributed/rpc.py _enc_tensor — the same
 * dtype|shape|bytes frame the gRPC path speaks).
 *
 * Build: g++ -O2 -shared -fPIC (distributed/fastwire.py, the
 * recordio.cc self-build pattern).
 */
#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

extern "C" {

/* 4 MB socket buffers: the data plane moves ~50-100 MB frames; the
 * kernel default (~200 KB) forces the sender into many small
 * round-trips with the receiver's window. */
static void fw_tune(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int buf = 4 << 20;
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

/* Listen on 127.0.0.1:port (the pserver data plane is host-local or
 * cluster-internal; binding wildcard is the caller's call via addr). */
int fw_listen(const char *addr, int port, int backlog) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((unsigned short)port);
    if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) { close(fd); return -2; }
    if (bind(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0) { close(fd); return -3; }
    if (listen(fd, backlog) != 0) { close(fd); return -4; }
    return fd;
}

int fw_accept(int lfd) {
    for (;;) {
        int fd = accept(lfd, 0, 0);
        if (fd >= 0) {
            fw_tune(fd);
            return fd;
        }
        if (errno != EINTR) return -1;
    }
}

int fw_connect(const char *addr, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((unsigned short)port);
    if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) { close(fd); return -2; }
    if (connect(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0) {
        close(fd);
        return -3;
    }
    fw_tune(fd);
    return fd;
}

/* Send exactly n bytes; returns n or <0 on error. */
long long fw_send(int fd, const char *buf, long long n) {
    long long done = 0;
    while (done < n) {
        ssize_t w = send(fd, buf + done, (size_t)(n - done), MSG_NOSIGNAL);
        if (w < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += w;
    }
    return done;
}

/* Vectored send: exactly sum(lens) bytes from n buffers in one writev
 * loop (the batched scatter path: per-tensor header+payload parts go
 * out without a Python-level join copy).  Returns total or <0. */
long long fw_sendv(int fd, const char **bufs, const long long *lens,
                   int n) {
    struct iovec iov[64];
    long long total = 0;
    int i = 0;
    while (i < n) {
        int k = 0;
        long long want = 0;
        for (; k < 64 && i + k < n; ++k) {
            iov[k].iov_base = (void *)bufs[i + k];
            iov[k].iov_len = (size_t)lens[i + k];
            want += lens[i + k];
        }
        long long done = 0;
        int cur = 0;
        while (done < want) {
            /* sendmsg, not writev: MSG_NOSIGNAL turns a dead peer into
             * EPIPE instead of a process-killing SIGPIPE (fw_send). */
            struct msghdr mh;
            memset(&mh, 0, sizeof(mh));
            mh.msg_iov = iov + cur;
            mh.msg_iovlen = (size_t)(k - cur);
            ssize_t w = sendmsg(fd, &mh, MSG_NOSIGNAL);
            if (w < 0) {
                if (errno == EINTR) continue;
                return -1;
            }
            done += w;
            total += w;
            /* advance past fully-written iovecs, trim a partial one */
            while (cur < k && (size_t)w >= iov[cur].iov_len) {
                w -= iov[cur].iov_len;
                ++cur;
            }
            if (cur < k && w > 0) {
                iov[cur].iov_base = (char *)iov[cur].iov_base + w;
                iov[cur].iov_len -= (size_t)w;
            }
        }
        i += k;
    }
    return total;
}

/* fw_recv with a deadline: like fw_recv, but returns -3 if timeout_ms
 * elapses before the full n bytes arrive.  Used for the connection
 * handshake — a listener that accepts and then goes silent (half-dead
 * process, wedged accept queue) must not pin a client thread forever
 * before the gRPC fallback can take over. */
long long fw_recv_timeout(int fd, char *buf, long long n, int timeout_ms) {
    long long done = 0;
    while (done < n) {
        struct pollfd pfd;
        pfd.fd = fd;
        pfd.events = POLLIN;
        int pr = poll(&pfd, 1, timeout_ms);
        if (pr == 0) return -3;
        if (pr < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        ssize_t r = recv(fd, buf + done, (size_t)(n - done), 0);
        if (r == 0) return done == 0 ? 0 : -2;
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += r;
    }
    return done;
}

/* Receive exactly n bytes; returns n, 0 on orderly close at a message
 * boundary (done == 0), or <0 on error / mid-message close. */
long long fw_recv(int fd, char *buf, long long n) {
    long long done = 0;
    while (done < n) {
        ssize_t r = recv(fd, buf + done, (size_t)(n - done), 0);
        if (r == 0) return done == 0 ? 0 : -2;
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += r;
    }
    return done;
}

void fw_close(int fd) { close(fd); }

}  /* extern "C" */
