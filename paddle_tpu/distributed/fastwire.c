/* fastwire: raw-socket bulk transfer for the pserver data plane.
 *
 * Role parity: reference paddle/pserver/LightNetwork.cpp — the C++
 * ParameterServer2 moved parameter blocks over raw sockets precisely
 * because a Python/RPC layer cannot feed large dense models.  This is
 * the minimal native half: blocking full-length send/recv loops over
 * TCP (TCP_NODELAY), called through ctypes so the GIL is released for
 * the whole transfer and shard streams overlap across threads.
 * Framing stays in Python (distributed/rpc.py _enc_tensor — the same
 * dtype|shape|bytes frame the gRPC path speaks).
 *
 * Build: g++ -O2 -shared -fPIC (distributed/fastwire.py, the
 * recordio.cc self-build pattern).
 */
#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" {

/* Listen on 127.0.0.1:port (the pserver data plane is host-local or
 * cluster-internal; binding wildcard is the caller's call via addr). */
int fw_listen(const char *addr, int port, int backlog) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((unsigned short)port);
    if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) { close(fd); return -2; }
    if (bind(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0) { close(fd); return -3; }
    if (listen(fd, backlog) != 0) { close(fd); return -4; }
    return fd;
}

int fw_accept(int lfd) {
    for (;;) {
        int fd = accept(lfd, 0, 0);
        if (fd >= 0) {
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            return fd;
        }
        if (errno != EINTR) return -1;
    }
}

int fw_connect(const char *addr, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((unsigned short)port);
    if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) { close(fd); return -2; }
    if (connect(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0) {
        close(fd);
        return -3;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return fd;
}

/* Send exactly n bytes; returns n or <0 on error. */
long long fw_send(int fd, const char *buf, long long n) {
    long long done = 0;
    while (done < n) {
        ssize_t w = send(fd, buf + done, (size_t)(n - done), MSG_NOSIGNAL);
        if (w < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += w;
    }
    return done;
}

/* Receive exactly n bytes; returns n, 0 on orderly close at a message
 * boundary (done == 0), or <0 on error / mid-message close. */
long long fw_recv(int fd, char *buf, long long n) {
    long long done = 0;
    while (done < n) {
        ssize_t r = recv(fd, buf + done, (size_t)(n - done), 0);
        if (r == 0) return done == 0 ? 0 : -2;
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += r;
    }
    return done;
}

void fw_close(int fd) { close(fd); }

}  /* extern "C" */
