"""Trainer<->pserver RPC over gRPC generic handlers.

Parity: reference operators/detail/send_recv.proto:19-28 (SendRecvService:
SendVariable / GetVariable / PrefetchVariable), grpc_client.h:168,
grpc_server.cc, and the sync/async serve loops of
operators/listen_and_serv_op.cc:99,166.

Implementation notes (TPU-host path):
- gRPC *generic* method handlers with a numpy-native wire format — no
  protoc codegen; tensors travel as a raw dtype|shape|bytes frame
  (memcpy-speed encode, zero-copy decode — see _enc_arr).
- The sync protocol is barrier-counted like the reference: trainers send
  every grad, then SendBarrier; once ``fanin`` barriers arrive the server
  aggregates (mean over trainers), runs the per-param optimize blocks, and
  bumps ``applied_round``; GetVariable(round) blocks until
  ``applied_round >= round``.  SendComplete decrements fanin (reference
  framework/executor.cc:50 SendComplete) and stops the server at zero.

Failure-path design (distributed/resilience.py is the policy home):
- Every SendVariable/SendBarrier carries a (round, sender) identity
  packed into the message's extra field, so the server DEDUPS by sender:
  replaying a round after a reconnect is idempotent, which is what makes
  client-side retry safe for non-idempotent gradient traffic.
- SendBarrier ACKS ONLY AFTER the round is applied — and, on checkpoint
  rounds, durably snapshotted — so a SIGKILL at any point either loses
  an un-acked round (every trainer still holds it in its replay cache
  and resends) or nothing (the round is already on disk).
- The client keeps a per-endpoint replay cache of the current round's
  grads; any retryable failure reconnects (re-resolving the endpoint via
  discovery when a resolver is installed) and replays the round first.
- A server-side trainer lease (reference go/master/service.go:368
  checkTimeout) expires a trainer that dies mid-round: the sync fanin
  decrements and the surviving trainers' round completes.
"""
from __future__ import annotations

import os
import threading
import time
from concurrent import futures

import numpy as np

from . import compress as _czip
from .compress import Compressed
from paddle_tpu.core import sanitizer as _san
from .resilience import FLAGS, InjectedFault, RetryPolicy, fault_point, \
    maybe_corrupt as _maybe_corrupt

from paddle_tpu.observability import metrics as _obs_metrics
from paddle_tpu.observability.trace import TRACER as _TRC, \
    round_cid as _rcid

# always-on wire/round metrics; spans below additionally gate on
# _TRC.on (FLAGS_telemetry) and carry the (round, sender, seq) wire
# identity as a correlation id so merged traces line trainer and
# pserver timelines up (observability/export.py)
_M_BYTES_TX = _obs_metrics.counter(
    "rpc_bytes_sent_total", "payload bytes shipped to pservers")
_M_BYTES_RX = _obs_metrics.counter(
    "rpc_bytes_recv_total", "payload bytes fetched from pservers")
_M_TRAINER_ROUNDS = _obs_metrics.counter(
    "trainer_rounds_total", "sync rounds advanced by this trainer")
_M_PS_ROUNDS = _obs_metrics.counter(
    "pserver_rounds_applied_total", "sync rounds applied by this server")
_M_PS_BYTES_RX = _obs_metrics.counter(
    "pserver_bytes_recv_total", "scatter payload bytes received")
_M_DEDUP = _obs_metrics.counter(
    "pserver_dedup_drops_total",
    "replayed/duplicate grads dropped by (round, sender, seq) dedup")
_M_REPLAYS = _obs_metrics.counter(
    "rpc_round_replays_total", "client round replays after reconnect")
# gradient-compression effectiveness (ISSUE 10): raw vs on-wire payload
# bytes of every outbound grad (equal when compression is off/raw), the
# codec's encode cost, and the server-side staleness spread
_M_WIRE_RAW = _obs_metrics.counter(
    "wire_bytes_raw_total",
    "outbound grad payload bytes BEFORE compression")
_M_WIRE_COMP = _obs_metrics.counter(
    "wire_bytes_compressed_total",
    "outbound grad payload bytes as shipped (post-codec)")
_M_COMPRESS_MS = _obs_metrics.histogram(
    "compress_ms", "per-tensor gradient codec encode time")
_M_STALE_GAP = _obs_metrics.gauge(
    "pserver_staleness_gap",
    "barriered-round spread between the fastest and slowest live "
    "trainer (bounded-staleness mode; 0 in lockstep sync)")
# scale observatory (ISSUE 12): cache-eviction meters for the bounded
# reply/replay caches, and the quorum-bookkeeping work counter the
# before/after sweep charts (legacy rescan walks O(trainers) entries
# per ack; the incremental path walks 1)
_M_REPLY_EVICT = _obs_metrics.counter(
    "pserver_reply_cache_evictions_total",
    "encoded-reply entries evicted past FLAGS_pserver_reply_cache_mb")
_M_REPLAY_EVICT = _obs_metrics.counter(
    "rpc_replay_cache_evictions_total",
    "replay-cache rounds evicted past FLAGS_rpc_replay_cache_mb "
    "(an evicted round is unrecoverable on server restart and walks "
    "forward as an empty apply)")
_M_QUORUM_SCAN = _obs_metrics.counter(
    "pserver_quorum_scan_ops_total",
    "sender-map entries walked by barrier-quorum bookkeeping "
    "(incremental: ~2 per ack amortized; FLAGS_barrier_rescan legacy: "
    "O(trainers) per ack)")
# Watchtower (ISSUE 13): the barrier handler's wall time INCLUDING the
# durable-ack wait — the data-plane latency distribution the pserver
# SLOs (barrier p99) evaluate from the tsdb's sampled percentiles
_M_BARRIER_MS = _obs_metrics.histogram(
    "pserver_barrier_ms",
    "SendBarrier handler wall time incl. the durable-ack wait")

from paddle_tpu.observability import ledger as _ledger

# wire-format version: 2 adds compressed frames (kind byte 2).  A
# client only ships them to an endpoint whose WireVersion RPC
# advertises >= 2; old servers (no such method) get raw frames.
WIRE_VERSION = 2

SERVICE = "paddle_tpu.PServer"

# fastwire data plane: raw-socket port = grpc port + this offset
# (0 disables).  Handshake magic keeps a mis-aimed connection safe.
FASTWIRE_PORT_OFFSET = int(os.environ.get("FLAGS_fastwire_port_offset",
                                          "2000"))

# gRPC defaults cap messages at 4 MB; one fc shard of a real model is
# routinely 10-100 MB (the reference moved such blocks over raw sockets,
# ParameterServer2.h).  Unlimited on both directions.
GRPC_OPTIONS = [("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1)]


def _enc_arr(parts, arr):
    """Append one array as dtype | ndim | shape | raw bytes.  Raw
    tobytes instead of np.save: the npy framing costs a full extra
    buffer pass (~650 MB/s measured vs memcpy), and a 100 MB dense
    round serializes ~400 MB — the hot path the reference served with
    zero-copy sockets (ParameterServer2.h)."""
    # NOT np.ascontiguousarray unconditionally: it promotes 0-d to 1-d
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        # fail at the SENDER: tobytes() on an object array would ship
        # heap pointers and only blow up at the remote decoder
        raise TypeError("cannot send object-dtype array over the "
                        "pserver wire (got dtype=%s)" % arr.dtype)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")
    parts.append(len(dt).to_bytes(2, "little"))
    parts.append(dt)
    parts.append(arr.ndim.to_bytes(1, "little"))
    for d in arr.shape:
        parts.append(int(d).to_bytes(8, "little"))
    # memoryview, not tobytes(): join copies it once — tobytes would
    # make that two full passes over a 100 MB payload
    parts.append(arr.data)


def _dec_arr(view, off):
    """Zero-copy array decode from a memoryview.  The result is a
    READ-ONLY view over the message buffer — every in-repo consumer is
    functional (aggregation, optimize blocks, device_put all produce
    fresh arrays); a caller that wants to mutate must .copy()."""
    n = int.from_bytes(view[off:off + 2], "little")
    off += 2
    dtype = np.dtype(view[off:off + n].tobytes().decode("ascii"))
    off += n
    ndim = view[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(view[off:off + 8], "little"))
        off += 8
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(view[off:off + nbytes],
                        dtype=dtype).reshape(shape)
    return arr, off + nbytes


def _compressed_head(c):
    """Kind-2 frame sub-header: codec | param | height | dtype | shape |
    n_arrays — everything decode needs besides the codec arrays."""
    dt = c.dtype.str.encode("ascii")
    head = [b"\x02", c.codec.to_bytes(1, "little"),
            int(c.param).to_bytes(4, "little"),
            int(c.height).to_bytes(8, "little", signed=True),
            len(dt).to_bytes(2, "little"), dt,
            len(c.shape).to_bytes(1, "little")]
    for d in c.shape:
        head.append(int(d).to_bytes(8, "little"))
    head.append(len(c.arrays).to_bytes(1, "little"))
    return b"".join(head)


def _dec_compressed(view, off):
    codec = view[off]
    off += 1
    param = int.from_bytes(view[off:off + 4], "little")
    off += 4
    height = int.from_bytes(view[off:off + 8], "little", signed=True)
    off += 8
    n = int.from_bytes(view[off:off + 2], "little")
    off += 2
    dtype = np.dtype(view[off:off + n].tobytes().decode("ascii"))
    off += n
    ndim = view[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(view[off:off + 8], "little"))
        off += 8
    n_arrays = view[off]
    off += 1
    arrays = []
    for _ in range(n_arrays):
        a, off = _dec_arr(view, off)
        arrays.append(a)
    return Compressed(codec, param, dtype, shape, height, arrays), off


def _enc_tensor(name, arr, extra=0):
    """Wire format: name | extra | kind | arrays.  Kinds: 0 dense, 1
    SelectedRows (rows, values, height — reference VariableMessage's
    SELECTED_ROWS type, send_recv.proto:48), 2 compressed
    (wire-format v2: codec header + codec arrays, distributed/
    compress.py; decoded transparently by _dec_tensor)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    nb = name.encode("utf-8")
    parts = [len(nb).to_bytes(4, "little"), nb,
             int(extra).to_bytes(8, "little", signed=True)]
    if isinstance(arr, Compressed):
        parts.append(_compressed_head(arr))
        for a in arr.arrays:
            _enc_arr(parts, a)
    elif isinstance(arr, SelectedRows):
        parts.append(b"\x01")
        parts.append(int(arr.height).to_bytes(8, "little"))
        _enc_arr(parts, np.asarray(arr.rows))
        _enc_arr(parts, np.asarray(arr.values))
    else:
        parts.append(b"\x00")
        _enc_arr(parts, np.asarray(arr))
    return b"".join(parts)


def _dec_tensor(data):
    from paddle_tpu.core.selected_rows import SelectedRows

    view = memoryview(data)
    n = int.from_bytes(view[:4], "little")
    name = view[4:4 + n].tobytes().decode("utf-8")
    off = 4 + n
    extra = int.from_bytes(view[off:off + 8], "little", signed=True)
    off += 8
    kind = view[off]
    off += 1
    if kind == 1:
        height = int.from_bytes(view[off:off + 8], "little")
        off += 8
        rows, off = _dec_arr(view, off)
        values, off = _dec_arr(view, off)
        return name, SelectedRows(rows, values, height), extra
    if kind == 2:
        # compressed frame (wire v2): decode to the dense/SelectedRows
        # value HERE, before any aggregation/dedup logic sees it — the
        # (round, sender, seq) semantics operate on decoded tensors
        # exactly as on raw frames
        c, off = _dec_compressed(view, off)
        return name, _czip.decompress(c), extra
    arr, off = _dec_arr(view, off)
    return name, arr, extra


def _aligned_empty(shape, dtype):
    """64-byte-aligned uninitialized array.  jax's CPU backend
    ZERO-COPIES aligned numpy arrays into jit/device_put; np.empty's
    16-byte malloc alignment forces a full copy of every 50-100 MB
    parameter/gradient buffer at each staging (measured ~95 ms per
    105 MB) — alignment alone turns that into ~0."""
    dtype = np.dtype(dtype)
    shape = tuple(int(d) for d in shape)
    n = int(np.prod(shape)) if shape else 1
    raw = np.empty(n * dtype.itemsize + 64, np.uint8)
    off = (-raw.ctypes.data) % 64
    return raw[off:off + n * dtype.itemsize].view(dtype).reshape(shape)


# canonical byte-length of a parts list lives next to the vectored
# send that must agree with it — one helper, one definition
from .fastwire import _parts_len as _parts_nbytes  # noqa: E402


def _coalesce_parts(parts):
    """Merge adjacent small bytes heads so the vectored send stays a
    handful of iovecs; numpy payloads pass through untouched."""
    out = []
    for p in parts:
        if isinstance(p, bytes) and out and isinstance(out[-1], bytes) \
                and len(out[-1]) + len(p) < (1 << 16):
            out[-1] = out[-1] + p
        else:
            out.append(p)
    return out


def _enc_arr_parts(parts, arr):
    """_enc_arr without the join: appends the dtype|shape head as bytes
    and the array ITSELF — fastwire ships it by buffer address, so a
    100 MB payload is never copied into a Python-level join."""
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        raise TypeError("cannot send object-dtype array over the "
                        "pserver wire (got dtype=%s)" % arr.dtype)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")
    head = [len(dt).to_bytes(2, "little"), dt,
            arr.ndim.to_bytes(1, "little")]
    for d in arr.shape:
        head.append(int(d).to_bytes(8, "little"))
    parts.append(b"".join(head))
    parts.append(arr)


def _enc_tensor_parts(name, arr, extra=0):
    """_enc_tensor as a parts list (bytes heads + ndarray payloads):
    the same wire bytes, zero payload copies on the fastwire path."""
    from paddle_tpu.core.selected_rows import SelectedRows

    nb = name.encode("utf-8")
    head = (len(nb).to_bytes(4, "little") + nb
            + int(extra).to_bytes(8, "little", signed=True))
    parts = []
    if isinstance(arr, Compressed):
        parts.append(head + _compressed_head(arr))
        for a in arr.arrays:
            _enc_arr_parts(parts, a)
    elif isinstance(arr, SelectedRows):
        parts.append(head + b"\x01"
                     + int(arr.height).to_bytes(8, "little"))
        _enc_arr_parts(parts, np.asarray(arr.rows))
        _enc_arr_parts(parts, np.asarray(arr.values))
    else:
        parts.append(head + b"\x00")
        _enc_arr_parts(parts, np.asarray(arr))
    return _coalesce_parts(parts)


def _join_parts(parts):
    """Materialize a parts list into one bytes payload (the gRPC
    fallback — gRPC owns its own serialization anyway)."""
    return b"".join(p if isinstance(p, (bytes, bytearray))
                    else memoryview(p).cast("B") for p in parts)


def _enc_batch_parts(frames):
    """Batched wire frame: u32 count | count x (u64 len | frame), as a
    parts list.  Each sub-frame is a complete _enc_tensor/_enc_msg
    frame carrying its OWN (round, sender, seq) identity, so dedup and
    replay semantics are identical to the unbatched wire."""
    out = [len(frames).to_bytes(4, "little")]
    for parts in frames:
        out.append(_parts_nbytes(parts).to_bytes(8, "little"))
        out.extend(parts)
    return _coalesce_parts(out)


def _iter_batch(view):
    """Yield zero-copy sub-frame views of a batched payload."""
    view = memoryview(view)
    n = int.from_bytes(view[:4], "little")
    off = 4
    for _ in range(n):
        ln = int.from_bytes(view[off:off + 8], "little")
        off += 8
        yield view[off:off + ln]
        off += ln


def _enc_msg(name, extra=0):
    nb = name.encode("utf-8")
    return (len(nb).to_bytes(4, "little") + nb
            + int(extra).to_bytes(8, "little", signed=True))


def _dec_msg(data):
    n = int.from_bytes(data[:4], "little")
    name = bytes(data[4:4 + n]).decode("utf-8")
    extra = int.from_bytes(data[4 + n:12 + n], "little", signed=True)
    return name, extra


# -- (round, sender, seq) identity packed into the 8-byte extra field -----
# Bit 62 flags the packed form so a legacy plain-round extra (always a
# small non-negative step count) decodes as an anonymous send; then 14
# bits of per-sender send sequence (async dedup), 24 bits of round, and
# 24 bits of per-process sender token.
_WIRE_SENDER_FLAG = 1 << 62
_SEQ_MASK = (1 << 14) - 1
_ROUND_MASK = (1 << 24) - 1
_SENDER_MASK = (1 << 24) - 1


def _pack_round_sender(round_, sender, seq=0):
    return (_WIRE_SENDER_FLAG | ((int(seq) & _SEQ_MASK) << 48)
            | ((int(round_) & _ROUND_MASK) << 24)
            | (int(sender) & _SENDER_MASK))


def _unpack_round_sender(extra):
    """-> (round, sender, seq) — sender is None (and seq 0) for
    legacy/anonymous extras."""
    if extra > 0 and (extra & _WIRE_SENDER_FLAG):
        return ((extra >> 24) & _ROUND_MASK, extra & _SENDER_MASK,
                (extra >> 48) & _SEQ_MASK)
    return extra, None, 0


class VariableServer:
    """Parameter-server side: owns the scope, applies optimize blocks.

    ``grad_to_block``: grad(-block) var name -> pserver sub-block index.
    ``apply_block``: callable(block_idx) running one optimize sub-block
    against the server scope (wired to the executor by listen_and_serv).
    ``trainer_lease``: seconds of mid-round silence after which a known
    trainer is expired from the sync fanin (0 disables; reference
    go/master/service.go:368 checkTimeout).
    ``grad_params``: grad name -> tuple of vars its optimize block
    writes.  When given, each shard's params raise a per-shard
    completion event the moment ITS apply commits, so streamed gathers
    return a shard without gating on the whole round.
    """

    def __init__(self, scope, grad_to_block, apply_block, fanin,
                 sync_mode=True, checkpoint_dir=None,
                 checkpoint_every_n=0, trainer_lease=None,
                 grad_params=None, staleness=None):
        import grpc

        self.scope = scope
        self.grad_to_block = dict(grad_to_block)
        self.apply_block = apply_block
        self.fanin_total = int(fanin)
        self.sync_mode = bool(sync_mode)
        # bounded-staleness window (ISSUE 10): a barrier for round r
        # acks once round r-k is applied+durable, and gets accept
        # k-stale params — k=0 (the default) is lockstep sync,
        # bit-exact with the k-unaware wire
        self.staleness = max(0, int(FLAGS.dist_staleness
                                    if staleness is None else staleness))
        self.grad_params = {k: tuple(v) for k, v in grad_params.items()} \
            if grad_params else {}
        # shard checkpointing (reference go/pserver/service.go:346:
        # each pserver persists ITS parameter shard so a restarted
        # server resumes instead of reinitializing)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_n = int(checkpoint_every_n or 0)
        self.trainer_lease = float(
            FLAGS.trainer_lease if trainer_lease is None else trainer_lease)

        self._cv = _san.make_condition("rpc.server.cv")
        # grad name -> {sender key: array}; sender-keyed so a replayed
        # round overwrites instead of double-counting in the sync mean
        self._pending = {g: {} for g in self.grad_to_block}
        self._applied_round = 0
        # per-shard completion: param name -> rounds applied for ITS
        # shard (bumped mid-round, before _applied_round), plus the
        # in-flight apply guard for the lock-release windows
        self._param_ready = {}
        self._applying = False
        self._apply_target = -1
        # (name -> (ready-round, encoded parts, nbytes)): both trainers
        # fetch the same shard every round — materialize + encode it
        # once.  Byte-capped by FLAGS_pserver_reply_cache_mb (LRU:
        # insertion order refreshed on hit); _reply_bytes is the
        # incremental ledger the cap and the resource probe read.
        self._reply_cache = {}
        self._reply_bytes = 0
        # per-shard reader/writer fence: an optimize block DONATES its
        # param buffers to the jit call, so a prefetch gathering rows
        # from the zero-copy scope view must exclude the window where
        # that param's own block is dispatching (bounded staleness
        # serves reads during the round's apply; lockstep sync already
        # fences by round structure).  Readers are ~ms row gathers, so
        # the apply's wait-for-readers is negligible.
        self._shard_readers = {}      # param name -> active reader count
        self._shard_applying = set()  # params whose block is in flight
        # sender -> highest round barriered.  Persistent across rounds
        # (bounded staleness: a fast trainer's round r+j barrier also
        # witnesses every round <= r+j); the per-round count is derived
        # against _applied_round.
        self._barrier_rounds = {}
        self._legacy_barriers = 0       # anonymous (empty-payload) barriers
        self._anon_seq = 0
        # incremental barrier quorum (ISSUE 12): count of LIVE,
        # non-completed senders whose high-water barrier reached
        # _applied_round — maintained O(1) on the hot ack path and
        # recomputed O(senders) only on the rare events (round apply,
        # lease expiry, completion).  FLAGS_barrier_rescan restores
        # the legacy full rescan per ack for the scale lab's A/B.
        self._quorum = 0
        self._barrier_hi = -1           # max round any sender barriered
        self._stale_next = 0.0          # staleness-gauge refresh throttle
        # resource ledgers (ISSUE 12): incremental byte/entry counters
        # for the per-(round, sender) pending map, sampled by the
        # observability ledger collector via _ledger_probe
        self._pending_bytes = 0
        self._pending_entries = 0
        self._round_entries = {}        # round -> live pending entries
        self._round_seen = {}           # round -> first-seen monotonic
        self._senders = {}              # sender -> {"label", "last_seen"}
        self._expired = set()           # senders removed by lease expiry
        self._completed = set()         # senders that sent SendComplete
        self._async_applied = {}        # (sender, name) -> last applied seq
        self._alive = self.fanin_total
        self._shutdown = _san.make_event("rpc.server.shutdown")
        # one save at a time (sanitizer-adopted: FLAGS_sanitizer=locks
        # instruments acquisition order, core/sanitizer.py)
        self._ckpt_lock = _san.make_lock("rpc.server.ckpt")
        if checkpoint_dir:
            # restore AFTER the round counter exists: load_shard also
            # recovers _applied_round from _SUCCESS, or trainers
            # blocked in GetVariable(round=N) would wait forever on a
            # restarted server stuck at round 0
            for cand in (checkpoint_dir, checkpoint_dir + ".old"):
                if os.path.isdir(cand) and os.path.exists(
                        os.path.join(cand, "_SUCCESS")):
                    self.load_shard(cand)
                    break
        # rounds that are visible AND safe against a crash: equal to
        # _applied_round except inside a checkpoint-write window
        self._durable_round = self._applied_round
        # weakref-owned: a server that is simply dropped (tests) falls
        # out of the ledger without an explicit unregister
        self._ledger_handle = _ledger.register(
            "pserver", VariableServer._ledger_probe, owner=self)
        # Watchtower (ISSUE 13): with FLAGS_tsdb_dir set, this server
        # process retains its metric history (rounds, barrier p99,
        # pending bytes via the ledger mirror) and arms the SLO
        # evaluator.  No-op without the flag; best-effort always.
        try:
            from paddle_tpu.observability import tsdb as _tsdb
            _tsdb.ensure_sampler()
        except Exception:
            pass

        handlers = {
            "SendVariable": self._h(self._send_variable),
            "SendVariables": self._h(self._send_variables),
            "GetVariable": self._h(self._get_variable),
            "GetVariables": self._h(self._get_variables),
            "PrefetchVariable": self._h(self._prefetch_variable),
            "SendBarrier": self._h(self._send_barrier),
            "FetchBarrier": self._h(self._fetch_barrier),
            "BarrierStatus": self._h(self._barrier_status),
            "ToggleProfile": self._h(self._toggle_profile),
            "SendComplete": self._h(self._send_complete),
            "WireVersion": self._h(self._wire_version),
        }
        # enough workers that fanin-1 blocked GetVariable waiters (plus
        # retried barrier handlers that linger until their client's
        # cancellation is noticed) can never starve the SendBarrier that
        # would wake them
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max(32, 8 * self.fanin_total + 8)),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(SERVICE, handlers),))

    @staticmethod
    def _h(fn):
        import grpc

        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(req, ctx))

    # -- lifecycle --
    def start(self, endpoint):
        """Bind + start; returns the bound port.  Also opens the
        fastwire raw-socket DATA plane at port+FASTWIRE_PORT_OFFSET
        (reference pserver/LightNetwork.cpp role): SendVariable /
        GetVariable bulk frames bypass Python gRPC; control RPCs
        (barriers, completion, profile) stay here.  Best-effort: no
        native toolchain or a taken port just means gRPC carries
        everything, as before."""
        port = self._server.add_insecure_port(endpoint)
        self._server.start()
        self._fast = None
        if FASTWIRE_PORT_OFFSET > 0:
            try:
                from . import fastwire
                self._fast = fastwire.FastServer(
                    port + FASTWIRE_PORT_OFFSET,
                    {"SendVariable": self._send_variable,
                     "GetVariable": self._get_variable,
                     "SendVariables": self._send_variables,
                     # embedding-row prefetches are bulk frames too
                     # (a CTR step moves tens of MB of rows)
                     "PrefetchVariable": self._prefetch_variable,
                     # streamed batched gather: frames go out per-shard
                     # the moment each apply commits
                     "GetVariables": (self._get_variables_stream,
                                      "stream")})
            except Exception:
                self._fast = None
        if self.sync_mode and self.trainer_lease > 0:
            threading.Thread(target=self._lease_loop, daemon=True).start()
        if self.sync_mode and self.staleness > 0:
            # bounded staleness: the apply must NOT run inside a
            # barrier handler (the handler's ack would then wait on the
            # apply it is itself executing, and no trainer could run
            # ahead) — a dedicated worker applies rounds as their
            # barriers complete, handlers just ack at durable > r-k
            threading.Thread(target=self._apply_loop, daemon=True).start()
        return port

    def _apply_loop(self):
        """Background apply worker (staleness > 0 only): applies each
        round the moment its barriers are complete, off every handler
        thread, and publishes durability for the relaxed acks."""
        while not self._shutdown.is_set():
            with self._cv:
                while not (0 < self._alive <= self._barrier_count()) \
                        and not self._shutdown.is_set():
                    self._cv.wait(timeout=0.25)
                if self._shutdown.is_set():
                    return
                snapshot = self._maybe_apply_locked()
            self._persist_and_ack(snapshot)

    def wait(self):
        """Block until every trainer sent SendComplete."""
        self._shutdown.wait()
        if getattr(self, "_fast", None) is not None:
            self._fast.stop()
        self._server.stop(grace=1).wait()
        _ledger.unregister(self._ledger_handle)

    def _ledger_probe(self):
        """Resource-ledger probe (ISSUE 12): O(1) reads of the
        incremental counters this class maintains on its own paths.
        Deliberately lock-free — GIL-consistent int reads; a torn
        sample is a diagnostic hiccup, a probe that contends the
        server lock at collector cadence is overhead."""
        backlog = self._barrier_hi - self._applied_round + 1
        seen = list(self._round_seen.values())
        oldest = (time.monotonic() - min(seen)) if seen else 0.0
        return {
            "pserver_pending_grad_bytes": self._pending_bytes,
            "pserver_pending_grad_entries": self._pending_entries,
            "pserver_reply_cache_bytes": self._reply_bytes,
            "pserver_reply_cache_entries": len(self._reply_cache),
            "pserver_barrier_set": self._quorum + self._legacy_barriers,
            "pserver_apply_backlog_rounds": max(0, backlog),
            "pserver_oldest_pending_age_s": round(oldest, 3),
            "pserver_known_senders": len(self._senders),
        }

    # -- condition helpers --
    def _wait_cv(self, pred, ctx):
        """Wait (lock held) until ``pred`` or shutdown; polls so a
        handler whose client cancelled/died exits instead of pinning a
        pool thread forever.  Returns False when the client vanished."""
        while not pred() and not self._shutdown.is_set():
            if ctx is not None and not ctx.is_active():
                return False
            self._cv.wait(timeout=0.25)
        return True

    def _touch(self, sender, label=None):
        """Record contact from ``sender`` (lock held).  An expired
        trainer that turns out to be alive rejoins the fanin."""
        ent = self._senders.get(sender)
        if ent is None:
            ent = {"label": "sender-%06x" % sender, "last_seen": 0.0}
            self._senders[sender] = ent
        if label:
            ent["label"] = label
        ent["last_seen"] = time.time()
        if sender in self._expired:
            self._expired.discard(sender)
            self._alive = min(self._alive + 1, self.fanin_total)
            if self._barrier_rounds.get(sender, -1) \
                    >= self._applied_round \
                    and sender not in self._completed:
                # rejoined WITH a standing barrier for the current
                # round: it re-enters the incremental quorum
                self._quorum += 1
                _M_QUORUM_SCAN.inc()

    def _barrier_count(self):
        """Barriers witnessing the round about to apply (lock held):
        LIVE senders whose highest barriered round reached
        _applied_round, plus the legacy anonymous count.  Served from
        the incrementally-maintained ``_quorum`` — the legacy full
        rescan (FLAGS_barrier_rescan) cost O(trainers) per ack, i.e.
        O(trainers²) per round, the first knee the scale lab charts
        (tools/scale_bench.py --before-after)."""
        if FLAGS.barrier_rescan:
            return self._barrier_scan_locked()
        return self._quorum + self._legacy_barriers

    def _barrier_scan_locked(self):
        """The full-rescan quorum (lock held) — the pre-ISSUE-12
        definition, kept as the A/B arm and the parity oracle for
        ``_quorum``.  Completed and expired senders are excluded on
        purpose: their grads for every round they witnessed are
        already in (or gone forever), and counting their persistent
        high-water barriers against the ``alive`` quota would let
        rounds apply before a slower LIVE peer barriered them — that
        peer's late grads would then be dedup-dropped as stale,
        violating the bounded-staleness contract (delayed <= k, never
        discarded).  An unseen live trainer contributes nothing here,
        so the count also cannot reach ``alive`` while someone has not
        even connected."""
        _M_QUORUM_SCAN.inc(len(self._barrier_rounds))
        return sum(1 for s, r in self._barrier_rounds.items()
                   if r >= self._applied_round
                   and s not in self._completed
                   and s not in self._expired) + self._legacy_barriers

    def _quorum_recompute_locked(self):
        """Rebuild ``_quorum`` from scratch (lock held) — the rare-
        event path: round apply (applied_round moved), lease expiry,
        sender completion.  Hot acks never pay this walk."""
        _M_QUORUM_SCAN.inc(len(self._barrier_rounds))
        self._quorum = sum(1 for s, r in self._barrier_rounds.items()
                           if r >= self._applied_round
                           and s not in self._completed
                           and s not in self._expired)

    def _barrier_max(self):
        # _barrier_hi is maintained at every barrier write and can only
        # grow, exactly like max() over the (never-shrinking) map
        return self._barrier_hi

    def _maybe_apply_locked(self):
        """Apply every round whose barriers are complete (lock held).
        Returns a state snapshot the CALLER must persist (outside the
        lock) before bumping _durable_round, or None.  ``_applying``
        guards re-entry: _apply_round releases the lock around each
        optimize block, so another handler can get here mid-round.
        Loops: under bounded staleness a straggler's barrier can
        complete SEVERAL pent-up rounds at once (the fast trainers'
        later barriers witness every earlier round), and a server
        restarted from a checkpoint OLDER than the trainers' rounds
        walks forward through the missing rounds — each applies only
        ITS OWN pending grads, so the rounds whose grads are
        unrecoverable (outside every trainer's replay window) pass as
        cheap empty applies instead of double-counting replays.  At
        k=0 in steady state at most one round can ever be complete, so
        one iteration runs — the lockstep path is unchanged."""
        need_ckpt = False
        while not self._applying and \
                0 < self._alive <= self._barrier_count():
            self._apply_round()
            if (self.checkpoint_every_n and self.checkpoint_dir and
                    self._applied_round % self.checkpoint_every_n == 0):
                # collect under the lock, WRITE outside it — disk I/O
                # must not stall every other RPC handler
                need_ckpt = True
            elif not need_ckpt:
                # no checkpoint pending: the round is durable the
                # moment it applied (once a checkpoint IS pending,
                # durability may not advance past it until persisted)
                self._durable_round = self._applied_round
        return self._collect_state() if need_ckpt else None

    def _persist_and_ack(self, snapshot):
        """Write the snapshot, then publish durability (barrier acks for
        this round are blocked until _durable_round catches up)."""
        if snapshot is None:
            return
        self.save_shard(self.checkpoint_dir, snapshot)
        with self._cv:
            self._durable_round = self._applied_round
            self._cv.notify_all()

    def _lease_loop(self):
        """Expire trainers that die mid-round: when barriers are stalled
        and a KNOWN sender that has not barriered this round has been
        silent past the lease, drop it from the fanin and complete the
        round with the survivors (mirrors Master._check_timeouts)."""
        interval = max(0.05, self.trainer_lease / 3.0)
        while not self._shutdown.wait(interval):
            snapshot = None
            with self._cv:
                if self._barrier_count() == 0:
                    continue    # nobody is waiting on a round
                now = time.time()
                for sender, ent in list(self._senders.items()):
                    if self._barrier_rounds.get(sender, -1) \
                            >= self._applied_round or \
                            sender in self._expired or \
                            sender in self._completed:
                        continue   # contributed, gone, or cleanly done
                    if now - ent["last_seen"] > self.trainer_lease:
                        self._expired.add(sender)
                        self._alive -= 1
                # expiry changes quorum membership; lease cadence is
                # rare, so the full rebuild is the simple correct move
                self._quorum_recompute_locked()
                snapshot = self._maybe_apply_locked()
            self._persist_and_ack(snapshot)

    # -- handlers --
    def _store_grad_locked(self, name, arr, extra):
        """One decoded tensor into the pending/apply machinery (lock
        held) — shared by the unbatched and batched scatter handlers."""
        round_, sender, seq = _unpack_round_sender(extra)
        if sender is not None:
            self._touch(sender)
        if name not in self._pending:
            # direct write (e.g. init push or non-optimized var)
            self.scope.set(name, arr)
            self._reply_drop_locked(name)
            return
        if sender is None:
            key = (int(round_) if isinstance(round_, int) else 0,
                   ("anon", self._anon_seq))
            self._anon_seq += 1
        else:
            if self.sync_mode and (
                    round_ < self._applied_round
                    or (self._applying and round_ < self._apply_target)):
                # stale replay of an applied round — including one that
                # slips through the apply loop's lock-release window
                # (its grads are already counted in the in-flight round)
                _M_DEDUP.inc()
                return
            if not self.sync_mode and seq and \
                    self._async_applied.get((sender, name)) == seq:
                # async applies on arrival and clears pending, so
                # the round-replay dedup can't help a retried send:
                # the per-sender send sequence is what makes a
                # resend of an already-applied grad a no-op
                _M_DEDUP.inc()
                return
            # keyed by (round, sender): under bounded staleness a fast
            # trainer's round r+1 grad arrives BEFORE round r applied —
            # it must accumulate, not overwrite, while a same-round
            # replay still lands on its own key (dedup by overwrite).
            # At k=0 every pending entry names the current round, so
            # insertion (= arrival) order and the aggregation mean are
            # bit-identical to the round-keyless wire.
            key = (int(round_), sender)
        ent = self._pending[name]
        old = ent.get(key)
        if old is not None:
            # same-key replay overwrites: swap its bytes in the ledger
            self._pending_bytes -= _ledger.value_nbytes(old)
        else:
            self._pending_entries += 1
            r = key[0]
            self._round_entries[r] = self._round_entries.get(r, 0) + 1
            # first pending entry of this round stamps its age — the
            # ledger's oldest-round-age resource reads it
            self._round_seen.setdefault(r, time.monotonic())
        self._pending_bytes += _ledger.value_nbytes(arr)
        ent[key] = arr
        if not self.sync_mode:
            self._apply_one(name)
            if sender is not None and seq:
                self._async_applied[(sender, name)] = seq
            self._cv.notify_all()

    def _inbound_health(self, name, arr, extra):
        """Numerics observatory (ISSUE 8): health-check one inbound
        grad — a poisoned round gets attributed to its (round, sender)
        cid in a numerics_*.json artifact, so the fault_matrix
        'numerics' preset (and a real mixed-precision blowup on a
        trainer) names the trainer that shipped it.  A no-op (one flag
        read) with FLAGS_check_numerics=off; with a mode on, the
        isfinite pass costs one read of the payload (the batched
        handler pays it under the scatter lock — acceptable for a
        debugging/observability tier, never on by default)."""
        from paddle_tpu.observability import numerics as _numerics

        try:
            round_, sender, _ = _unpack_round_sender(extra)
            _numerics.server_check_grad(name, arr, round_, sender)
        except Exception:
            pass  # diagnostics never sink the scatter they observe

    def _send_variable(self, req, ctx=None):
        _M_PS_BYTES_RX.inc(len(req))
        name, arr, extra = _dec_tensor(req)
        self._inbound_health(name, arr, extra)
        sp = None
        if _TRC.on:
            round_, sender, _ = _unpack_round_sender(extra)
            sp = _TRC.begin(
                "pserver.scatter",
                _rcid(round_) if sender is not None else None,
                {"n": 1})
        try:
            with self._cv:
                self._store_grad_locked(name, arr, extra)
        finally:
            if sp is not None:
                _TRC.end(sp)
        return b""

    def _send_variables(self, req, ctx=None):
        """Batched scatter: every shard a trainer routes to this
        endpoint in one frame, decoded zero-copy sub-frame by
        sub-frame.  Each carries its own (round, sender, seq) identity,
        so dedup/replay semantics match the unbatched wire exactly."""
        _M_PS_BYTES_RX.inc(len(req))
        sp = _TRC.begin("pserver.scatter") if _TRC.on else None
        n = 0
        try:
            with self._cv:
                for frame in _iter_batch(req):
                    name, arr, extra = _dec_tensor(frame)
                    self._inbound_health(name, arr, extra)
                    if sp is not None and sp.cid is None:
                        round_, sender, _ = _unpack_round_sender(extra)
                        if sender is not None:
                            sp.cid = _rcid(round_)
                            sp.args = {"sender": "%06x" % sender}
                    self._store_grad_locked(name, arr, extra)
                    n += 1
        finally:
            if sp is not None:
                _TRC.end(sp, args={"n": n})
        return b""

    def _send_barrier(self, req, ctx=None):
        # span covers the whole handler INCLUDING the durable-ack wait:
        # a hang here shows up in the flight recorder as an open
        # pserver.barrier span with the sender in its args (sp is None
        # when tracing is off; _send_barrier_impl tolerates that)
        t0 = time.perf_counter()
        try:
            with _TRC.span("pserver.barrier") as sp:
                return self._send_barrier_impl(req, ctx, sp)
        finally:
            _M_BARRIER_MS.observe((time.perf_counter() - t0) * 1e3)

    def _send_barrier_impl(self, req, ctx, sp):
        snapshot = None
        with self._cv:
            if req:
                label, extra = _dec_msg(req)
                round_, sender, _ = _unpack_round_sender(extra)
            else:
                label, round_, sender = None, None, None
            if sender is not None:
                if sp is not None:
                    sp.cid = _rcid(round_)
                    sp.args = {"sender": label}
                self._touch(sender, label)
                if round_ >= self._applied_round:
                    prev = self._barrier_rounds.get(sender, -1)
                    self._barrier_rounds[sender] = max(prev, round_)
                    if round_ > self._barrier_hi:
                        self._barrier_hi = round_
                    if prev < self._applied_round \
                            and sender not in self._completed \
                            and sender not in self._expired:
                        # first barrier from this sender to reach the
                        # applying round: O(1) quorum bump — the whole
                        # point of the incremental bookkeeping
                        self._quorum += 1
                        _M_QUORUM_SCAN.inc()
                    self._update_staleness_locked()
                    if self.staleness > 0:
                        # wake the apply worker; this handler only
                        # waits for durable > r-k below
                        self._cv.notify_all()
                    else:
                        snapshot = self._maybe_apply_locked()
                # else: replay of an applied round — do NOT join the
                # current round's barrier set, but do NOT ack early
                # either: the round may still be mid-checkpoint-write,
                # and the ack must imply durability (the wait below is
                # instant once _durable_round caught up)
            else:
                round_ = None    # legacy wire: count it, ack immediately
                self._legacy_barriers += 1
                snapshot = self._maybe_apply_locked()
        self._persist_and_ack(snapshot)
        if round_ is None:
            return b""  # legacy anonymous barrier: ack immediately
        # ack only once the round is applied AND (on checkpoint rounds)
        # durably on disk — a crash before this point leaves every
        # trainer un-acked and replaying the round, so nothing is lost.
        # Bounded staleness relaxes this by k rounds: the trainer may
        # run ahead while the last k rounds are still applying (and a
        # crash can lose at most those k un-acked rounds).
        k = self.staleness
        with self._cv:
            self._wait_cv(lambda: self._durable_round > round_ - k, ctx)
        return b""

    def _update_staleness_locked(self):
        """Refresh the fast-vs-slow barrier spread gauge (lock held).
        Throttled past 32 senders: the spread scan is O(senders), and
        per-ack it would be O(trainers²) per round at 256 trainers —
        a 20 Hz gauge is every bit as observable.  Small fanins stay
        per-ack exact."""
        if len(self._barrier_rounds) > 32:
            now = time.monotonic()
            if now < self._stale_next:
                return
            self._stale_next = now + 0.05
        live = [r for s, r in self._barrier_rounds.items()
                if s not in self._expired and s not in self._completed]
        if len(live) >= 2:
            _M_STALE_GAP.set(max(live) - min(live))

    def _wire_version(self, req, ctx=None):
        """Wire-format negotiation (ISSUE 10): a client probes this
        before shipping compressed (kind 2) frames; an OLD server has
        no such method, the call fails UNIMPLEMENTED, and the client
        falls back to raw frames for that endpoint — see MIGRATION.md."""
        return _enc_msg(",".join(sorted(_czip.CODECS)), WIRE_VERSION)

    # -- shard checkpointing ------------------------------------------
    def _collect_state(self):
        """Snapshot (name, array) pairs — cheap reference grabs; scope
        writes REPLACE values, so held arrays stay consistent."""
        snap = []
        for name in self.scope.local_var_names():
            try:
                arr = np.asarray(self.scope.find_var(name))
            except Exception:
                continue  # live channels/readers &c. are not state
            if arr.dtype == object:
                continue
            snap.append((name, arr))
        return snap, self._applied_round

    def save_shard(self, dirname, snapshot=None):
        """Persist the shard.  Crash-safe: write to a tmp dir, keep the
        previous checkpoint at <dirname>.old until the new one is in
        place (load falls back to .old, so a kill between the renames
        cannot lose the only good checkpoint).  Filenames are
        URL-quoted var names (injective both ways)."""
        from urllib.parse import quote

        import shutil

        snap, round_ = snapshot if snapshot is not None \
            else self._collect_state()
        with self._ckpt_lock:  # overlapping rounds must not interleave
            tmp = dirname + ".tmp.%d" % os.getpid()
            # start CLEAN: a previously aborted save must not leak its
            # stale files into this checkpoint (load_shard reads every
            # file in the dir)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            for name, arr in snap:
                with open(os.path.join(tmp, quote(name, safe="")),
                          "wb") as f:
                    np.save(f, arr)
            from paddle_tpu.core.fsutil import atomic_write
            atomic_write(os.path.join(tmp, "_SUCCESS"), str(round_))
            old = dirname + ".old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.isdir(dirname):
                os.rename(dirname, old)
            os.rename(tmp, dirname)
            shutil.rmtree(old, ignore_errors=True)

    def load_shard(self, dirname):
        from urllib.parse import unquote

        for fn in os.listdir(dirname):
            if fn == "_SUCCESS":
                with open(os.path.join(dirname, fn)) as f:
                    try:
                        self._applied_round = int(f.read().strip() or 0)
                    except ValueError:
                        pass
                continue
            with open(os.path.join(dirname, fn), "rb") as f:
                self.scope.set(unquote(fn), np.load(f))

    def _ready_locked(self, name, round_):
        """True when ``name`` is safe to serve at ``round_``: the whole
        round applied, or — mid-round — this shard's own apply already
        committed (per-shard completion event via grad_params).  Under
        bounded staleness the effective wait round relaxes by k: a get
        may observe params missing up to the last k rounds' updates."""
        eff = round_ - self.staleness
        if self._applied_round >= eff:
            return True
        r = self._param_ready.get(name)
        return r is not None and r >= eff

    def _read_var_locked(self, name, ctx=None):
        """Materialize a scope value robustly (lock held).  Under
        bounded staleness a k-stale read is allowed WHILE the value's
        own optimize block is in flight — and that apply DONATES the
        param buffer, so the scope can briefly hold an invalidated jax
        array.  On such a read, wait for the apply to commit its fresh
        buffer and retry.  Returns None only when the client vanished
        mid-wait."""
        from paddle_tpu.core.selected_rows import SelectedRows

        for _ in range(10000):
            val = self.scope.find_var(name)
            if _san.is_husk(val):
                # buffer sanitizer (ISSUE 14): the slot names the
                # donation that consumed it.  With the apply in flight
                # this is the SANCTIONED k-stale read racing the
                # optimize block's donated params (the PR 10 fence):
                # wait for the commit to re-bind, don't trip.  With no
                # apply in flight the re-bind never happened — surface
                # the named BufferLifetimeError.
                if self._applying or name in self._shard_applying:
                    if not self._wait_cv(lambda: not self._applying,
                                         ctx):
                        return None
                    continue
                val._trip()
            try:
                if isinstance(val, SelectedRows):
                    return SelectedRows(np.asarray(val.rows),
                                        np.asarray(val.values),
                                        val.height)
                return np.asarray(val)
            except Exception:
                # donated husk: the in-flight apply owns the buffer —
                # its commit (scope.set) publishes a fresh one
                if not self._wait_cv(lambda: not self._applying, ctx):
                    return None
        raise RuntimeError(
            "pserver could not materialize %r: buffer repeatedly "
            "invalidated by concurrent applies" % name)

    def _reply_drop_locked(self, name):
        """Remove one reply-cache entry, keeping the byte ledger exact
        (lock held)."""
        ent = self._reply_cache.pop(name, None)
        if ent is not None:
            self._reply_bytes -= ent[2]

    def _materialize_locked(self, name, ctx=None):
        """Encoded parts for ``name``'s current value (lock held).
        Cached per shard-round: with fanin trainers fetching the same
        shard every round, the host materialization + encode happens
        once, not fanin times.  Byte-capped (ISSUE 12): past
        FLAGS_pserver_reply_cache_mb the least-recently-served entries
        evict (metered) — an eviction only costs a re-encode on the
        next get, so cached replies can never OOM a 256-trainer
        server."""
        key = self._param_ready.get(name, self._applied_round)
        ent = self._reply_cache.get(name)
        if ent is not None and ent[0] == key:
            # LRU refresh: dicts iterate in insertion order, so a
            # move-to-end keeps eviction aimed at cold shards
            self._reply_cache[name] = self._reply_cache.pop(name)
            return ent[1]
        # materialize INSIDE the lock: a concurrent async-mode apply
        # donates the param's device buffer, invalidating it
        val = self._read_var_locked(name, ctx)
        if val is None:
            return []
        parts = _enc_tensor_parts(name, val)
        self._reply_drop_locked(name)   # stale-round entry, if any
        nbytes = _parts_nbytes(parts)
        self._reply_cache[name] = (key, parts, nbytes)
        self._reply_bytes += nbytes
        cap = float(FLAGS.pserver_reply_cache_mb) * 1e6
        while cap > 0 and self._reply_bytes > cap \
                and len(self._reply_cache) > 1:
            oldest = next(iter(self._reply_cache))
            if oldest == name:
                break   # never evict the entry being served
            self._reply_drop_locked(oldest)
            _M_REPLY_EVICT.inc()
        return parts

    def _invalidate_locked(self, gname):
        """Drop cached replies a just-applied block may have rewritten
        (lock held).  Without a grad->outputs map we cannot know what
        the block wrote — drop everything."""
        self._reply_drop_locked(gname)
        outs = self.grad_params.get(gname)
        if outs is None:
            self._reply_cache.clear()
            self._reply_bytes = 0
        else:
            for p in outs:
                self._reply_drop_locked(p)

    def _get_variable(self, req, ctx=None):
        name, round_ = _dec_msg(req)
        with self._cv:
            if self.sync_mode:
                if not self._wait_cv(
                        lambda: self._ready_locked(name, round_), ctx):
                    return b""  # client gone: response is discarded
            parts = self._materialize_locked(name)
        return _join_parts(parts)

    def _get_variables(self, req, ctx=None):
        """Batched gather, unary (gRPC fallback): waits until every
        requested shard is ready, replies with the frames
        length-prefixed back to back (count known to the caller)."""
        items = [_dec_msg(f) for f in _iter_batch(req)]
        sp = None
        if _TRC.on and items:
            r = max(min(r for _, r in items) - 1, 0)
            sp = _TRC.begin("pserver.gather", _rcid(r),
                            {"n": len(items)})
        try:
            with self._cv:
                if self.sync_mode:
                    if not self._wait_cv(
                            lambda: all(self._ready_locked(n, r)
                                        for n, r in items), ctx):
                        return b""
                frames = [self._materialize_locked(n) for n, _ in items]
        finally:
            if sp is not None:
                _TRC.end(sp)
        out = []
        for parts in frames:
            out.append(_parts_nbytes(parts).to_bytes(8, "little"))
            out.extend(parts)
        return _join_parts(out)

    def _get_variables_stream(self, req, write):
        """Batched gather over fastwire: each shard's frame is written
        the MOMENT its apply commits (per-shard completion events from
        the apply loop) instead of gating every get on the whole round
        — the full-duplex half of send/apply/get overlap."""
        remaining = {}
        for f in _iter_batch(req):
            name, round_ = _dec_msg(f)
            remaining[name] = round_
        sp = None
        if _TRC.on and remaining:
            # get(round=N) serves the params trainer round N-1 produced
            r = max(min(remaining.values()) - 1, 0)
            sp = _TRC.begin("pserver.gather", _rcid(r),
                            {"n": len(remaining)})
        try:
            while remaining:
                with self._cv:
                    if self.sync_mode:
                        self._wait_cv(
                            lambda: any(self._ready_locked(n, r)
                                        for n, r in remaining.items()),
                            None)
                        ready = [n for n, r in remaining.items()
                                 if self._ready_locked(n, r)]
                        if not ready:  # shutdown mid-wait: serve current
                            ready = list(remaining)
                    else:
                        ready = list(remaining)
                    frames = [self._materialize_locked(n) for n in ready]
                for name, parts in zip(ready, frames):
                    write([_parts_nbytes(parts).to_bytes(8, "little")]
                          + list(parts))
                    del remaining[name]
        finally:
            # a write() failure (client died mid-stream) must not leak
            # a forever-open span onto this handler thread's stack —
            # the flight recorder would report a phantom blocked gather
            if sp is not None:
                _TRC.end(sp)

    def _prefetch_variable(self, req, ctx=None):
        """Row-subset read of a sharded table (reference
        send_recv.proto:27 PrefetchVariable + grpc_server.cc prefetch
        path): request carries LOCAL row ids of this server's block;
        response is the gathered rows.  Sync-mode waits for the same
        applied round as GetVariable so a prefetch never reads a table
        mid-update."""
        name, ids, round_ = _dec_tensor(req)
        with self._cv:
            if self.sync_mode:
                eff = round_ - self.staleness
                if not self._wait_cv(
                        lambda: self._applied_round >= eff, ctx):
                    return b""
            # reader side of the per-shard fence: never gather while
            # the table's own optimize block is dispatching (the jit
            # call owns — and will delete — the scope buffer)
            if not self._wait_cv(
                    lambda: name not in self._shard_applying, ctx):
                return b""
            self._shard_readers[name] = \
                self._shard_readers.get(name, 0) + 1
        try:
            table = np.asarray(self.scope.find_var(name))
            rows = table[np.asarray(ids, np.int64)]
        finally:
            with self._cv:
                self._shard_readers[name] -= 1
                self._cv.notify_all()
        return _enc_tensor(name, rows)

    def _fetch_barrier(self, req, ctx=None):
        return b""

    def _barrier_status(self, req, ctx=None):
        """Introspection for the trainer-side watchdog: who barriered
        the current round, and who the server is still waiting on."""
        import json

        with self._cv:
            arrived = sorted(
                self._senders[s]["label"]
                for s, r in self._barrier_rounds.items()
                if r >= self._applied_round and s in self._senders)
            known = sorted(
                ent["label"] for s, ent in self._senders.items()
                if s not in self._expired)
            sender_rounds = {
                self._senders[s]["label"]: r
                for s, r in self._barrier_rounds.items()
                if s in self._senders}
            status = {
                "applied_round": self._applied_round,
                "durable_round": self._durable_round,
                "alive": self._alive,
                "fanin": self.fanin_total,
                "barriers": self._barrier_count(),
                "staleness": self.staleness,
                "sender_rounds": sender_rounds,
                "arrived": arrived,
                "known": known,
                "waiting_for": sorted(set(known) - set(arrived)),
            }
        # Watchtower (ISSUE 13): currently-firing burn-rate alerts ride
        # the same introspection reply the watchdog already polls, so
        # "is the server healthy" and "is it meeting its SLOs" are one
        # call.  Best-effort — an empty list when no evaluator runs.
        try:
            from paddle_tpu.observability import slo as _slo
            status["slo_alerts"] = _slo.alerts_brief()
        except Exception:
            status["slo_alerts"] = []
        return json.dumps(status).encode()

    def _toggle_profile(self, req, ctx=None):
        """Trainer-driven server profiling (reference
        send_recv.proto:76 VariableMessage.profile: the trainer's
        profiler state rides the RPC envelope and switches the
        pserver's profiler).  extra=1 starts, extra=0 stops and writes
        the table to the named path.  Idempotent across trainers: with
        fanin>1 every trainer's toggle reaches the server, so redundant
        start/stop must be no-ops, and the default path is per-process
        (a fixed /tmp name would be predictable and cross-server
        clobbering)."""
        from paddle_tpu.fluid import profiler as prof

        path, on = _dec_msg(req)
        with self._cv:
            if bool(on) == getattr(self, "_profiling", False):
                return b""       # redundant toggle from another trainer
            self._profiling = bool(on)
        if on:
            prof.start_profiler(state="CPU")
        else:
            if not path:
                import tempfile
                path = os.path.join(
                    tempfile.mkdtemp(prefix="pserver_prof_"),
                    "profile")
            prof.stop_profiler(sorted_key="total", profile_path=path)
        return b""

    def _send_complete(self, req, ctx=None):
        snapshot = None
        with self._cv:
            sender = None
            if req:
                _, extra = _dec_msg(req)
                _, sender, _ = _unpack_round_sender(extra)
            if sender is None:
                self._alive -= 1        # legacy anonymous complete
            elif sender in self._completed:
                pass                    # duplicate/retried complete
            else:
                self._completed.add(sender)
                if sender in self._expired:
                    # the lease already decremented for this trainer —
                    # a second decrement would shut the server down
                    # under trainers still mid-round
                    self._expired.discard(sender)
                else:
                    self._alive -= 1
            # completion excludes the sender from the quorum — rebuild
            # (once per trainer lifetime; never on the ack path)
            self._quorum_recompute_locked()
            if self._alive <= 0:
                # drain before shutdown: under bounded staleness the
                # last k rounds can still be pending when the final
                # complete arrives — every completed sender barriered
                # them, so finish the in-flight apply and run the rest
                # now (at k=0 every acked round already applied and
                # this loop is a no-op)
                while True:
                    if self._applying:
                        # the apply worker owns a round right now
                        # (lock released around its optimize blocks) —
                        # wait it out, then re-check for more
                        self._cv.wait(timeout=0.05)
                        continue
                    if self._barrier_max() < self._applied_round:
                        break
                    self._apply_round()
                    if (self.checkpoint_every_n and self.checkpoint_dir
                            and self._applied_round
                            % self.checkpoint_every_n == 0):
                        snapshot = self._collect_state()
                    else:
                        self._durable_round = self._applied_round
                self._shutdown.set()
            else:
                # stragglers of a half-round: apply what arrived
                snapshot = self._maybe_apply_locked()
            self._cv.notify_all()
        self._persist_and_ack(snapshot)
        return b""

    # -- application (lock held) --
    def _aggregate_locked(self, gname, upto=None):
        """Mean the pending grads for ``gname`` with round <= ``upto``
        (None = everything) and remove them (lock held); None when
        nothing arrived this round.  Later rounds' entries — a fast
        trainer running ahead under bounded staleness — stay pending
        for THEIR round's apply."""
        from paddle_tpu.core.selected_rows import SelectedRows

        ent = self._pending[gname]
        if upto is None:
            keys = list(ent)
        else:
            keys = [k for k in ent if k[0] <= upto]
        vals = []
        for k in keys:
            v = ent.pop(k)
            vals.append(v)
            self._pending_bytes -= _ledger.value_nbytes(v)
            self._pending_entries -= 1
            # key is (round, sender) from _store_grad_locked; tolerate
            # a bare round key (tests inject entries directly)
            r = k[0] if isinstance(k, tuple) else int(k)
            n = self._round_entries.get(r, 0) - 1
            if n <= 0:
                self._round_entries.pop(r, None)
                self._round_seen.pop(r, None)
            else:
                self._round_entries[r] = n
        if not vals:
            return None
        if any(isinstance(v, SelectedRows) for v in vals):
            # mean of sparse grads = concatenated rows, values / N
            # (scatter-add makes concatenation a sum)
            return SelectedRows(
                np.concatenate([np.asarray(v.rows) for v in vals]),
                np.concatenate([np.asarray(v.values) for v in vals])
                / len(vals),
                vals[0].height)
        if len(vals) == 1:
            return np.asarray(vals[0])
        v0 = np.asarray(vals[0])
        # aggregate into an ALIGNED buffer (the optimize block stages
        # it zero-copy) with the minimum of full-buffer passes: one
        # allocating add + one in-place scale
        agg = _aligned_empty(v0.shape, v0.dtype)
        np.add(v0, vals[1], out=agg)
        for v in vals[2:]:
            agg += v
        agg *= 1.0 / len(vals)
        return agg

    def _apply_one(self, gname):
        """Aggregate + optimize one shard (lock held throughout — the
        async-mode arrival path)."""
        agg = self._aggregate_locked(gname)
        if agg is None:
            return
        self.scope.set(gname, agg)
        self._invalidate_locked(gname)
        # same per-shard fence as _apply_round: a concurrent prefetch
        # gathering from the zero-copy view must finish before this
        # apply donates the param buffer
        outs = self.grad_params.get(gname, ())
        while any(self._shard_readers.get(p) for p in outs) and \
                not self._shutdown.is_set():
            self._cv.wait(timeout=0.05)
        self.apply_block(self.grad_to_block[gname])
        self._invalidate_locked(gname)

    def _apply_round(self):
        """Apply every shard of the round (lock held on entry/exit).
        The lock is RELEASED around each shard's optimize block so
        sends/gets keep flowing while it computes, and each shard's
        params raise their per-shard completion event the moment its
        apply commits — streamed gathers return them while later
        shards (and the durability write) are still in flight."""
        nxt = self._applied_round + 1
        # correlate with the TRAINER round whose grads this apply
        # consumes (== the applied counter: trainer rounds are 0-based)
        consume = self._applied_round
        cid = _rcid(consume)
        sp = _TRC.begin("pserver.apply_round", cid,
                        {"senders": self._barrier_count()}) \
            if _TRC.on else None
        self._applying = True
        self._apply_target = nxt
        try:
            for g in self.grad_to_block:
                agg = self._aggregate_locked(g, upto=consume)
                if agg is not None:
                    self.scope.set(g, agg)
                    self._invalidate_locked(g)
                    # writer side of the per-shard fence: wait out any
                    # in-flight row gathers of this shard's params,
                    # then mark them applying for the donation window
                    outs = self.grad_params.get(g, ())
                    while any(self._shard_readers.get(p)
                              for p in outs) and \
                            not self._shutdown.is_set():
                        self._cv.wait(timeout=0.05)
                    self._shard_applying.update(outs)
                    self._cv.release()
                    # the PR 10 window: the shard's params are donated
                    # to the optimize dispatch with the lock dropped —
                    # under the weaver this is a scheduling decision
                    _san.weaver_yield("rpc.apply_window")
                    try:
                        if _TRC.on:
                            with _TRC.span("pserver.apply_shard", cid,
                                           {"grad": g}):
                                self.apply_block(self.grad_to_block[g])
                        else:
                            self.apply_block(self.grad_to_block[g])
                    finally:
                        self._cv.acquire()
                        self._shard_applying.difference_update(outs)
                    self._invalidate_locked(g)
                # shard committed (or had nothing to do — its params
                # already hold the round's values): publish per-shard
                # readiness so a streamed gather can ship it now
                for p in self.grad_params.get(g, ()):
                    self._param_ready[p] = nxt
                self._cv.notify_all()
        finally:
            self._applying = False
            if sp is not None:
                _TRC.end(sp)
        self._applied_round = nxt
        _M_PS_ROUNDS.inc()
        self._legacy_barriers = 0
        # applied_round moved: the quorum's membership predicate
        # changed for every sender — one O(senders) rebuild per ROUND
        # (vs per ack in the legacy rescan)
        self._quorum_recompute_locked()
        self._cv.notify_all()


class RPCClient:
    """Trainer side (reference grpc_client.h:168).  Process-wide singleton:
    send/recv ops share channels, the sync round counter, the (round,
    sender) replay cache, and the retry policy."""

    _instance = None

    def __init__(self):
        import socket as _socket
        import uuid

        self._channels = {}
        self._lock = _san.make_lock("rpc.client.channels")
        self.step = 0
        # per-process identity: the server dedups (round, sender) so
        # replaying a round after a reconnect cannot double-count
        self.sender = uuid.uuid4().int & _SENDER_MASK
        self._seq = 0   # per-send sequence: async-mode resend dedup
        self.label = "trainer%s@%s:%d" % (
            os.getenv("PADDLE_TRAINER_ID", "?"),
            _socket.gethostname(), os.getpid())
        # name this process's telemetry dumps (first writer wins: a
        # pserver process labeled itself at listen_and_serv already)
        _TRC.set_label(self.label)
        self.retry = RetryPolicy.from_env()
        self._resolver = None     # logical ep -> current physical ep
        self._redirects = {}      # logical ep -> physical ep overrides
        # ep -> {round: {"grads": {name: (arr, seq)}, "barriered"}}.
        # Rounds > step - (staleness+1) are retained for replay: at
        # k=0 that is exactly the current round (the PR 4 cache); with
        # k>0 the k un-acked rounds stay replayable too.
        self._round_cache = {}
        # seq + replay cache: the batched senders record from threads
        # (sanitizer-adopted lock, like every rpc/observability lock)
        self._cache_lock = _san.make_lock("rpc.client.cache")
        self._residuals = {}      # (ep, name) -> error-feedback residual
        self._wire_ver = {}       # ep -> negotiated wire version
        self._barrier_pending = None  # (threads, errs) of in-flight
        #                           overlapped barriers (launch/join)
        # replay-cache byte ledger (ISSUE 12): maintained under
        # _cache_lock wherever rounds/grads are recorded or pruned;
        # FLAGS_rpc_replay_cache_mb caps it (oldest non-current rounds
        # evict, metered).  Weakref-owned probe: test-created extra
        # clients fall out of the ledger when collected.
        self._replay_bytes = 0
        self._ledger_handle = _ledger.register(
            "rpc_client", RPCClient._ledger_probe, owner=self)

    def _ledger_probe(self):
        """Client-side resource ledger: replay-cache footprint and the
        error-feedback residual store (both can only be judged per
        process — the server never sees them)."""
        rounds = sum(len(eph) for eph in self._round_cache.values())
        res = sum(int(getattr(a, "nbytes", 0))
                  for a in self._residuals.values())
        return {"rpc_replay_cache_bytes": self._replay_bytes,
                "rpc_replay_cache_rounds": rounds,
                "rpc_residual_bytes": res}

    @classmethod
    def instance(cls):
        if cls._instance is None:
            cls._instance = RPCClient()
            # Watchtower (ISSUE 13): the trainer side of the data
            # plane also retains its history when FLAGS_tsdb_dir is
            # set (rpc bytes, trainer rounds, step walls)
            try:
                from paddle_tpu.observability import tsdb as _tsdb
                _tsdb.ensure_sampler()
            except Exception:
                pass
        return cls._instance

    @classmethod
    def reset(cls):
        cls._instance = None
        from . import hierarchy
        hierarchy.reset()

    def set_resolver(self, fn):
        """Install an endpoint re-resolver (resilience.EndpointResolver
        .resolve): consulted on reconnect so a pserver restarted on a
        new port is found through the discovery registry."""
        self._resolver = fn

    # -- transport ----------------------------------------------------
    def _phys(self, ep):
        return self._redirects.get(ep, ep)

    def _channel(self, phys):
        import grpc

        with self._lock:
            ch = self._channels.get(phys)
            if ch is None:
                ch = grpc.insecure_channel(phys, options=GRPC_OPTIONS)
                self._channels[phys] = ch
        return ch

    def _call(self, ep, method, payload, timeout=None):
        fn = self._channel(self._phys(ep)).unary_unary(
            "/%s/%s" % (SERVICE, method))
        return fn(payload, wait_for_ready=True, timeout=timeout)

    def _stub(self, ep, method):
        return self._channel(self._phys(ep)).unary_unary(
            "/%s/%s" % (SERVICE, method))

    def _reconnect(self, ep):
        """Drop the (possibly dead) channel and re-resolve the endpoint
        through discovery when a resolver is installed."""
        with self._lock:
            ch = self._channels.pop(self._phys(ep), None)
        if ch is not None:
            try:
                ch.close()
            except Exception:
                pass
        if self._resolver is not None:
            try:
                new = self._resolver(ep)
            except Exception:
                new = None
            if new and new != ep:
                self._redirects[ep] = new
            elif new == ep:
                self._redirects.pop(ep, None)

    # -- replay cache -------------------------------------------------
    def _next_seq(self):
        """Per-send sequence, 1..16383 wrapping (0 = 'no seq').  An
        async-mode server drops a resend whose (sender, name, seq)
        already applied; a replay reuses the ORIGINAL seq."""
        with self._cache_lock:
            self._seq = (self._seq % _SEQ_MASK) + 1
            return self._seq

    def _record_send(self, ep, name, arr):
        """Cache this round's send for replay; returns its seq.
        Thread-safe: the batched scatter records from per-endpoint
        sender threads.  Rounds older than the bounded-staleness
        replay window (step - staleness) are pruned here."""
        seq = self._next_seq()
        nb = _ledger.value_nbytes(arr)
        with self._cache_lock:
            eph = self._round_cache.setdefault(ep, {})
            c = eph.get(self.step)
            if c is None:
                c = eph[self.step] = {"grads": {}, "barriered": False,
                                      "bytes": 0}
                keep = self.step - max(0, int(FLAGS.dist_staleness))
                for r in [r for r in eph if r < keep]:
                    self._replay_bytes -= eph[r]["bytes"]
                    del eph[r]
            # latest value per name: a round resend replaces, never
            # appends
            old = c["grads"].get(name)
            if old is not None:
                onb = _ledger.value_nbytes(old[0])
                c["bytes"] -= onb
                self._replay_bytes -= onb
            c["grads"][name] = (arr, seq)
            c["bytes"] += nb
            self._replay_bytes += nb
            self._evict_replay_locked()
        return seq

    def _evict_replay_locked(self):
        """Enforce FLAGS_rpc_replay_cache_mb (cache lock held): evict
        whole retained ROUNDS, oldest first across endpoints, never the
        in-flight round (a retry of the current send must find its
        recorded frames).  An evicted round is unrecoverable on a
        server restart and walks forward as a cheap empty apply —
        exactly the fate of a round outside the staleness window
        (MIGRATION.md).  If the current round alone exceeds the cap,
        correctness wins over the cap."""
        cap = float(FLAGS.rpc_replay_cache_mb) * 1e6
        if cap <= 0:
            return
        while self._replay_bytes > cap:
            oldest_ep = oldest_r = None
            for ep, eph in self._round_cache.items():
                for r in eph:
                    if r >= self.step:
                        continue
                    if oldest_r is None or r < oldest_r:
                        oldest_ep, oldest_r = ep, r
            if oldest_r is None:
                return
            c = self._round_cache[oldest_ep].pop(oldest_r)
            self._replay_bytes -= c["bytes"]
            _M_REPLAY_EVICT.inc()

    def _recorded(self, ep, name, round_=None):
        """The cached (arr, seq) of this round's send of ``name`` to
        ``ep``, or None.  The cached value is post-codec, so a replay
        or retry ships bit-identical frames."""
        with self._cache_lock:
            c = self._round_cache.get(ep, {}).get(
                self.step if round_ is None else round_)
            return c["grads"].get(name) if c else None

    def _barrier_payload(self, round_):
        return _enc_msg(self.label, _pack_round_sender(round_, self.sender))

    def _replay_round(self, ep):
        """After a reconnect the server may have restarted and lost its
        un-applied state: resend every retained round's cached grads
        oldest-first (the server dedups by sender+seq, so this is
        idempotent) and, where this trainer already barriered a round,
        the barrier too.  At staleness 0 exactly one round is retained
        — the PR 4 behavior."""
        with self._cache_lock:
            eph = {r: {"grads": dict(c["grads"]),
                       "barriered": c["barriered"]}
                   for r, c in (self._round_cache.get(ep) or {}).items()}
        if not eph:
            return
        _M_REPLAYS.inc()
        to = self.retry.call_timeout
        for r in sorted(eph):
            c = eph[r]
            for name, (arr, seq) in c["grads"].items():
                self._call(
                    ep, "SendVariable",
                    _enc_tensor(name, arr,
                                _pack_round_sender(r, self.sender, seq)),
                    timeout=to)
            if c["barriered"]:
                self._call(ep, "SendBarrier", self._barrier_payload(r),
                           timeout=to)

    def _retry_op(self, ep, method, payload, point=None, replay=False,
                  decode=False):
        """One RPC under the retry policy: per-attempt timeout, capped
        backoff, reconnect (+ optional round replay) between attempts,
        DeadlineExceeded when the budget runs out."""
        def attempt():
            if point:
                fault_point(point)
            return self._call(ep, method, payload,
                              timeout=self.retry.call_timeout)

        def on_retry(exc, attempt_no):
            self._reconnect(ep)
            if replay:
                self._replay_round(ep)

        reply = self.retry.run(
            attempt, describe="%s(%s)" % (method, ep), on_retry=on_retry)
        return _dec_tensor(reply)[1] if decode else reply

    # -- compression (wire v2) ----------------------------------------
    def wire_version(self, ep):
        """Negotiated wire version of ``ep``, probed once (WireVersion
        RPC).  An old server has no such method — the UNIMPLEMENTED
        reply pins the endpoint to v1 (raw frames); a TRANSIENT failure
        is not cached, so the next round re-probes."""
        v = self._wire_ver.get(ep)
        if v is not None:
            return v
        try:
            reply = self._call(ep, "WireVersion", b"",
                               timeout=self.retry.call_timeout)
            _, v = _dec_msg(reply)
            v = int(v)
        except Exception as e:
            v = 1
            if RetryPolicy.is_retryable(e):
                return v          # transient: do not pin the endpoint
        self._wire_ver[ep] = v
        return v

    def _prep_send(self, ep, name, arr):
        """Host conversion + fault-lab corruption + the negotiated
        codec (FLAGS_dist_compress) with trainer-side error feedback.
        Called exactly once per (ep, name, round) — _prep_and_record
        guards re-entry via the replay cache, so residual updates never
        double-apply under retries."""
        arr = self._to_host(arr)
        arr = _maybe_corrupt("send_grad", self.step, arr)
        mode = FLAGS.dist_compress
        raw_nb = _czip.wire_nbytes(arr)
        _M_WIRE_RAW.inc(raw_nb)
        if not mode or self.wire_version(ep) < 2:
            _M_WIRE_COMP.inc(raw_nb)
            return arr
        t0 = time.perf_counter()
        from paddle_tpu.core.selected_rows import SelectedRows

        if not isinstance(arr, SelectedRows) and mode in ("int8", "topk") \
                and np.asarray(arr).dtype in (np.float32, np.float64) \
                and np.asarray(arr).size >= _czip.MIN_COMPRESS_ELEMS:
            # error feedback: fold the previous rounds' quantization
            # residual into this grad, then keep what THIS encode
            # dropped — the bias cancels across steps instead of
            # compounding (Lin et al., DGC)
            key = (ep, name)
            with self._cache_lock:
                res = self._residuals.get(key)
            base = np.asarray(arr)
            eff = base + res if res is not None \
                and res.shape == base.shape else base
            out = _czip.compress(eff, mode, FLAGS.dist_topk_ratio)
            if isinstance(out, Compressed):
                with self._cache_lock:
                    self._residuals[key] = np.asarray(
                        eff - _czip.decompress(out), np.float32)
        else:
            out = _czip.compress(arr, mode, FLAGS.dist_topk_ratio)
        _M_COMPRESS_MS.observe((time.perf_counter() - t0) * 1e3)
        _M_WIRE_COMP.inc(_czip.wire_nbytes(out))
        return out

    def _prep_and_record(self, ep, name, arr, reuse=False):
        """(wire-ready value, seq) for one outbound grad.  With
        ``reuse`` (the RETRY paths) a value already recorded for this
        round is returned verbatim — the resend ships the SAME
        post-codec bytes under the same seq, and error-feedback state
        advances exactly once per round.  A fresh send (reuse=False)
        always re-runs the codec and REPLACES the round-cache entry
        under a new seq — async mode re-sends the same grad name every
        step within one client round."""
        if reuse:
            rec = self._recorded(ep, name)
            if rec is not None:
                return rec
        out = self._prep_send(ep, name, arr)
        return out, self._record_send(ep, name, out)

    # -- data plane ---------------------------------------------------
    def send_var(self, ep, name, arr):
        arr, seq = self._prep_and_record(ep, name, arr)
        self._retry_op(
            ep, "SendVariable",
            _enc_tensor(name, arr, _pack_round_sender(self.step,
                                                      self.sender, seq)),
            point="send_grad", replay=True)

    def _fast_pool(self):
        pool = getattr(self, "_fastwire_pool", None)
        if pool is None and FASTWIRE_PORT_OFFSET > 0:
            from . import fastwire
            pool = fastwire.FastConnPool(FASTWIRE_PORT_OFFSET)
            self._fastwire_pool = pool
        return pool

    def _fast_call(self, ep, method, payload):
        """One fastwire round-trip, or None when the endpoint has no
        data plane (gRPC fallback).  A STALE pooled connection (failure
        before the payload went out) retries once on a fresh one; a
        failure after the payload was sent raises a retryable
        ConnectionError carrying ``sent_payload=True`` — reads replay it
        freely, while _overlapped(idempotent=False) excludes it from the
        resend and surfaces it to the caller (the frame may already be
        applied; belt over the wire protocol's (round, sender, seq)
        dedup suspenders)."""
        pool = self._fast_pool()
        if pool is None:
            return None
        for _ in range(2):
            conn = pool.checkout(self._phys(ep))
            if conn is None:
                return None
            try:
                reply = conn.call(method, payload)
                pool.checkin(self._phys(ep), conn)
                return reply
            except ConnectionError as e:
                pool.discard(conn)
                if getattr(e, "sent_payload", True):
                    raise
        return None

    def _overlapped(self, method, point, eps, payloads, replay,
                    use_fast=True, idempotent=True):
        """Shared fan-out: first attempt everything in flight together —
        fastwire threads where the endpoint offers a data plane, then
        gRPC futures — and push any retryable failure through the
        sequential retry path (reconnect + optional round replay).
        Fatal errors surface immediately.  Returns raw replies.

        ``idempotent=False`` (state-mutating sends): a fastwire failure
        AFTER the payload went out is excluded from the gRPC fallback —
        the server may have consumed and applied the frame, and a
        resend would double-apply — and re-raised after the join so the
        caller learns the send may have landed.  Reads keep the
        fallback: re-fetching is always safe."""
        n = len(eps)
        results = [None] * n
        pending = list(range(n))
        post_send = None
        pool = self._fast_pool() if use_fast else None
        if pool is not None:
            errs = {}  # thread index -> captured exception

            def one(i):
                try:
                    fault_point(point)
                    results[i] = self._fast_call(eps[i], method,
                                                 payloads[i])
                except Exception as e:
                    # captured, classified AFTER join: a post-send
                    # failure of a non-idempotent send must not
                    # silently become a gRPC resend
                    errs[i] = e
                    results[i] = None

            ts = [threading.Thread(target=one, args=(i,))
                  for i in pending]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            excluded = set()
            fatal = None
            for i, e in sorted(errs.items()):
                if not RetryPolicy.is_retryable(e):
                    fatal = fatal or e
                elif not idempotent and getattr(e, "sent_payload",
                                                False):
                    # the server may have consumed and APPLIED the
                    # frame: resending over gRPC would double-apply
                    # (e.g. a SendVariable gradient skewing the sync
                    # average) — exclude from the fallback; re-raised
                    # AFTER the other endpoints' safe fallbacks finish
                    # so the round is as complete as it can be
                    excluded.add(i)
                    post_send = post_send or e
            if fatal is not None:
                # chain the maybe-applied send so recovery logic sees
                # both the fatal failure and the uncertain delivery
                raise fatal from post_send
            pending = [i for i in pending
                       if results[i] is None and i not in excluded]
        futs, need_retry = [], []
        for i in pending:
            try:
                fault_point(point)
                futs.append((i, self._stub(eps[i], method)
                             .future(payloads[i], wait_for_ready=True,
                                     timeout=self.retry.call_timeout)))
            except InjectedFault as e:
                if not e.retryable:
                    raise
                need_retry.append(i)
        for i, f in futs:
            try:
                results[i] = f.result()
            except Exception as e:
                if not RetryPolicy.is_retryable(e):
                    raise
                need_retry.append(i)
        for i in need_retry:
            results[i] = self._retry_op(eps[i], method, payloads[i],
                                        point=point, replay=replay)
        if post_send is not None:
            # surfaced only after every safe item completed its
            # fallback: the caller learns this send may have landed
            raise post_send
        return results

    @staticmethod
    def _to_host(arr):
        """Materialize a (possibly device-resident) value as numpy —
        called INSIDE the per-endpoint sender threads, so the d2h
        conversion of shard k+1 overlaps the in-flight wire send of
        shard k instead of sitting on the round's critical path."""
        from paddle_tpu.core.selected_rows import SelectedRows

        if isinstance(arr, SelectedRows):
            return SelectedRows(np.asarray(arr.rows),
                                np.asarray(arr.values), arr.height)
        return np.asarray(arr)

    def send_vars(self, triples):
        """Batched overlapped sends: [(ep, name, arr)].  All of a
        trainer's shards for one endpoint travel as ONE fastwire
        scatter frame (vectored send, no Python-level join), endpoints
        in flight together; each sub-frame carries its own (round,
        sender, seq) identity so replay dedup is unchanged.  Values may
        still be device arrays — conversion happens in the sender
        threads.  FLAGS_pserver_wire_batch=0 restores the per-variable
        wire.

        With FLAGS_dist_hier_local set (hierarchical aggregation),
        grads detour through the host-local group leader: followers
        ship them over the loopback channel, the leader stashes its own
        in-process — the pserver upload happens once per group, at
        barrier time (``_hier_upload``)."""
        from . import hierarchy
        if hierarchy.enabled():
            if hierarchy.role().leader:
                return hierarchy.leader_stash(self, triples)
            return hierarchy.follower_send(self, triples)
        return self._send_vars_wire(triples)

    def _send_vars_wire(self, triples):
        """The pserver-facing send fan-out (post any hierarchy detour)."""
        if not _TRC.on:
            return self._send_vars_impl(triples)
        sp = _TRC.begin("rpc.send_vars", _rcid(self.step),
                        {"n": len(triples),
                         "sender": "%06x" % self.sender})
        try:
            return self._send_vars_impl(triples)
        finally:
            _TRC.end(sp)

    def _send_vars_impl(self, triples):
        if not FLAGS.pserver_wire_batch:
            return self._send_vars_unbatched(triples)
        by_ep = {}
        for ep, name, arr in triples:
            by_ep.setdefault(ep, []).append((name, arr))
        errs = {}

        def one(ep, items):
            fault_point("send_grad")
            frames = []
            for name, arr in items:
                # _prep_and_record: host convert + corrupt-lab poison +
                # negotiated codec, all BEFORE the replay cache records
                # the value — replays of the round stay bit-identical
                arr, seq = self._prep_and_record(ep, name, arr)
                frames.append(_enc_tensor_parts(
                    name, arr,
                    _pack_round_sender(self.step, self.sender, seq)))
            self._send_batch(ep, frames)

        def wrapped(ep, items):
            try:
                one(ep, items)
            except Exception as e:
                errs[ep] = e

        eps = list(by_ep)
        if len(eps) == 1:
            wrapped(eps[0], by_ep[eps[0]])
        else:
            ts = [threading.Thread(target=wrapped, args=(ep, by_ep[ep]))
                  for ep in eps]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        # same classification as _overlapped(idempotent=False): fatal
        # first; a fastwire failure AFTER the payload went out must not
        # become a resend (the server may have applied the frame) and
        # re-raises after the safe endpoints finished their fallbacks
        post_send = None
        fatal = None
        retry = []
        for ep, e in sorted(errs.items()):
            if not RetryPolicy.is_retryable(e):
                fatal = fatal or e
            elif getattr(e, "sent_payload", False):
                post_send = post_send or e
            else:
                retry.append(ep)
        if fatal is not None:
            raise fatal from post_send
        for ep in retry:
            # resend THIS CALL's items — the round cache also holds
            # earlier send ops' grads (replayed separately via
            # replay=True), so filtering by it would silently drop any
            # tensor the failure preempted before recording.  Tensors
            # that WERE recorded reuse their original (arr, seq), so a
            # duplicate delivery stays dedup-able.
            frames = []
            for name, arr in by_ep[ep]:
                # tensors that WERE recorded reuse their original
                # (post-codec arr, seq) so a duplicate delivery stays
                # dedup-able; unrecorded ones run the codec now
                arr, seq = self._prep_and_record(ep, name, arr,
                                                 reuse=True)
                frames.append(_enc_tensor_parts(
                    name, arr,
                    _pack_round_sender(self.step, self.sender, seq)))
            self._retry_op(ep, "SendVariables",
                           _join_parts(_enc_batch_parts(frames)),
                           point="send_grad", replay=True)
        if post_send is not None:
            raise post_send

    def _send_vars_unbatched(self, triples):
        """The per-variable wire (pre-batching behavior; reference
        grpc_client AsyncSendVar + Wait) — kept for parity testing via
        FLAGS_pserver_wire_batch=0."""
        payloads = []
        for ep, name, arr in triples:
            arr, seq = self._prep_and_record(ep, name, arr)
            payloads.append(_enc_tensor(
                name, arr,
                _pack_round_sender(self.step, self.sender, seq)))
        self._overlapped("SendVariable", "send_grad",
                         [t[0] for t in triples], payloads, replay=True,
                         idempotent=False)
        # delivered-bytes accounting (after the fan-out returns, like
        # the batched path)
        _M_BYTES_TX.inc(sum(len(p) for p in payloads))

    def _send_batch(self, ep, frames):
        """One endpoint's batched scatter: fastwire vectored send of
        the parts (payloads shipped by buffer address), gRPC batched
        message when the endpoint offers no data plane."""
        pool = self._fast_pool()
        if pool is not None:
            parts = _enc_batch_parts(frames)
            for _ in range(2):
                conn = pool.checkout(self._phys(ep))
                if conn is None:
                    break
                try:
                    conn.call("SendVariables", parts)
                    # count DELIVERED payload bytes only, after the call
                    # returns: counting up front would double-count a
                    # round that falls back to gRPC (and count bytes
                    # that never went out at all)
                    _M_BYTES_TX.inc(_parts_nbytes(parts))
                    pool.checkin(self._phys(ep), conn)
                    return
                except ConnectionError as e:
                    pool.discard(conn)
                    if getattr(e, "sent_payload", True):
                        raise
        payload = _join_parts(_enc_batch_parts(frames))
        self._call(ep, "SendVariables", payload,
                   timeout=self.retry.call_timeout)
        _M_BYTES_TX.inc(len(payload))

    def get_var(self, ep, name, round_=None):
        round_ = self.step if round_ is None else round_
        arr = self._retry_op(ep, "GetVariable", _enc_msg(name, round_),
                             point="get_param", replay=True, decode=True)
        _M_BYTES_RX.inc(getattr(arr, "nbytes", 0) or 0)
        return arr

    def get_vars(self, pairs, round_=None, sinks=None):
        """Overlapped gets: [(ep, name)] -> [arr] (reference
        AsyncGetVar + Wait).  Batched per endpoint: one streamed
        fastwire gather per ep, frames consumed AS THE SERVER COMMITS
        each shard's apply.  ``sinks[i]``, when given, is called in the
        receiving thread with the decoded array and its return value
        replaces it in the result — the recv op uses this to copy
        slices straight into the preassembled param (no concat pass)
        while later shards are still on the wire.
        FLAGS_pserver_wire_batch=0 restores per-variable gets."""
        round_ = self.step if round_ is None else round_
        if not _TRC.on:
            return self._get_vars_impl(pairs, round_, sinks)
        # get(round=N) consumes the apply of trainer round N-1: tag the
        # span with THAT round's correlation id
        sp = _TRC.begin("rpc.get_vars", _rcid(max(round_ - 1, 0)),
                        {"n": len(pairs), "wait_round": round_})
        try:
            return self._get_vars_impl(pairs, round_, sinks)
        finally:
            _TRC.end(sp)

    def _get_vars_impl(self, pairs, round_, sinks):
        if not FLAGS.pserver_wire_batch:
            replies = self._overlapped(
                "GetVariable", "get_param", [ep for ep, _ in pairs],
                [_enc_msg(name, round_) for _, name in pairs],
                replay=True)
            out = [_dec_tensor(r)[1] for r in replies]
            for a in out:
                _M_BYTES_RX.inc(getattr(a, "nbytes", 0) or 0)
            if sinks is not None:
                out = [s(a) if s is not None else a
                       for s, a in zip(sinks, out)]
            return out
        results = [None] * len(pairs)
        filled = [False] * len(pairs)
        by_ep = {}
        for i, (ep, name) in enumerate(pairs):
            by_ep.setdefault(ep, []).append((i, name))
        errs = {}

        def consume(i, arr):
            _M_BYTES_RX.inc(getattr(arr, "nbytes", 0) or 0)
            sink = sinks[i] if sinks is not None else None
            results[i] = sink(arr) if sink is not None else arr
            filled[i] = True

        def one(ep, items):
            fault_point("get_param")
            idx_of = {name: i for i, name in items}

            def on_frame(view):
                name, arr, _ = _dec_tensor(view)
                consume(idx_of[name], arr)

            if not self._get_batch_fast(ep, [(n, round_) for _, n in
                                             items], on_frame):
                # no data plane: one batched gRPC gather
                payload = _join_parts(_enc_batch_parts(
                    [[_enc_msg(n, round_)] for _, n in items]))
                reply = self._call(ep, "GetVariables", payload,
                                   timeout=self.retry.call_timeout)
                view = memoryview(reply)
                off = 0
                for _ in items:
                    ln = int.from_bytes(view[off:off + 8], "little")
                    off += 8
                    name, arr, _ = _dec_tensor(view[off:off + ln])
                    off += ln
                    consume(idx_of[name], arr)

        def wrapped(ep, items):
            try:
                one(ep, items)
            except Exception as e:
                errs[ep] = e

        eps = list(by_ep)
        if len(eps) == 1:
            wrapped(eps[0], by_ep[eps[0]])
        else:
            ts = [threading.Thread(target=wrapped, args=(ep, by_ep[ep]))
                  for ep in eps]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        for ep, e in sorted(errs.items()):
            if not RetryPolicy.is_retryable(e):
                raise e
        # gets are idempotent: re-fetch whatever is missing through the
        # sequential retry path (reconnect + round replay)
        for i, (ep, name) in enumerate(pairs):
            if not filled[i]:
                arr = self._retry_op(ep, "GetVariable",
                                     _enc_msg(name, round_),
                                     point="get_param", replay=True,
                                     decode=True)
                consume(i, arr)
        return results

    def _get_batch_fast(self, ep, items, on_frame):
        """Streamed batched gather over fastwire; False -> caller uses
        gRPC.  A failure after the request went out simply leaves
        frames unfilled — the caller re-fetches those (reads are always
        safe to retry)."""
        pool = self._fast_pool()
        if pool is None:
            return False
        parts = _enc_batch_parts([[_enc_msg(n, r)] for n, r in items])
        for attempt in range(2):
            conn = pool.checkout(self._phys(ep))
            if conn is None:
                return False
            try:
                conn.call_stream("GetVariables", parts, len(items),
                                 on_frame)
                pool.checkin(self._phys(ep), conn)
                return True
            except ConnectionError as e:
                pool.discard(conn)
                if getattr(e, "sent_payload", True):
                    raise
        return False

    def prefetch_vars(self, triples, round_=None):
        """Overlapped row prefetches: [(ep, block_name, local_ids)] ->
        [rows] (reference AsyncPrefetchVar + Wait).  Rides the fastwire
        data plane (a CTR-shaped step prefetches tens of MB of
        embedding rows); reads are idempotent, so the gRPC fallback
        re-fetch is always safe."""
        round_ = self.step if round_ is None else round_
        replies = self._overlapped(
            "PrefetchVariable", "prefetch", [t[0] for t in triples],
            [_enc_tensor(name, np.asarray(ids, np.int64), round_)
             for _, name, ids in triples],
            replay=False)
        return [_dec_tensor(r)[1] for r in replies]

    def _hier_round_start(self):
        """Hierarchical-aggregation hook at the trainer's barrier.
        Returns True when this client handled the round locally (a
        FOLLOWER: barrier signaled to the group leader, local round
        advanced — no pserver barrier).  A LEADER flushes the group's
        pre-reduced grads to the pservers here (ONE upload per group,
        through the normal compressed/recorded send path) and then
        falls through to the real barrier."""
        from . import hierarchy
        if not hierarchy.enabled():
            return False
        if not hierarchy.role().leader:
            hierarchy.follower_barrier(self)
            self.step += 1
            _M_TRAINER_ROUNDS.inc()
            return True
        triples = hierarchy.leader_flush(self)
        if triples:
            self._send_vars_wire(triples)
        return False

    def send_barrier(self, eps):
        """Barrier every pserver CONCURRENTLY: the server-side barrier
        now blocks until the round is applied (and durably checkpointed
        on checkpoint rounds), so sequential calls across endpoints
        could deadlock if trainers ordered them differently."""
        if self._hier_round_start():
            return
        payload = self._barrier_payload(self.step)
        round_ = self.step
        errs = []

        def one(ep):
            try:
                sp = _TRC.begin("rpc.barrier", _rcid(round_),
                                {"ep": ep}) if _TRC.on else None
                try:
                    self._retry_op(ep, "SendBarrier", payload,
                                   point="send_barrier", replay=True)
                finally:
                    if sp is not None:
                        _TRC.end(sp)
                with self._cache_lock:
                    c = self._round_cache.get(ep, {}).get(round_)
                    if c is not None:
                        c["barriered"] = True
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=one, args=(ep,)) for ep in eps]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise errs[0]
        self.step += 1
        _M_TRAINER_ROUNDS.inc()

    def launch_barriers(self, eps):
        """Full-duplex round: START the SendBarrier RPCs in background
        threads and advance the local round counter immediately.  The
        param gets that follow (round step+1) then run concurrently
        with the in-flight barriers — the server streams each shard as
        its apply commits while the acks still wait on round
        durability.  ``join_barriers`` (the trainer's fetch_barrier)
        collects acks/errors before the next round's sends, preserving
        the ack-implies-durable contract at the round boundary."""
        self.join_barriers()   # defensive: never two rounds in flight
        if self._hier_round_start():
            return
        payload = self._barrier_payload(self.step)
        round_ = self.step
        errs = []

        def one(ep):
            try:
                sp = _TRC.begin("rpc.barrier", _rcid(round_),
                                {"ep": ep, "overlapped": True}) \
                    if _TRC.on else None
                try:
                    self._retry_op(ep, "SendBarrier", payload,
                                   point="send_barrier", replay=True)
                finally:
                    if sp is not None:
                        _TRC.end(sp)
                with self._cache_lock:
                    c = self._round_cache.get(ep, {}).get(round_)
                    if c is not None:
                        c["barriered"] = True
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=one, args=(ep,), daemon=True)
              for ep in eps]
        for t in ts:
            t.start()
        self._barrier_pending = (ts, errs)
        self.step += 1
        _M_TRAINER_ROUNDS.inc()

    def join_barriers(self):
        """Join the overlapped barriers launched by launch_barriers,
        surfacing the first failure.  No-op when nothing is pending."""
        pending = self._barrier_pending
        if pending is None:
            return
        ts, errs = pending
        for t in ts:
            t.join()
        self._barrier_pending = None
        if errs:
            raise errs[0]

    def fetch_barrier(self, eps):
        for ep in eps:
            self._retry_op(ep, "FetchBarrier", b"", point="fetch_barrier")

    def barrier_status(self, ep, timeout=5.0):
        """The server's sync-barrier introspection (watchdog support)."""
        import json

        return json.loads(
            self._call(ep, "BarrierStatus", b"", timeout=timeout).decode())

    def toggle_profile(self, eps, on, profile_path=""):
        """Switch profiling on every pserver from the trainer side
        (reference VariableMessage.profile envelope bit)."""
        for ep in eps:
            self._call(ep, "ToggleProfile",
                       _enc_msg(profile_path, 1 if on else 0))

    def send_complete(self, eps):
        # hierarchical mode: followers complete to their group leader
        # (the pserver's fanin counts GROUPS); the leader waits for its
        # followers so the single group completion is really last
        from . import hierarchy
        if hierarchy.enabled():
            if not hierarchy.role().leader:
                # followers NEVER complete to the pservers — Fanin
                # counts groups, and a follower's SendComplete would
                # decrement the server's fanin under the still-running
                # leader.  Best-effort like the sends below.
                try:
                    hierarchy.follower_complete(self)
                except Exception:
                    pass
                return
            try:
                hierarchy.leader_wait_complete(self)
            except Exception:
                pass   # completion is best-effort, like the sends below
        # identity payload: the server must not double-decrement its
        # fanin for a trainer the lease already expired, and must drop
        # a duplicate complete from the same process
        payload = _enc_msg(self.label,
                           _pack_round_sender(self.step, self.sender))
        for ep in eps:
            try:
                self._call(ep, "SendComplete", payload, timeout=10.0)
            except Exception:
                pass  # server may already be down
