"""Trainer<->pserver RPC over gRPC generic handlers.

Parity: reference operators/detail/send_recv.proto:19-28 (SendRecvService:
SendVariable / GetVariable / PrefetchVariable), grpc_client.h:168,
grpc_server.cc, and the sync/async serve loops of
operators/listen_and_serv_op.cc:99,166.

Implementation notes (TPU-host path):
- gRPC *generic* method handlers with a numpy-native wire format — no
  protoc codegen; tensors travel as a raw dtype|shape|bytes frame
  (memcpy-speed encode, zero-copy decode — see _enc_arr).
- The sync protocol is barrier-counted like the reference: trainers send
  every grad, then SendBarrier; once ``fanin`` barriers arrive the server
  aggregates (mean over trainers), runs the per-param optimize blocks, and
  bumps ``applied_round``; GetVariable(round) blocks until
  ``applied_round >= round``.  SendComplete decrements fanin (reference
  framework/executor.cc:50 SendComplete) and stops the server at zero.

Failure-path design (distributed/resilience.py is the policy home):
- Every SendVariable/SendBarrier carries a (round, sender) identity
  packed into the message's extra field, so the server DEDUPS by sender:
  replaying a round after a reconnect is idempotent, which is what makes
  client-side retry safe for non-idempotent gradient traffic.
- SendBarrier ACKS ONLY AFTER the round is applied — and, on checkpoint
  rounds, durably snapshotted — so a SIGKILL at any point either loses
  an un-acked round (every trainer still holds it in its replay cache
  and resends) or nothing (the round is already on disk).
- The client keeps a per-endpoint replay cache of the current round's
  grads; any retryable failure reconnects (re-resolving the endpoint via
  discovery when a resolver is installed) and replays the round first.
- A server-side trainer lease (reference go/master/service.go:368
  checkTimeout) expires a trainer that dies mid-round: the sync fanin
  decrements and the surviving trainers' round completes.
"""
from __future__ import annotations

import os
import threading
import time
from concurrent import futures

import numpy as np

from .resilience import FLAGS, InjectedFault, RetryPolicy, fault_point

SERVICE = "paddle_tpu.PServer"

# fastwire data plane: raw-socket port = grpc port + this offset
# (0 disables).  Handshake magic keeps a mis-aimed connection safe.
FASTWIRE_PORT_OFFSET = int(os.environ.get("FLAGS_fastwire_port_offset",
                                          "2000"))

# gRPC defaults cap messages at 4 MB; one fc shard of a real model is
# routinely 10-100 MB (the reference moved such blocks over raw sockets,
# ParameterServer2.h).  Unlimited on both directions.
GRPC_OPTIONS = [("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1)]


def _enc_arr(parts, arr):
    """Append one array as dtype | ndim | shape | raw bytes.  Raw
    tobytes instead of np.save: the npy framing costs a full extra
    buffer pass (~650 MB/s measured vs memcpy), and a 100 MB dense
    round serializes ~400 MB — the hot path the reference served with
    zero-copy sockets (ParameterServer2.h)."""
    # NOT np.ascontiguousarray unconditionally: it promotes 0-d to 1-d
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        # fail at the SENDER: tobytes() on an object array would ship
        # heap pointers and only blow up at the remote decoder
        raise TypeError("cannot send object-dtype array over the "
                        "pserver wire (got dtype=%s)" % arr.dtype)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")
    parts.append(len(dt).to_bytes(2, "little"))
    parts.append(dt)
    parts.append(arr.ndim.to_bytes(1, "little"))
    for d in arr.shape:
        parts.append(int(d).to_bytes(8, "little"))
    # memoryview, not tobytes(): join copies it once — tobytes would
    # make that two full passes over a 100 MB payload
    parts.append(arr.data)


def _dec_arr(view, off):
    """Zero-copy array decode from a memoryview.  The result is a
    READ-ONLY view over the message buffer — every in-repo consumer is
    functional (aggregation, optimize blocks, device_put all produce
    fresh arrays); a caller that wants to mutate must .copy()."""
    n = int.from_bytes(view[off:off + 2], "little")
    off += 2
    dtype = np.dtype(view[off:off + n].tobytes().decode("ascii"))
    off += n
    ndim = view[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(view[off:off + 8], "little"))
        off += 8
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(view[off:off + nbytes],
                        dtype=dtype).reshape(shape)
    return arr, off + nbytes


def _enc_tensor(name, arr, extra=0):
    """Wire format: name | extra | kind (0 dense, 1 SelectedRows) | arrays.
    SelectedRows travel as (rows, values, height) — reference
    VariableMessage's SELECTED_ROWS type (send_recv.proto:48)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    nb = name.encode("utf-8")
    parts = [len(nb).to_bytes(4, "little"), nb,
             int(extra).to_bytes(8, "little", signed=True)]
    if isinstance(arr, SelectedRows):
        parts.append(b"\x01")
        parts.append(int(arr.height).to_bytes(8, "little"))
        _enc_arr(parts, np.asarray(arr.rows))
        _enc_arr(parts, np.asarray(arr.values))
    else:
        parts.append(b"\x00")
        _enc_arr(parts, np.asarray(arr))
    return b"".join(parts)


def _dec_tensor(data):
    from paddle_tpu.core.selected_rows import SelectedRows

    view = memoryview(data)
    n = int.from_bytes(view[:4], "little")
    name = view[4:4 + n].tobytes().decode("utf-8")
    off = 4 + n
    extra = int.from_bytes(view[off:off + 8], "little", signed=True)
    off += 8
    kind = view[off]
    off += 1
    if kind == 1:
        height = int.from_bytes(view[off:off + 8], "little")
        off += 8
        rows, off = _dec_arr(view, off)
        values, off = _dec_arr(view, off)
        return name, SelectedRows(rows, values, height), extra
    arr, off = _dec_arr(view, off)
    return name, arr, extra


def _enc_msg(name, extra=0):
    nb = name.encode("utf-8")
    return (len(nb).to_bytes(4, "little") + nb
            + int(extra).to_bytes(8, "little", signed=True))


def _dec_msg(data):
    n = int.from_bytes(data[:4], "little")
    name = bytes(data[4:4 + n]).decode("utf-8")
    extra = int.from_bytes(data[4 + n:12 + n], "little", signed=True)
    return name, extra


# -- (round, sender, seq) identity packed into the 8-byte extra field -----
# Bit 62 flags the packed form so a legacy plain-round extra (always a
# small non-negative step count) decodes as an anonymous send; then 14
# bits of per-sender send sequence (async dedup), 24 bits of round, and
# 24 bits of per-process sender token.
_WIRE_SENDER_FLAG = 1 << 62
_SEQ_MASK = (1 << 14) - 1
_ROUND_MASK = (1 << 24) - 1
_SENDER_MASK = (1 << 24) - 1


def _pack_round_sender(round_, sender, seq=0):
    return (_WIRE_SENDER_FLAG | ((int(seq) & _SEQ_MASK) << 48)
            | ((int(round_) & _ROUND_MASK) << 24)
            | (int(sender) & _SENDER_MASK))


def _unpack_round_sender(extra):
    """-> (round, sender, seq) — sender is None (and seq 0) for
    legacy/anonymous extras."""
    if extra > 0 and (extra & _WIRE_SENDER_FLAG):
        return ((extra >> 24) & _ROUND_MASK, extra & _SENDER_MASK,
                (extra >> 48) & _SEQ_MASK)
    return extra, None, 0


class VariableServer:
    """Parameter-server side: owns the scope, applies optimize blocks.

    ``grad_to_block``: grad(-block) var name -> pserver sub-block index.
    ``apply_block``: callable(block_idx) running one optimize sub-block
    against the server scope (wired to the executor by listen_and_serv).
    ``trainer_lease``: seconds of mid-round silence after which a known
    trainer is expired from the sync fanin (0 disables; reference
    go/master/service.go:368 checkTimeout).
    """

    def __init__(self, scope, grad_to_block, apply_block, fanin,
                 sync_mode=True, checkpoint_dir=None,
                 checkpoint_every_n=0, trainer_lease=None):
        import grpc

        self.scope = scope
        self.grad_to_block = dict(grad_to_block)
        self.apply_block = apply_block
        self.fanin_total = int(fanin)
        self.sync_mode = bool(sync_mode)
        # shard checkpointing (reference go/pserver/service.go:346:
        # each pserver persists ITS parameter shard so a restarted
        # server resumes instead of reinitializing)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_n = int(checkpoint_every_n or 0)
        self.trainer_lease = float(
            FLAGS.trainer_lease if trainer_lease is None else trainer_lease)

        self._cv = threading.Condition()
        # grad name -> {sender key: array}; sender-keyed so a replayed
        # round overwrites instead of double-counting in the sync mean
        self._pending = {g: {} for g in self.grad_to_block}
        self._applied_round = 0
        self._barrier_senders = set()   # senders barriered this round
        self._barrier_round = -1        # highest round those barriers name
        self._legacy_barriers = 0       # anonymous (empty-payload) barriers
        self._anon_seq = 0
        self._senders = {}              # sender -> {"label", "last_seen"}
        self._expired = set()           # senders removed by lease expiry
        self._completed = set()         # senders that sent SendComplete
        self._async_applied = {}        # (sender, name) -> last applied seq
        self._alive = self.fanin_total
        self._shutdown = threading.Event()
        self._ckpt_lock = threading.Lock()  # one save at a time
        if checkpoint_dir:
            # restore AFTER the round counter exists: load_shard also
            # recovers _applied_round from _SUCCESS, or trainers
            # blocked in GetVariable(round=N) would wait forever on a
            # restarted server stuck at round 0
            for cand in (checkpoint_dir, checkpoint_dir + ".old"):
                if os.path.isdir(cand) and os.path.exists(
                        os.path.join(cand, "_SUCCESS")):
                    self.load_shard(cand)
                    break
        # rounds that are visible AND safe against a crash: equal to
        # _applied_round except inside a checkpoint-write window
        self._durable_round = self._applied_round

        handlers = {
            "SendVariable": self._h(self._send_variable),
            "GetVariable": self._h(self._get_variable),
            "PrefetchVariable": self._h(self._prefetch_variable),
            "SendBarrier": self._h(self._send_barrier),
            "FetchBarrier": self._h(self._fetch_barrier),
            "BarrierStatus": self._h(self._barrier_status),
            "ToggleProfile": self._h(self._toggle_profile),
            "SendComplete": self._h(self._send_complete),
        }
        # enough workers that fanin-1 blocked GetVariable waiters (plus
        # retried barrier handlers that linger until their client's
        # cancellation is noticed) can never starve the SendBarrier that
        # would wake them
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max(32, 8 * self.fanin_total + 8)),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(SERVICE, handlers),))

    @staticmethod
    def _h(fn):
        import grpc

        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(req, ctx))

    # -- lifecycle --
    def start(self, endpoint):
        """Bind + start; returns the bound port.  Also opens the
        fastwire raw-socket DATA plane at port+FASTWIRE_PORT_OFFSET
        (reference pserver/LightNetwork.cpp role): SendVariable /
        GetVariable bulk frames bypass Python gRPC; control RPCs
        (barriers, completion, profile) stay here.  Best-effort: no
        native toolchain or a taken port just means gRPC carries
        everything, as before."""
        port = self._server.add_insecure_port(endpoint)
        self._server.start()
        self._fast = None
        if FASTWIRE_PORT_OFFSET > 0:
            try:
                from . import fastwire
                self._fast = fastwire.FastServer(
                    port + FASTWIRE_PORT_OFFSET,
                    {"SendVariable": self._send_variable,
                     "GetVariable": self._get_variable})
            except Exception:
                self._fast = None
        if self.sync_mode and self.trainer_lease > 0:
            threading.Thread(target=self._lease_loop, daemon=True).start()
        return port

    def wait(self):
        """Block until every trainer sent SendComplete."""
        self._shutdown.wait()
        if getattr(self, "_fast", None) is not None:
            self._fast.stop()
        self._server.stop(grace=1).wait()

    # -- condition helpers --
    def _wait_cv(self, pred, ctx):
        """Wait (lock held) until ``pred`` or shutdown; polls so a
        handler whose client cancelled/died exits instead of pinning a
        pool thread forever.  Returns False when the client vanished."""
        while not pred() and not self._shutdown.is_set():
            if ctx is not None and not ctx.is_active():
                return False
            self._cv.wait(timeout=0.25)
        return True

    def _touch(self, sender, label=None):
        """Record contact from ``sender`` (lock held).  An expired
        trainer that turns out to be alive rejoins the fanin."""
        ent = self._senders.get(sender)
        if ent is None:
            ent = {"label": "sender-%06x" % sender, "last_seen": 0.0}
            self._senders[sender] = ent
        if label:
            ent["label"] = label
        ent["last_seen"] = time.time()
        if sender in self._expired:
            self._expired.discard(sender)
            self._alive = min(self._alive + 1, self.fanin_total)

    def _barrier_count(self):
        return len(self._barrier_senders) + self._legacy_barriers

    def _maybe_apply_locked(self):
        """Apply the round if every live trainer barriered (lock held).
        Returns a state snapshot the CALLER must persist (outside the
        lock) before bumping _durable_round, or None."""
        if not (0 < self._alive <= self._barrier_count()):
            return None
        self._apply_round()
        if (self.checkpoint_every_n and self.checkpoint_dir and
                self._applied_round % self.checkpoint_every_n == 0):
            # collect under the lock, WRITE outside it — disk I/O must
            # not stall every other RPC handler
            return self._collect_state()
        self._durable_round = self._applied_round
        return None

    def _persist_and_ack(self, snapshot):
        """Write the snapshot, then publish durability (barrier acks for
        this round are blocked until _durable_round catches up)."""
        if snapshot is None:
            return
        self.save_shard(self.checkpoint_dir, snapshot)
        with self._cv:
            self._durable_round = self._applied_round
            self._cv.notify_all()

    def _lease_loop(self):
        """Expire trainers that die mid-round: when barriers are stalled
        and a KNOWN sender that has not barriered this round has been
        silent past the lease, drop it from the fanin and complete the
        round with the survivors (mirrors Master._check_timeouts)."""
        interval = max(0.05, self.trainer_lease / 3.0)
        while not self._shutdown.wait(interval):
            snapshot = None
            with self._cv:
                if self._barrier_count() == 0:
                    continue    # nobody is waiting on a round
                now = time.time()
                for sender, ent in list(self._senders.items()):
                    if sender in self._barrier_senders or \
                            sender in self._expired or \
                            sender in self._completed:
                        continue   # contributed, gone, or cleanly done
                    if now - ent["last_seen"] > self.trainer_lease:
                        self._expired.add(sender)
                        self._alive -= 1
                snapshot = self._maybe_apply_locked()
            self._persist_and_ack(snapshot)

    # -- handlers --
    def _send_variable(self, req, ctx=None):
        name, arr, extra = _dec_tensor(req)
        round_, sender, seq = _unpack_round_sender(extra)
        with self._cv:
            if sender is not None:
                self._touch(sender)
            if name not in self._pending:
                # direct write (e.g. init push or non-optimized var)
                self.scope.set(name, arr)
                return b""
            if sender is None:
                key = ("anon", self._anon_seq)
                self._anon_seq += 1
            else:
                if self.sync_mode and round_ < self._applied_round:
                    return b""   # stale replay of an applied round
                if not self.sync_mode and seq and \
                        self._async_applied.get((sender, name)) == seq:
                    # async applies on arrival and clears pending, so
                    # the round-replay dedup can't help a retried send:
                    # the per-sender send sequence is what makes a
                    # resend of an already-applied grad a no-op
                    return b""
                key = sender
            self._pending[name][key] = arr
            if not self.sync_mode:
                self._apply_one(name)
                if sender is not None and seq:
                    self._async_applied[(sender, name)] = seq
                self._cv.notify_all()
        return b""

    def _send_barrier(self, req, ctx=None):
        snapshot = None
        with self._cv:
            if req:
                label, extra = _dec_msg(req)
                round_, sender, _ = _unpack_round_sender(extra)
            else:
                label, round_, sender = None, None, None
            if sender is not None:
                self._touch(sender, label)
                if round_ >= self._applied_round:
                    self._barrier_senders.add(sender)
                    self._barrier_round = max(self._barrier_round, round_)
                    snapshot = self._maybe_apply_locked()
                # else: replay of an applied round — do NOT join the
                # current round's barrier set, but do NOT ack early
                # either: the round may still be mid-checkpoint-write,
                # and the ack must imply durability (the wait below is
                # instant once _durable_round caught up)
            else:
                round_ = None    # legacy wire: count it, ack immediately
                self._legacy_barriers += 1
                snapshot = self._maybe_apply_locked()
        self._persist_and_ack(snapshot)
        if round_ is None:
            return b""  # legacy anonymous barrier: ack immediately
        # ack only once the round is applied AND (on checkpoint rounds)
        # durably on disk — a crash before this point leaves every
        # trainer un-acked and replaying the round, so nothing is lost
        with self._cv:
            self._wait_cv(lambda: self._durable_round > round_, ctx)
        return b""

    # -- shard checkpointing ------------------------------------------
    def _collect_state(self):
        """Snapshot (name, array) pairs — cheap reference grabs; scope
        writes REPLACE values, so held arrays stay consistent."""
        snap = []
        for name in self.scope.local_var_names():
            try:
                arr = np.asarray(self.scope.find_var(name))
            except Exception:
                continue  # live channels/readers &c. are not state
            if arr.dtype == object:
                continue
            snap.append((name, arr))
        return snap, self._applied_round

    def save_shard(self, dirname, snapshot=None):
        """Persist the shard.  Crash-safe: write to a tmp dir, keep the
        previous checkpoint at <dirname>.old until the new one is in
        place (load falls back to .old, so a kill between the renames
        cannot lose the only good checkpoint).  Filenames are
        URL-quoted var names (injective both ways)."""
        from urllib.parse import quote

        import shutil

        snap, round_ = snapshot if snapshot is not None \
            else self._collect_state()
        with self._ckpt_lock:  # overlapping rounds must not interleave
            tmp = dirname + ".tmp.%d" % os.getpid()
            # start CLEAN: a previously aborted save must not leak its
            # stale files into this checkpoint (load_shard reads every
            # file in the dir)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            for name, arr in snap:
                with open(os.path.join(tmp, quote(name, safe="")),
                          "wb") as f:
                    np.save(f, arr)
            from paddle_tpu.core.fsutil import atomic_write
            atomic_write(os.path.join(tmp, "_SUCCESS"), str(round_))
            old = dirname + ".old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.isdir(dirname):
                os.rename(dirname, old)
            os.rename(tmp, dirname)
            shutil.rmtree(old, ignore_errors=True)

    def load_shard(self, dirname):
        from urllib.parse import unquote

        for fn in os.listdir(dirname):
            if fn == "_SUCCESS":
                with open(os.path.join(dirname, fn)) as f:
                    try:
                        self._applied_round = int(f.read().strip() or 0)
                    except ValueError:
                        pass
                continue
            with open(os.path.join(dirname, fn), "rb") as f:
                self.scope.set(unquote(fn), np.load(f))

    def _get_variable(self, req, ctx=None):
        name, round_ = _dec_msg(req)
        with self._cv:
            if self.sync_mode:
                if not self._wait_cv(
                        lambda: self._applied_round >= round_, ctx):
                    return b""  # client gone: response is discarded
            # materialize to host INSIDE the lock: a concurrent async-mode
            # apply donates the param's device buffer, invalidating it
            val = np.asarray(self.scope.find_var(name))
        return _enc_tensor(name, val)

    def _prefetch_variable(self, req, ctx=None):
        """Row-subset read of a sharded table (reference
        send_recv.proto:27 PrefetchVariable + grpc_server.cc prefetch
        path): request carries LOCAL row ids of this server's block;
        response is the gathered rows.  Sync-mode waits for the same
        applied round as GetVariable so a prefetch never reads a table
        mid-update."""
        name, ids, round_ = _dec_tensor(req)
        with self._cv:
            if self.sync_mode:
                if not self._wait_cv(
                        lambda: self._applied_round >= round_, ctx):
                    return b""
            table = np.asarray(self.scope.find_var(name))
        rows = table[np.asarray(ids, np.int64)]
        return _enc_tensor(name, rows)

    def _fetch_barrier(self, req, ctx=None):
        return b""

    def _barrier_status(self, req, ctx=None):
        """Introspection for the trainer-side watchdog: who barriered
        the current round, and who the server is still waiting on."""
        import json

        with self._cv:
            arrived = sorted(
                self._senders[s]["label"] for s in self._barrier_senders
                if s in self._senders)
            known = sorted(
                ent["label"] for s, ent in self._senders.items()
                if s not in self._expired)
            return json.dumps({
                "applied_round": self._applied_round,
                "durable_round": self._durable_round,
                "alive": self._alive,
                "fanin": self.fanin_total,
                "barriers": self._barrier_count(),
                "arrived": arrived,
                "known": known,
                "waiting_for": sorted(set(known) - set(arrived)),
            }).encode()

    def _toggle_profile(self, req, ctx=None):
        """Trainer-driven server profiling (reference
        send_recv.proto:76 VariableMessage.profile: the trainer's
        profiler state rides the RPC envelope and switches the
        pserver's profiler).  extra=1 starts, extra=0 stops and writes
        the table to the named path.  Idempotent across trainers: with
        fanin>1 every trainer's toggle reaches the server, so redundant
        start/stop must be no-ops, and the default path is per-process
        (a fixed /tmp name would be predictable and cross-server
        clobbering)."""
        from paddle_tpu.fluid import profiler as prof

        path, on = _dec_msg(req)
        with self._cv:
            if bool(on) == getattr(self, "_profiling", False):
                return b""       # redundant toggle from another trainer
            self._profiling = bool(on)
        if on:
            prof.start_profiler(state="CPU")
        else:
            if not path:
                import tempfile
                path = os.path.join(
                    tempfile.mkdtemp(prefix="pserver_prof_"),
                    "profile")
            prof.stop_profiler(sorted_key="total", profile_path=path)
        return b""

    def _send_complete(self, req, ctx=None):
        snapshot = None
        with self._cv:
            sender = None
            if req:
                _, extra = _dec_msg(req)
                _, sender, _ = _unpack_round_sender(extra)
            if sender is None:
                self._alive -= 1        # legacy anonymous complete
            elif sender in self._completed:
                pass                    # duplicate/retried complete
            else:
                self._completed.add(sender)
                if sender in self._expired:
                    # the lease already decremented for this trainer —
                    # a second decrement would shut the server down
                    # under trainers still mid-round
                    self._expired.discard(sender)
                else:
                    self._alive -= 1
            if self._alive <= 0:
                self._shutdown.set()
            else:
                # stragglers of a half-round: apply what arrived
                snapshot = self._maybe_apply_locked()
            self._cv.notify_all()
        self._persist_and_ack(snapshot)
        return b""

    # -- application (lock held) --
    def _apply_one(self, gname):
        from paddle_tpu.core.selected_rows import SelectedRows

        vals = list(self._pending[gname].values())
        if not vals:
            return
        if any(isinstance(v, SelectedRows) for v in vals):
            # mean of sparse grads = concatenated rows, values / N
            # (scatter-add makes concatenation a sum)
            agg = SelectedRows(
                np.concatenate([np.asarray(v.rows) for v in vals]),
                np.concatenate([np.asarray(v.values) for v in vals])
                / len(vals),
                vals[0].height)
        elif len(vals) == 1:
            agg = np.asarray(vals[0])
        else:
            # wire-decoded arrays are READ-ONLY views over the gRPC
            # message buffer: copy once, then accumulate in place
            agg = np.array(vals[0], copy=True)
            for v in vals[1:]:
                agg += v
            agg /= len(vals)
        self.scope.set(gname, agg)
        self._pending[gname] = {}
        self.apply_block(self.grad_to_block[gname])

    def _apply_round(self):
        for g in self._pending:
            self._apply_one(g)
        if self._barrier_round > self._applied_round:
            # restarted from a checkpoint OLDER than the trainers'
            # round (checkpoint_every_n > 1): the skipped rounds' grads
            # are unrecoverable, so jump to the trainers' round and
            # count the replayed grads ONCE — bounded staleness instead
            # of re-applying the same gradients once per missing round
            self._applied_round = self._barrier_round
        self._applied_round += 1
        self._barrier_senders = set()
        self._barrier_round = -1
        self._legacy_barriers = 0
        self._cv.notify_all()


class RPCClient:
    """Trainer side (reference grpc_client.h:168).  Process-wide singleton:
    send/recv ops share channels, the sync round counter, the (round,
    sender) replay cache, and the retry policy."""

    _instance = None

    def __init__(self):
        import socket as _socket
        import uuid

        self._channels = {}
        self._lock = threading.Lock()
        self.step = 0
        # per-process identity: the server dedups (round, sender) so
        # replaying a round after a reconnect cannot double-count
        self.sender = uuid.uuid4().int & _SENDER_MASK
        self._seq = 0   # per-send sequence: async-mode resend dedup
        self.label = "trainer%s@%s:%d" % (
            os.getenv("PADDLE_TRAINER_ID", "?"),
            _socket.gethostname(), os.getpid())
        self.retry = RetryPolicy.from_env()
        self._resolver = None     # logical ep -> current physical ep
        self._redirects = {}      # logical ep -> physical ep overrides
        self._round_cache = {}    # ep -> {"round", "grads", "barriered"}

    @classmethod
    def instance(cls):
        if cls._instance is None:
            cls._instance = RPCClient()
        return cls._instance

    @classmethod
    def reset(cls):
        cls._instance = None

    def set_resolver(self, fn):
        """Install an endpoint re-resolver (resilience.EndpointResolver
        .resolve): consulted on reconnect so a pserver restarted on a
        new port is found through the discovery registry."""
        self._resolver = fn

    # -- transport ----------------------------------------------------
    def _phys(self, ep):
        return self._redirects.get(ep, ep)

    def _channel(self, phys):
        import grpc

        with self._lock:
            ch = self._channels.get(phys)
            if ch is None:
                ch = grpc.insecure_channel(phys, options=GRPC_OPTIONS)
                self._channels[phys] = ch
        return ch

    def _call(self, ep, method, payload, timeout=None):
        fn = self._channel(self._phys(ep)).unary_unary(
            "/%s/%s" % (SERVICE, method))
        return fn(payload, wait_for_ready=True, timeout=timeout)

    def _stub(self, ep, method):
        return self._channel(self._phys(ep)).unary_unary(
            "/%s/%s" % (SERVICE, method))

    def _reconnect(self, ep):
        """Drop the (possibly dead) channel and re-resolve the endpoint
        through discovery when a resolver is installed."""
        with self._lock:
            ch = self._channels.pop(self._phys(ep), None)
        if ch is not None:
            try:
                ch.close()
            except Exception:
                pass
        if self._resolver is not None:
            try:
                new = self._resolver(ep)
            except Exception:
                new = None
            if new and new != ep:
                self._redirects[ep] = new
            elif new == ep:
                self._redirects.pop(ep, None)

    # -- replay cache -------------------------------------------------
    def _next_seq(self):
        """Per-send sequence, 1..16383 wrapping (0 = 'no seq').  An
        async-mode server drops a resend whose (sender, name, seq)
        already applied; a replay reuses the ORIGINAL seq."""
        self._seq = (self._seq % _SEQ_MASK) + 1
        return self._seq

    def _record_send(self, ep, name, arr):
        """Cache this round's send for replay; returns its seq."""
        c = self._round_cache.get(ep)
        if c is None or c["round"] != self.step:
            c = {"round": self.step, "grads": {}, "barriered": False}
            self._round_cache[ep] = c
        # latest value per name: a round resend replaces, never appends
        seq = self._next_seq()
        c["grads"][name] = (arr, seq)
        return seq

    def _barrier_payload(self, round_):
        return _enc_msg(self.label, _pack_round_sender(round_, self.sender))

    def _replay_round(self, ep):
        """After a reconnect the server may have restarted and lost this
        round's un-applied state: resend the cached grads (the server
        dedups by sender+seq, so this is idempotent) and, if this
        trainer already barriered the round, the barrier too."""
        c = self._round_cache.get(ep)
        if not c:
            return
        to = self.retry.call_timeout
        for name, (arr, seq) in c["grads"].items():
            self._call(
                ep, "SendVariable",
                _enc_tensor(name, arr,
                            _pack_round_sender(c["round"], self.sender,
                                               seq)),
                timeout=to)
        if c["barriered"]:
            self._call(ep, "SendBarrier", self._barrier_payload(c["round"]),
                       timeout=to)

    def _retry_op(self, ep, method, payload, point=None, replay=False,
                  decode=False):
        """One RPC under the retry policy: per-attempt timeout, capped
        backoff, reconnect (+ optional round replay) between attempts,
        DeadlineExceeded when the budget runs out."""
        def attempt():
            if point:
                fault_point(point)
            return self._call(ep, method, payload,
                              timeout=self.retry.call_timeout)

        def on_retry(exc, attempt_no):
            self._reconnect(ep)
            if replay:
                self._replay_round(ep)

        reply = self.retry.run(
            attempt, describe="%s(%s)" % (method, ep), on_retry=on_retry)
        return _dec_tensor(reply)[1] if decode else reply

    # -- data plane ---------------------------------------------------
    def send_var(self, ep, name, arr):
        seq = self._record_send(ep, name, arr)
        self._retry_op(
            ep, "SendVariable",
            _enc_tensor(name, arr, _pack_round_sender(self.step,
                                                      self.sender, seq)),
            point="send_grad", replay=True)

    def _fast_pool(self):
        pool = getattr(self, "_fastwire_pool", None)
        if pool is None and FASTWIRE_PORT_OFFSET > 0:
            from . import fastwire
            pool = fastwire.FastConnPool(FASTWIRE_PORT_OFFSET)
            self._fastwire_pool = pool
        return pool

    def _fast_call(self, ep, method, payload):
        """One fastwire round-trip, or None when the endpoint has no
        data plane (gRPC fallback).  A STALE pooled connection (failure
        before the payload went out) retries once on a fresh one; a
        failure after the payload was sent raises a retryable
        ConnectionError carrying ``sent_payload=True`` — reads replay it
        freely, while _overlapped(idempotent=False) excludes it from the
        resend and surfaces it to the caller (the frame may already be
        applied; belt over the wire protocol's (round, sender, seq)
        dedup suspenders)."""
        pool = self._fast_pool()
        if pool is None:
            return None
        for _ in range(2):
            conn = pool.checkout(self._phys(ep))
            if conn is None:
                return None
            try:
                reply = conn.call(method, payload)
                pool.checkin(self._phys(ep), conn)
                return reply
            except ConnectionError as e:
                pool.discard(conn)
                if getattr(e, "sent_payload", True):
                    raise
        return None

    def _overlapped(self, method, point, eps, payloads, replay,
                    use_fast=True, idempotent=True):
        """Shared fan-out: first attempt everything in flight together —
        fastwire threads where the endpoint offers a data plane, then
        gRPC futures — and push any retryable failure through the
        sequential retry path (reconnect + optional round replay).
        Fatal errors surface immediately.  Returns raw replies.

        ``idempotent=False`` (state-mutating sends): a fastwire failure
        AFTER the payload went out is excluded from the gRPC fallback —
        the server may have consumed and applied the frame, and a
        resend would double-apply — and re-raised after the join so the
        caller learns the send may have landed.  Reads keep the
        fallback: re-fetching is always safe."""
        n = len(eps)
        results = [None] * n
        pending = list(range(n))
        post_send = None
        pool = self._fast_pool() if use_fast else None
        if pool is not None:
            errs = {}  # thread index -> captured exception

            def one(i):
                try:
                    fault_point(point)
                    results[i] = self._fast_call(eps[i], method,
                                                 payloads[i])
                except Exception as e:
                    # captured, classified AFTER join: a post-send
                    # failure of a non-idempotent send must not
                    # silently become a gRPC resend
                    errs[i] = e
                    results[i] = None

            ts = [threading.Thread(target=one, args=(i,))
                  for i in pending]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            excluded = set()
            fatal = None
            for i, e in sorted(errs.items()):
                if not RetryPolicy.is_retryable(e):
                    fatal = fatal or e
                elif not idempotent and getattr(e, "sent_payload",
                                                False):
                    # the server may have consumed and APPLIED the
                    # frame: resending over gRPC would double-apply
                    # (e.g. a SendVariable gradient skewing the sync
                    # average) — exclude from the fallback; re-raised
                    # AFTER the other endpoints' safe fallbacks finish
                    # so the round is as complete as it can be
                    excluded.add(i)
                    post_send = post_send or e
            if fatal is not None:
                # chain the maybe-applied send so recovery logic sees
                # both the fatal failure and the uncertain delivery
                raise fatal from post_send
            pending = [i for i in pending
                       if results[i] is None and i not in excluded]
        futs, need_retry = [], []
        for i in pending:
            try:
                fault_point(point)
                futs.append((i, self._stub(eps[i], method)
                             .future(payloads[i], wait_for_ready=True,
                                     timeout=self.retry.call_timeout)))
            except InjectedFault as e:
                if not e.retryable:
                    raise
                need_retry.append(i)
        for i, f in futs:
            try:
                results[i] = f.result()
            except Exception as e:
                if not RetryPolicy.is_retryable(e):
                    raise
                need_retry.append(i)
        for i in need_retry:
            results[i] = self._retry_op(eps[i], method, payloads[i],
                                        point=point, replay=replay)
        if post_send is not None:
            # surfaced only after every safe item completed its
            # fallback: the caller learns this send may have landed
            raise post_send
        return results

    def send_vars(self, triples):
        """Overlapped sends: [(ep, name, arr)] in flight together
        (reference grpc_client AsyncSendVar + Wait).  Bulk frames ride
        the fastwire data plane when the server offers it; the C
        send loop releases the GIL, so the per-shard threads genuinely
        overlap."""
        payloads = []
        for ep, name, arr in triples:
            seq = self._record_send(ep, name, arr)
            payloads.append(_enc_tensor(
                name, arr,
                _pack_round_sender(self.step, self.sender, seq)))
        self._overlapped("SendVariable", "send_grad",
                         [t[0] for t in triples], payloads, replay=True,
                         idempotent=False)

    def get_var(self, ep, name, round_=None):
        round_ = self.step if round_ is None else round_
        return self._retry_op(ep, "GetVariable", _enc_msg(name, round_),
                              point="get_param", replay=True, decode=True)

    def get_vars(self, pairs, round_=None):
        """Overlapped gets: [(ep, name)] -> [arr], one joined wait
        (reference AsyncGetVar + Wait); fastwire data plane when
        offered."""
        round_ = self.step if round_ is None else round_
        replies = self._overlapped(
            "GetVariable", "get_param", [ep for ep, _ in pairs],
            [_enc_msg(name, round_) for _, name in pairs], replay=True)
        return [_dec_tensor(r)[1] for r in replies]

    def prefetch_vars(self, triples, round_=None):
        """Overlapped row prefetches: [(ep, block_name, local_ids)] ->
        [rows] (reference AsyncPrefetchVar + Wait)."""
        round_ = self.step if round_ is None else round_
        replies = self._overlapped(
            "PrefetchVariable", "prefetch", [t[0] for t in triples],
            [_enc_tensor(name, np.asarray(ids, np.int64), round_)
             for _, name, ids in triples],
            replay=False, use_fast=False)
        return [_dec_tensor(r)[1] for r in replies]

    def send_barrier(self, eps):
        """Barrier every pserver CONCURRENTLY: the server-side barrier
        now blocks until the round is applied (and durably checkpointed
        on checkpoint rounds), so sequential calls across endpoints
        could deadlock if trainers ordered them differently."""
        payload = self._barrier_payload(self.step)
        errs = []

        def one(ep):
            try:
                self._retry_op(ep, "SendBarrier", payload,
                               point="send_barrier", replay=True)
                c = self._round_cache.get(ep)
                if c is not None and c["round"] == self.step:
                    c["barriered"] = True
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=one, args=(ep,)) for ep in eps]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise errs[0]
        self.step += 1

    def fetch_barrier(self, eps):
        for ep in eps:
            self._retry_op(ep, "FetchBarrier", b"", point="fetch_barrier")

    def barrier_status(self, ep, timeout=5.0):
        """The server's sync-barrier introspection (watchdog support)."""
        import json

        return json.loads(
            self._call(ep, "BarrierStatus", b"", timeout=timeout).decode())

    def toggle_profile(self, eps, on, profile_path=""):
        """Switch profiling on every pserver from the trainer side
        (reference VariableMessage.profile envelope bit)."""
        for ep in eps:
            self._call(ep, "ToggleProfile",
                       _enc_msg(profile_path, 1 if on else 0))

    def send_complete(self, eps):
        # identity payload: the server must not double-decrement its
        # fanin for a trainer the lease already expired, and must drop
        # a duplicate complete from the same process
        payload = _enc_msg(self.label,
                           _pack_round_sender(self.step, self.sender))
        for ep in eps:
            try:
                self._call(ep, "SendComplete", payload, timeout=10.0)
            except Exception:
                pass  # server may already be down
