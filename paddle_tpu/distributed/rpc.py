"""Trainer<->pserver RPC over gRPC generic handlers.

Parity: reference operators/detail/send_recv.proto:19-28 (SendRecvService:
SendVariable / GetVariable / PrefetchVariable), grpc_client.h:168,
grpc_server.cc, and the sync/async serve loops of
operators/listen_and_serv_op.cc:99,166.

Implementation notes (TPU-host path):
- gRPC *generic* method handlers with a numpy-native wire format — no
  protoc codegen; tensors travel as a raw dtype|shape|bytes frame
  (memcpy-speed encode, zero-copy decode — see _enc_arr).
- The sync protocol is barrier-counted like the reference: trainers send
  every grad, then SendBarrier; once ``fanin`` barriers arrive the server
  aggregates (mean over trainers), runs the per-param optimize blocks, and
  bumps ``applied_round``; GetVariable(round) blocks until
  ``applied_round >= round``.  SendComplete decrements fanin (reference
  framework/executor.cc:50 SendComplete) and stops the server at zero.
"""
from __future__ import annotations

import os
import threading
from concurrent import futures

import numpy as np

SERVICE = "paddle_tpu.PServer"

# fastwire data plane: raw-socket port = grpc port + this offset
# (0 disables).  Handshake magic keeps a mis-aimed connection safe.
FASTWIRE_PORT_OFFSET = int(os.environ.get("FLAGS_fastwire_port_offset",
                                          "2000"))

# gRPC defaults cap messages at 4 MB; one fc shard of a real model is
# routinely 10-100 MB (the reference moved such blocks over raw sockets,
# ParameterServer2.h).  Unlimited on both directions.
GRPC_OPTIONS = [("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1)]


def _enc_arr(parts, arr):
    """Append one array as dtype | ndim | shape | raw bytes.  Raw
    tobytes instead of np.save: the npy framing costs a full extra
    buffer pass (~650 MB/s measured vs memcpy), and a 100 MB dense
    round serializes ~400 MB — the hot path the reference served with
    zero-copy sockets (ParameterServer2.h)."""
    # NOT np.ascontiguousarray unconditionally: it promotes 0-d to 1-d
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        # fail at the SENDER: tobytes() on an object array would ship
        # heap pointers and only blow up at the remote decoder
        raise TypeError("cannot send object-dtype array over the "
                        "pserver wire (got dtype=%s)" % arr.dtype)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")
    parts.append(len(dt).to_bytes(2, "little"))
    parts.append(dt)
    parts.append(arr.ndim.to_bytes(1, "little"))
    for d in arr.shape:
        parts.append(int(d).to_bytes(8, "little"))
    # memoryview, not tobytes(): join copies it once — tobytes would
    # make that two full passes over a 100 MB payload
    parts.append(arr.data)


def _dec_arr(view, off):
    """Zero-copy array decode from a memoryview.  The result is a
    READ-ONLY view over the message buffer — every in-repo consumer is
    functional (aggregation, optimize blocks, device_put all produce
    fresh arrays); a caller that wants to mutate must .copy()."""
    n = int.from_bytes(view[off:off + 2], "little")
    off += 2
    dtype = np.dtype(view[off:off + n].tobytes().decode("ascii"))
    off += n
    ndim = view[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(view[off:off + 8], "little"))
        off += 8
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(view[off:off + nbytes],
                        dtype=dtype).reshape(shape)
    return arr, off + nbytes


def _enc_tensor(name, arr, extra=0):
    """Wire format: name | extra | kind (0 dense, 1 SelectedRows) | arrays.
    SelectedRows travel as (rows, values, height) — reference
    VariableMessage's SELECTED_ROWS type (send_recv.proto:48)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    nb = name.encode("utf-8")
    parts = [len(nb).to_bytes(4, "little"), nb,
             int(extra).to_bytes(8, "little", signed=True)]
    if isinstance(arr, SelectedRows):
        parts.append(b"\x01")
        parts.append(int(arr.height).to_bytes(8, "little"))
        _enc_arr(parts, np.asarray(arr.rows))
        _enc_arr(parts, np.asarray(arr.values))
    else:
        parts.append(b"\x00")
        _enc_arr(parts, np.asarray(arr))
    return b"".join(parts)


def _dec_tensor(data):
    from paddle_tpu.core.selected_rows import SelectedRows

    view = memoryview(data)
    n = int.from_bytes(view[:4], "little")
    name = view[4:4 + n].tobytes().decode("utf-8")
    off = 4 + n
    extra = int.from_bytes(view[off:off + 8], "little", signed=True)
    off += 8
    kind = view[off]
    off += 1
    if kind == 1:
        height = int.from_bytes(view[off:off + 8], "little")
        off += 8
        rows, off = _dec_arr(view, off)
        values, off = _dec_arr(view, off)
        return name, SelectedRows(rows, values, height), extra
    arr, off = _dec_arr(view, off)
    return name, arr, extra


def _enc_msg(name, extra=0):
    nb = name.encode("utf-8")
    return (len(nb).to_bytes(4, "little") + nb
            + int(extra).to_bytes(8, "little", signed=True))


def _dec_msg(data):
    n = int.from_bytes(data[:4], "little")
    name = bytes(data[4:4 + n]).decode("utf-8")
    extra = int.from_bytes(data[4 + n:12 + n], "little", signed=True)
    return name, extra


class VariableServer:
    """Parameter-server side: owns the scope, applies optimize blocks.

    ``grad_to_block``: grad(-block) var name -> pserver sub-block index.
    ``apply_block``: callable(block_idx) running one optimize sub-block
    against the server scope (wired to the executor by listen_and_serv).
    """

    def __init__(self, scope, grad_to_block, apply_block, fanin,
                 sync_mode=True, checkpoint_dir=None,
                 checkpoint_every_n=0):
        import grpc

        self.scope = scope
        self.grad_to_block = dict(grad_to_block)
        self.apply_block = apply_block
        self.fanin_total = int(fanin)
        self.sync_mode = bool(sync_mode)
        # shard checkpointing (reference go/pserver/service.go:346:
        # each pserver persists ITS parameter shard so a restarted
        # server resumes instead of reinitializing)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_n = int(checkpoint_every_n or 0)

        self._cv = threading.Condition()
        self._pending = {g: [] for g in self.grad_to_block}
        self._applied_round = 0
        self._barriers = 0
        self._alive = self.fanin_total
        self._shutdown = threading.Event()
        self._ckpt_lock = threading.Lock()  # one save at a time
        if checkpoint_dir:
            # restore AFTER the round counter exists: load_shard also
            # recovers _applied_round from _SUCCESS, or trainers
            # blocked in GetVariable(round=N) would wait forever on a
            # restarted server stuck at round 0
            for cand in (checkpoint_dir, checkpoint_dir + ".old"):
                if os.path.isdir(cand) and os.path.exists(
                        os.path.join(cand, "_SUCCESS")):
                    self.load_shard(cand)
                    break

        handlers = {
            "SendVariable": self._h(self._send_variable),
            "GetVariable": self._h(self._get_variable),
            "PrefetchVariable": self._h(self._prefetch_variable),
            "SendBarrier": self._h(self._send_barrier),
            "FetchBarrier": self._h(self._fetch_barrier),
            "ToggleProfile": self._h(self._toggle_profile),
            "SendComplete": self._h(self._send_complete),
        }
        # enough workers that fanin-1 blocked GetVariable waiters can never
        # starve the SendBarrier that would wake them
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max(16, 4 * self.fanin_total + 4)),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(SERVICE, handlers),))

    @staticmethod
    def _h(fn):
        import grpc

        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: fn(req))

    # -- lifecycle --
    def start(self, endpoint):
        """Bind + start; returns the bound port.  Also opens the
        fastwire raw-socket DATA plane at port+FASTWIRE_PORT_OFFSET
        (reference pserver/LightNetwork.cpp role): SendVariable /
        GetVariable bulk frames bypass Python gRPC; control RPCs
        (barriers, completion, profile) stay here.  Best-effort: no
        native toolchain or a taken port just means gRPC carries
        everything, as before."""
        port = self._server.add_insecure_port(endpoint)
        self._server.start()
        self._fast = None
        if FASTWIRE_PORT_OFFSET > 0:
            try:
                from . import fastwire
                self._fast = fastwire.FastServer(
                    port + FASTWIRE_PORT_OFFSET,
                    {"SendVariable": self._send_variable,
                     "GetVariable": self._get_variable})
            except Exception:
                self._fast = None
        return port

    def wait(self):
        """Block until every trainer sent SendComplete."""
        self._shutdown.wait()
        if getattr(self, "_fast", None) is not None:
            self._fast.stop()
        self._server.stop(grace=1).wait()

    # -- handlers --
    def _send_variable(self, req):
        name, arr, _round = _dec_tensor(req)
        with self._cv:
            if name not in self._pending:
                # direct write (e.g. init push or non-optimized var)
                self.scope.set(name, arr)
                return b""
            self._pending[name].append(arr)
            if not self.sync_mode:
                self._apply_one(name)
                self._cv.notify_all()
        return b""

    def _send_barrier(self, req):
        snapshot = None
        with self._cv:
            self._barriers += 1
            if self._barriers >= self._alive:
                self._apply_round()
                if (self.checkpoint_every_n and self.checkpoint_dir and
                        self._applied_round %
                        self.checkpoint_every_n == 0):
                    # collect under the lock, WRITE outside it — disk
                    # I/O must not stall every other RPC handler
                    snapshot = self._collect_state()
        if snapshot is not None:
            self.save_shard(self.checkpoint_dir, snapshot)
        return b""

    # -- shard checkpointing ------------------------------------------
    def _collect_state(self):
        """Snapshot (name, array) pairs — cheap reference grabs; scope
        writes REPLACE values, so held arrays stay consistent."""
        snap = []
        for name in self.scope.local_var_names():
            try:
                arr = np.asarray(self.scope.find_var(name))
            except Exception:
                continue  # live channels/readers &c. are not state
            if arr.dtype == object:
                continue
            snap.append((name, arr))
        return snap, self._applied_round

    def save_shard(self, dirname, snapshot=None):
        """Persist the shard.  Crash-safe: write to a tmp dir, keep the
        previous checkpoint at <dirname>.old until the new one is in
        place (load falls back to .old, so a kill between the renames
        cannot lose the only good checkpoint).  Filenames are
        URL-quoted var names (injective both ways)."""
        from urllib.parse import quote

        import shutil

        snap, round_ = snapshot if snapshot is not None \
            else self._collect_state()
        with self._ckpt_lock:  # overlapping rounds must not interleave
            tmp = dirname + ".tmp.%d" % os.getpid()
            # start CLEAN: a previously aborted save must not leak its
            # stale files into this checkpoint (load_shard reads every
            # file in the dir)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            for name, arr in snap:
                with open(os.path.join(tmp, quote(name, safe="")),
                          "wb") as f:
                    np.save(f, arr)
            with open(os.path.join(tmp, "_SUCCESS"), "w") as f:
                f.write(str(round_))
            old = dirname + ".old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.isdir(dirname):
                os.rename(dirname, old)
            os.rename(tmp, dirname)
            shutil.rmtree(old, ignore_errors=True)

    def load_shard(self, dirname):
        from urllib.parse import unquote

        for fn in os.listdir(dirname):
            if fn == "_SUCCESS":
                with open(os.path.join(dirname, fn)) as f:
                    try:
                        self._applied_round = int(f.read().strip() or 0)
                    except ValueError:
                        pass
                continue
            with open(os.path.join(dirname, fn), "rb") as f:
                self.scope.set(unquote(fn), np.load(f))

    def _get_variable(self, req):
        name, round_ = _dec_msg(req)
        with self._cv:
            if self.sync_mode:
                self._cv.wait_for(
                    lambda: self._applied_round >= round_
                    or self._shutdown.is_set())
            # materialize to host INSIDE the lock: a concurrent async-mode
            # apply donates the param's device buffer, invalidating it
            val = np.asarray(self.scope.find_var(name))
        return _enc_tensor(name, val)

    def _prefetch_variable(self, req):
        """Row-subset read of a sharded table (reference
        send_recv.proto:27 PrefetchVariable + grpc_server.cc prefetch
        path): request carries LOCAL row ids of this server's block;
        response is the gathered rows.  Sync-mode waits for the same
        applied round as GetVariable so a prefetch never reads a table
        mid-update."""
        name, ids, round_ = _dec_tensor(req)
        with self._cv:
            if self.sync_mode:
                self._cv.wait_for(
                    lambda: self._applied_round >= round_
                    or self._shutdown.is_set())
            table = np.asarray(self.scope.find_var(name))
        rows = table[np.asarray(ids, np.int64)]
        return _enc_tensor(name, rows)

    def _fetch_barrier(self, req):
        return b""

    def _toggle_profile(self, req):
        """Trainer-driven server profiling (reference
        send_recv.proto:76 VariableMessage.profile: the trainer's
        profiler state rides the RPC envelope and switches the
        pserver's profiler).  extra=1 starts, extra=0 stops and writes
        the table to the named path.  Idempotent across trainers: with
        fanin>1 every trainer's toggle reaches the server, so redundant
        start/stop must be no-ops, and the default path is per-process
        (a fixed /tmp name would be predictable and cross-server
        clobbering)."""
        from paddle_tpu.fluid import profiler as prof

        path, on = _dec_msg(req)
        with self._cv:
            if bool(on) == getattr(self, "_profiling", False):
                return b""       # redundant toggle from another trainer
            self._profiling = bool(on)
        if on:
            prof.start_profiler(state="CPU")
        else:
            if not path:
                import tempfile
                path = os.path.join(
                    tempfile.mkdtemp(prefix="pserver_prof_"),
                    "profile")
            prof.stop_profiler(sorted_key="total", profile_path=path)
        return b""

    def _send_complete(self, req):
        with self._cv:
            self._alive -= 1
            if self._alive <= 0:
                self._shutdown.set()
            elif self._barriers >= self._alive > 0:
                # stragglers of a half-round: apply what arrived
                self._apply_round()
            self._cv.notify_all()
        return b""

    # -- application (lock held) --
    def _apply_one(self, gname):
        from paddle_tpu.core.selected_rows import SelectedRows

        vals = self._pending[gname]
        if not vals:
            return
        if any(isinstance(v, SelectedRows) for v in vals):
            # mean of sparse grads = concatenated rows, values / N
            # (scatter-add makes concatenation a sum)
            agg = SelectedRows(
                np.concatenate([np.asarray(v.rows) for v in vals]),
                np.concatenate([np.asarray(v.values) for v in vals])
                / len(vals),
                vals[0].height)
        elif len(vals) == 1:
            agg = np.asarray(vals[0])
        else:
            agg = np.sum(vals, axis=0) / len(vals)
        self.scope.set(gname, agg)
        self._pending[gname] = []
        self.apply_block(self.grad_to_block[gname])

    def _apply_round(self):
        for g in self._pending:
            self._apply_one(g)
        self._applied_round += 1
        self._barriers = 0
        self._cv.notify_all()


class RPCClient:
    """Trainer side (reference grpc_client.h:168).  Process-wide singleton:
    send/recv ops share channels and the sync round counter."""

    _instance = None

    def __init__(self):
        self._channels = {}
        self._lock = threading.Lock()
        self.step = 0

    @classmethod
    def instance(cls):
        if cls._instance is None:
            cls._instance = RPCClient()
        return cls._instance

    @classmethod
    def reset(cls):
        cls._instance = None

    def _call(self, ep, method, payload):
        import grpc

        with self._lock:
            ch = self._channels.get(ep)
            if ch is None:
                ch = grpc.insecure_channel(ep, options=GRPC_OPTIONS)
                self._channels[ep] = ch
        fn = ch.unary_unary("/%s/%s" % (SERVICE, method))
        return fn(payload, wait_for_ready=True)

    def _stub(self, ep, method):
        import grpc

        with self._lock:
            ch = self._channels.get(ep)
            if ch is None:
                ch = grpc.insecure_channel(ep, options=GRPC_OPTIONS)
                self._channels[ep] = ch
        return ch.unary_unary("/%s/%s" % (SERVICE, method))

    def send_var(self, ep, name, arr):
        self._call(ep, "SendVariable", _enc_tensor(name, arr, self.step))

    def _fast_pool(self):
        pool = getattr(self, "_fastwire_pool", None)
        if pool is None and FASTWIRE_PORT_OFFSET > 0:
            from . import fastwire
            pool = fastwire.FastConnPool(FASTWIRE_PORT_OFFSET)
            self._fastwire_pool = pool
        return pool

    def _fast_call(self, ep, method, payload):
        """One fastwire round-trip, or None when the endpoint has no
        data plane (gRPC fallback).  A STALE pooled connection (failure
        before the payload went out) retries once on a fresh one; a
        failure after the payload was sent must raise — the server may
        already have applied the frame, and resending (fast or gRPC)
        would double-apply a non-idempotent gradient."""
        pool = self._fast_pool()
        if pool is None:
            return None
        for _ in range(2):
            conn = pool.checkout(ep)
            if conn is None:
                return None
            try:
                reply = conn.call(method, payload)
                pool.checkin(ep, conn)
                return reply
            except ConnectionError as e:
                pool.discard(conn)
                if getattr(e, "sent_payload", True):
                    raise RuntimeError(
                        "fastwire connection to %s failed after the "
                        "frame was sent; cannot safely resend a "
                        "possibly-applied %s" % (ep, method)) from e
        return None

    def send_vars(self, triples):
        """Overlapped sends: [(ep, name, arr)] in flight together
        (reference grpc_client AsyncSendVar + Wait).  Bulk frames ride
        the fastwire data plane when the server offers it; the C
        send loop releases the GIL, so the per-shard threads genuinely
        overlap."""
        pool = self._fast_pool()
        if pool is not None:
            results = [None] * len(triples)

            def one(i, ep, name, arr):
                results[i] = self._fast_call(
                    ep, "SendVariable", _enc_tensor(name, arr, self.step))

            ts = [threading.Thread(target=one, args=(i, ep, nm, ar))
                  for i, (ep, nm, ar) in enumerate(triples)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            rest = [triples[i] for i, r in enumerate(results)
                    if r is None]
        else:
            rest = list(triples)
        if not rest:
            return
        futs = [self._stub(ep, "SendVariable").future(
            _enc_tensor(name, arr, self.step), wait_for_ready=True)
            for ep, name, arr in rest]
        for f in futs:
            f.result()

    def get_var(self, ep, name, round_=None):
        round_ = self.step if round_ is None else round_
        _, arr, _ = _dec_tensor(
            self._call(ep, "GetVariable", _enc_msg(name, round_)))
        return arr

    def get_vars(self, pairs, round_=None):
        """Overlapped gets: [(ep, name)] -> [arr], one joined wait
        (reference AsyncGetVar + Wait); fastwire data plane when
        offered."""
        round_ = self.step if round_ is None else round_
        pool = self._fast_pool()
        results = [None] * len(pairs)
        rest_idx = list(range(len(pairs)))
        if pool is not None:
            def one(i, ep, name):
                r = self._fast_call(ep, "GetVariable",
                                    _enc_msg(name, round_))
                if r is not None:
                    results[i] = _dec_tensor(r)[1]

            ts = [threading.Thread(target=one, args=(i, ep, nm))
                  for i, (ep, nm) in enumerate(pairs)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            rest_idx = [i for i in rest_idx if results[i] is None]
        futs = [(i, self._stub(pairs[i][0], "GetVariable").future(
            _enc_msg(pairs[i][1], round_), wait_for_ready=True))
            for i in rest_idx]
        for i, f in futs:
            results[i] = _dec_tensor(f.result())[1]
        return results

    def prefetch_vars(self, triples, round_=None):
        """Overlapped row prefetches: [(ep, block_name, local_ids)] ->
        [rows] (reference AsyncPrefetchVar + Wait)."""
        round_ = self.step if round_ is None else round_
        futs = [self._stub(ep, "PrefetchVariable").future(
            _enc_tensor(name, np.asarray(ids, np.int64), round_),
            wait_for_ready=True)
            for ep, name, ids in triples]
        return [_dec_tensor(f.result())[1] for f in futs]

    def send_barrier(self, eps):
        for ep in eps:
            self._call(ep, "SendBarrier", b"")
        self.step += 1

    def fetch_barrier(self, eps):
        for ep in eps:
            self._call(ep, "FetchBarrier", b"")

    def toggle_profile(self, eps, on, profile_path=""):
        """Switch profiling on every pserver from the trainer side
        (reference VariableMessage.profile envelope bit)."""
        for ep in eps:
            self._call(ep, "ToggleProfile",
                       _enc_msg(profile_path, 1 if on else 0))

    def send_complete(self, eps):
        for ep in eps:
            try:
                self._call(ep, "SendComplete", b"")
            except Exception:
                pass  # server may already be down
