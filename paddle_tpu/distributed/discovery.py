"""Service discovery + leader election over a shared filesystem.

Parity: the reference's etcd usage — pservers/master register endpoints
that trainers watch (go/pserver/etcd_client.go, go/master/etcd_client.go
:27-31 with a leader lock so a standby master can take over).  The
TPU-native deployment substrate here is a shared filesystem (every
multi-host TPU pod has one); the same three primitives are provided:

  EndpointRegistry  register/list/wait_for with heartbeat TTLs
                    (etcd key leases)
  FileLock          single-writer lock with stale-holder takeover
                    (etcd election: the master's AddOwner campaign)
  MasterHA          standby master loop: campaign, recover from
                    snapshot, serve, republish the endpoint

Files are written atomically (tmp + rename), heartbeats are mtime-based,
and a crashed holder's lock is reclaimed after ``ttl`` seconds.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid

from paddle_tpu.core import sanitizer as _san

__all__ = ["EndpointRegistry", "FileLock", "MasterHA"]

DEFAULT_TTL = 10.0


class EndpointRegistry:
    """Register live endpoints under <root>/<kind>/; liveness = file
    mtime heartbeat within ttl."""

    def __init__(self, root, ttl=DEFAULT_TTL):
        self.root = root
        self.ttl = float(ttl)
        self._beats = {}  # (kind, endpoint) -> stop Event

    def _path(self, kind, endpoint):
        safe = endpoint.replace("/", "_").replace(":", "_")
        return os.path.join(self.root, kind, safe + ".json")

    def register(self, kind, endpoint, meta=None, heartbeat=True):
        """Publish endpoint; a daemon thread refreshes the heartbeat
        until unregister (etcd lease keep-alive analog)."""
        path = self._path(kind, endpoint)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        payload = {"endpoint": endpoint, "pid": os.getpid(),
                   "meta": meta or {}}
        tmp = path + ".%d.tmp" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        if heartbeat:
            stop = _san.make_event("discovery.beat.stop")
            self._beats[(kind, endpoint)] = stop

            def beat():
                while not stop.wait(self.ttl / 3.0):
                    try:
                        os.utime(path)
                    except OSError:
                        return  # unregistered underneath us

            threading.Thread(target=beat, daemon=True).start()
        return path

    def unregister(self, kind, endpoint):
        stop = self._beats.pop((kind, endpoint), None)
        if stop is not None:
            stop.set()
        try:
            os.remove(self._path(kind, endpoint))
        except FileNotFoundError:
            pass

    def list(self, kind):
        """Endpoints with a fresh heartbeat, sorted."""
        return sorted(ep for ep, _ in self.list_meta(kind))

    def list_meta(self, kind):
        """[(endpoint, meta)] for endpoints with a fresh heartbeat,
        sorted by endpoint.  ``meta`` is whatever register() published —
        e.g. a pserver's stable shard id, which lets a trainer re-map a
        restarted server that came back on a new port."""
        d = os.path.join(self.root, kind)
        out = []
        now = time.time()
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return []
        for fn in names:
            p = os.path.join(d, fn)
            try:
                if now - os.stat(p).st_mtime > self.ttl:
                    continue
                with open(p) as f:
                    payload = json.load(f)
                out.append((payload["endpoint"], payload.get("meta") or {}))
            except (OSError, ValueError, KeyError):
                continue  # torn write / removed underneath us
        return sorted(out)

    def wait_for(self, kind, n=1, timeout=30.0, poll=0.1):
        """Block until >= n live endpoints of ``kind`` exist (trainers
        discovering pservers / the master)."""
        deadline = time.time() + timeout
        while True:
            eps = self.list(kind)
            if len(eps) >= n:
                return eps
            if time.time() > deadline:
                raise TimeoutError(
                    "only %d/%d %r endpoints appeared within %.1fs"
                    % (len(eps), n, kind, timeout))
            time.sleep(poll)


class FileLock:
    """Single-writer lock with stale-holder takeover — the leader-
    election analog (go/master/etcd_client.go:27-31 AddOwner).  The
    holder heartbeats the lock file; a candidate steals it when the
    heartbeat is older than ttl (the holder crashed)."""

    def __init__(self, path, ttl=DEFAULT_TTL, on_lost=None):
        self.path = path
        self.ttl = float(ttl)
        self._stop = None
        # pid.thread alone collides for two FileLock instances in one
        # thread (in-process active+standby); the nonce makes ownership
        # checks identify the instance, not just the thread.
        self.token = "%d.%d.%s" % (os.getpid(), threading.get_ident(),
                                   uuid.uuid4().hex[:8])
        self.lost = False      # set when another holder stole the lock
        self._on_lost = on_lost

    def try_acquire(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(self.token)
        except FileExistsError:
            try:
                age = time.time() - os.stat(self.path).st_mtime
            except FileNotFoundError:
                return self.try_acquire()  # raced a release
            if age <= self.ttl:
                return False
            # Stale holder: the steal itself must be single-winner, or
            # two standbys both become master (split brain).  An
            # O_EXCL ".steal" intent file is the election: exactly one
            # candidate creates it, removes the stale lock, and
            # recurses into the O_CREAT|O_EXCL path above; a stealer
            # that died mid-steal leaves a stale intent file that ages
            # out the same way.
            steal = self.path + ".steal"
            try:
                fd = os.open(steal, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                try:
                    if time.time() - os.stat(steal).st_mtime > self.ttl:
                        os.remove(steal)  # dead stealer; retry later
                except FileNotFoundError:
                    pass
                return False
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(self.token)
                try:
                    os.remove(self.path)
                except FileNotFoundError:
                    pass
                return self.try_acquire()
            finally:
                try:
                    os.remove(steal)
                except FileNotFoundError:
                    pass
        self._heartbeat()
        return True

    def acquire(self, timeout=60.0, poll=0.2):
        deadline = time.time() + timeout
        while not self.try_acquire():
            if time.time() > deadline:
                raise TimeoutError("lock %s not acquired in %.1fs"
                                   % (self.path, timeout))
            time.sleep(poll)
        return self

    def _heartbeat(self):
        stop = _san.make_event("discovery.watch.stop")
        self._stop = stop
        self.lost = False

        def beat():
            while not stop.wait(self.ttl / 3.0):
                # verify we STILL hold it before touching: a holder that
                # stalled past ttl may have been stolen from — blindly
                # utime-ing the new holder's file would hide the loss
                # and leave two live leaders (split brain)
                try:
                    with open(self.path) as f:
                        if f.read() != self.token:
                            raise OSError("stolen")
                    os.utime(self.path)
                except OSError:
                    self.lost = True
                    cb = self._on_lost
                    if cb is not None:
                        try:
                            cb()
                        except Exception:
                            pass
                    return

        threading.Thread(target=beat, daemon=True).start()

    def release(self):
        if self._stop is not None:
            self._stop.set()
            self._stop = None
        try:
            with open(self.path) as f:
                if f.read() == self.token:
                    os.remove(self.path)
        except OSError:
            pass


class MasterHA:
    """Run a Master behind leader election: campaign on the lock,
    recover state from the shared snapshot, serve, publish the endpoint
    in the registry.  A standby started the same way blocks in
    ``campaign()`` until the active master dies, then takes over from
    the snapshot — trainers re-resolve via the registry and the dataset
    completes exactly once (done-queue accounting survives in the
    snapshot)."""

    KIND = "master"

    def __init__(self, root, endpoint, lease_timeout=None, ttl=None,
                 **master_kwargs):
        from .master import DEFAULT_LEASE, Master, MasterServer

        ttl = DEFAULT_TTL if ttl is None else ttl
        self.registry = EndpointRegistry(root, ttl=ttl)
        # fencing: if another master steals the (stale) lock while this
        # one is stalled, stop serving the moment the beat notices
        self.lock = FileLock(os.path.join(root, "master.lock"), ttl=ttl,
                             on_lost=self._on_leadership_lost)
        self.endpoint = endpoint
        master_kwargs.setdefault("snapshot_path",
                                 os.path.join(root, "master.snapshot"))
        self.master = Master(
            lease_timeout=lease_timeout or DEFAULT_LEASE,
            **master_kwargs)
        self.server = MasterServer(self.master)

    def campaign(self, timeout=120.0):
        """Block until leadership is won, then serve + register."""
        self.lock.acquire(timeout=timeout)
        # leadership won: (re)load whatever the previous master durably
        # finished — pending leases are void, their tasks return to todo
        if os.path.exists(self.master._snapshot_path):
            self.master._recover()
        self.server.start(self.endpoint)
        self.registry.register(self.KIND, self.endpoint)
        return self

    def _on_leadership_lost(self):
        self.registry.unregister(self.KIND, self.endpoint)
        self.server.stop()

    def stop(self):
        self.registry.unregister(self.KIND, self.endpoint)
        self.server.stop()
        self.lock.release()


def resolve_master(root, timeout=30.0, ttl=DEFAULT_TTL):
    """Trainer-side: the active master's endpoint (first live one)."""
    return EndpointRegistry(root, ttl=ttl).wait_for(
        "master", 1, timeout=timeout)[0]


class HAMasterClient:
    """MasterClient that discovers the active master through the
    registry and transparently re-resolves + reconnects when it dies
    mid-call (go/master/client.go re-watches etcd the same way)."""

    def __init__(self, root, timeout=60.0, ttl=DEFAULT_TTL):
        self.root = root
        self.timeout = float(timeout)
        self.ttl = ttl
        self._client = None
        self._endpoint = None

    def _ensure(self):
        from .master import MasterClient
        from .resilience import RetryPolicy

        if self._client is None:
            self._endpoint = resolve_master(self.root, self.timeout,
                                            self.ttl)
            # fail-fast inner client: re-resolution of a NEW master
            # lives in THIS retry loop, so the per-endpoint client must
            # surface the first transient error instead of retrying the
            # dead endpoint until its own deadline
            self._client = MasterClient(
                self._endpoint,
                retry=RetryPolicy(max_attempts=1,
                                  call_timeout=min(5.0, self.timeout)))
        return self._client

    def _retry(self, fn, *args, **kwargs):
        try:
            import grpc
            transient = (grpc.RpcError, ConnectionError, OSError,
                         TimeoutError)
        except ImportError:
            transient = (ConnectionError, OSError, TimeoutError)
        deadline = time.time() + self.timeout
        while True:
            try:
                return fn(self._ensure(), *args, **kwargs)
            except transient:
                # master gone (or not up yet): drop the channel, wait
                # for a (possibly new) one to register, try again —
                # programming errors (TypeError &c.) surface at once
                self._client = None
                if time.time() > deadline:
                    raise
                time.sleep(0.2)

    def set_dataset(self, payloads):
        return self._retry(lambda c: c.set_dataset(payloads))

    def get_task(self, block=True):
        return self._retry(lambda c: c.get_task(block=block))

    def task_finished(self, task_id):
        return self._retry(lambda c: c.task_finished(task_id))

    def task_failed(self, task_id):
        return self._retry(lambda c: c.task_failed(task_id))

    def counts(self):
        return self._retry(lambda c: c.counts())
