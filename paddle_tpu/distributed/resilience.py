"""Failure-path machinery for the distributed stack.

Parity: the reference pairs Fluid with a Go fault-tolerance stack —
go/pserver/client (retry + etcd re-resolution), go/master/service.go
:368 checkTimeout (lease expiry re-queues a dead trainer's work), and
etcd-backed recovery for both daemons.  This module is the Python-side
analog used by distributed/rpc.py and distributed/master.py:

  RetryPolicy       per-call deadlines + capped exponential backoff with
                    jitter, classifying retryable vs fatal gRPC errors
  FaultInjector     env-driven fault hooks (``FLAGS_fault_spec``) that
                    probabilistically drop, delay, or hard-error calls
                    at named injection points — the testable crash lab
  EndpointResolver  re-resolve a restarted pserver's endpoint through
                    discovery.EndpointRegistry (same shard id, possibly
                    a new port)
  watchdog_error    turn an exhausted deadline into an error naming the
                    peers a barrier is still waiting on, instead of an
                    indefinite hang

Env knobs (all optional; see README "Fault tolerance"):
  FLAGS_fault_spec        e.g. "send_grad:drop:0.1,get_param:delay:2.0"
  FLAGS_fault_seed        deterministic injection RNG seed
  FLAGS_rpc_deadline      total per-operation deadline, seconds
  FLAGS_rpc_call_timeout  per-attempt gRPC timeout, seconds
  FLAGS_rpc_retry_backoff / FLAGS_rpc_max_backoff / FLAGS_rpc_max_attempts
  FLAGS_trainer_lease     pserver-side lease: a mid-round trainer silent
                          this long is expired from the sync fanin
"""
from __future__ import annotations

import random
import threading

from paddle_tpu.core import sanitizer as _san
import time

from paddle_tpu.core.flags import FLAGS, define_flag

from paddle_tpu.observability import metrics as _obs_metrics

_M_RETRIES = _obs_metrics.counter(
    "rpc_retries_total", "retryable RPC failures that entered backoff")
_M_FAULTS = _obs_metrics.counter(
    "faults_injected_total", "FaultInjector rules fired")

__all__ = [
    "RetryPolicy", "FaultInjector", "InjectedFault", "DeadlineExceeded",
    "WatchdogTimeout", "EndpointResolver", "fault_point", "get_injector",
    "install_faults", "maybe_corrupt", "watchdog_error",
]

define_flag("fault_spec", "",
            "fault injection spec: point:action:value[:limit],...")
define_flag("fault_seed", 0, "fault injection RNG seed (0 = OS entropy)")
define_flag("rpc_deadline", 600.0,
            "total deadline for one distributed operation, seconds")
define_flag("rpc_call_timeout", 30.0,
            "per-attempt timeout of one RPC, seconds")
define_flag("rpc_retry_backoff", 0.05, "initial retry backoff, seconds")
define_flag("rpc_max_backoff", 2.0, "backoff cap, seconds")
define_flag("rpc_max_attempts", 0, "attempt cap per operation (0 = none)")
define_flag("trainer_lease", 0.0,
            "pserver sync fanin lease: expire a trainer silent this "
            "long mid-round (0 disables)")
define_flag("pserver_checkpoint_root", "",
            "root dir for per-endpoint pserver shard checkpoints")
define_flag("pserver_checkpoint_every_n", 0,
            "checkpoint the pserver shard every N applied rounds")
define_flag("pserver_wire_batch", True,
            "ship all of a trainer's shards for an endpoint as ONE "
            "batched fastwire scatter frame (and gather the return leg "
            "as one streamed call) instead of per-variable messages; "
            "0 restores the unbatched wire")
define_flag("pserver_overlap", True,
            "full-duplex sync rounds: barrier acks overlap with param "
            "gets (the server streams each shard as its apply commits) "
            "and grad convert/encode overlaps in-flight sends; 0 "
            "restores the serialized send->barrier->get round")


class InjectedFault(ConnectionError):
    """A fault fired by FaultInjector.  ``retryable`` mirrors how a real
    failure of that kind would classify (drop = transient network loss;
    error = a poisoned/fatal reply)."""

    def __init__(self, point, action, retryable=True):
        super().__init__("injected fault at %r: %s" % (point, action))
        self.point = point
        self.action = action
        self.retryable = retryable


class DeadlineExceeded(TimeoutError):
    """An operation ran out of retry budget (time or attempts)."""

    def __init__(self, message, last_error=None, attempts=0, elapsed=0.0):
        super().__init__(message)
        self.last_error = last_error
        self.attempts = attempts
        self.elapsed = elapsed


class WatchdogTimeout(TimeoutError):
    """A collective hang converted into an error naming the stragglers."""


class RetryPolicy:
    """Deadline + capped exponential backoff + jitter.

    ``call_timeout`` bounds ONE attempt (passed to gRPC as the call
    deadline); ``deadline`` bounds the whole operation across retries.
    Retryable: transient transport states (UNAVAILABLE, DEADLINE_EXCEEDED,
    ABORTED, RESOURCE_EXHAUSTED, CANCELLED), socket-level OSErrors, and
    retryable InjectedFaults.  Everything else — INVALID_ARGUMENT, a
    server-side crash surfacing as UNKNOWN/INTERNAL, programming errors —
    is fatal and surfaces immediately.
    """

    def __init__(self, deadline=None, call_timeout=None, base_backoff=None,
                 max_backoff=None, multiplier=2.0, jitter=0.5,
                 max_attempts=None, rng=None):
        self.deadline = float(FLAGS.rpc_deadline if deadline is None
                              else deadline)
        self.call_timeout = float(FLAGS.rpc_call_timeout
                                  if call_timeout is None else call_timeout)
        self.base_backoff = float(FLAGS.rpc_retry_backoff
                                  if base_backoff is None else base_backoff)
        self.max_backoff = float(FLAGS.rpc_max_backoff
                                 if max_backoff is None else max_backoff)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.max_attempts = int(FLAGS.rpc_max_attempts
                                if max_attempts is None else max_attempts)
        self._rng = rng or random.Random()

    @classmethod
    def from_env(cls):
        return cls()

    # -- classification --
    @staticmethod
    def is_retryable(exc):
        if isinstance(exc, InjectedFault):
            return exc.retryable
        if isinstance(exc, DeadlineExceeded):
            return False
        try:
            import grpc
            if isinstance(exc, grpc.RpcError):
                code = exc.code() if callable(getattr(exc, "code", None)) \
                    else None
                return code in (grpc.StatusCode.UNAVAILABLE,
                                grpc.StatusCode.DEADLINE_EXCEEDED,
                                grpc.StatusCode.ABORTED,
                                grpc.StatusCode.RESOURCE_EXHAUSTED,
                                grpc.StatusCode.CANCELLED)
        except ImportError:
            pass
        return isinstance(exc, (ConnectionError, TimeoutError, OSError))

    def backoff(self, attempt):
        """Capped exponential with +-jitter (attempt counts from 1)."""
        raw = min(self.max_backoff,
                  self.base_backoff * (self.multiplier ** (attempt - 1)))
        lo = max(0.0, 1.0 - self.jitter)
        return raw * self._rng.uniform(lo, 1.0 + self.jitter)

    def run(self, fn, describe="", on_retry=None):
        """Call ``fn`` until it succeeds, a fatal error surfaces, or the
        deadline/attempt budget runs out (-> DeadlineExceeded).
        ``on_retry(exc, attempt)`` runs before each retry — reconnects,
        round replays; its own retryable failures feed back into the
        loop instead of aborting it."""
        start = time.monotonic()
        attempt = 0
        last = None
        while True:
            try:
                return fn()
            except Exception as e:
                if not self.is_retryable(e):
                    raise
                last = e
                _M_RETRIES.inc()
            attempt += 1
            elapsed = time.monotonic() - start
            delay = self.backoff(attempt)
            if (self.max_attempts and attempt >= self.max_attempts) or \
                    elapsed + delay > self.deadline:
                raise DeadlineExceeded(
                    "%s failed after %d attempt(s) in %.1fs "
                    "(deadline %.1fs): %s"
                    % (describe or "rpc", attempt, elapsed, self.deadline,
                       last),
                    last_error=last, attempts=attempt,
                    elapsed=elapsed) from last
            time.sleep(delay)
            if on_retry is not None:
                try:
                    on_retry(last, attempt)
                except Exception as e:
                    if not self.is_retryable(e):
                        raise
                    last = e


class _Rule:
    __slots__ = ("point", "action", "value", "limit", "fired")

    def __init__(self, point, action, value, limit=0):
        self.point = point
        self.action = action
        self.value = value
        self.limit = int(limit)
        self.fired = 0


class FaultInjector:
    """Probabilistic fault hooks at named injection points.

    Spec grammar (comma-separated entries, colon-separated fields):
      <point>:drop:<prob>[:<limit>]    raise a RETRYABLE InjectedFault
                                       with probability <prob>
      <point>:delay:<secs>[:<limit>]   sleep <secs> before the call
      <point>:error:<prob>[:<limit>]   raise a FATAL InjectedFault
      <point>:corrupt:<round>[:<limit>]  poison wire tensors with NaN
                                       at sync round <round> (ISSUE 8:
                                       the numerics-observatory crash
                                       lab — limit 1 poisons exactly
                                       one tensor of that round)
    ``limit`` caps total firings of that rule (0 / omitted = unlimited).
    Known points: send_grad, get_param, prefetch, send_barrier,
    fetch_barrier, master_rpc (a rule may also name any custom point).

    ``corrupt`` rules never raise; the data plane calls
    ``maybe_corrupt(point, round, arr)`` with each outbound tensor and
    ships whatever comes back — detection and (round, sender)
    attribution is the PSERVER's job (observability/numerics.py
    server_check_grad, asserted end-to-end by ``tools/fault_matrix.py
    --preset numerics``).
    """

    ACTIONS = ("drop", "delay", "error", "corrupt")

    def __init__(self, spec="", seed=None):
        self.rules = self._parse(spec)
        self._rng = random.Random(seed or None)
        self._lock = _san.make_lock("resilience.injector")
        self.stats = {}

    @classmethod
    def from_env(cls):
        return cls(FLAGS.fault_spec, seed=FLAGS.fault_seed or None)

    @staticmethod
    def _parse(spec):
        rules = []
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            fields = entry.split(":")
            if len(fields) not in (3, 4):
                raise ValueError(
                    "bad fault spec entry %r: want "
                    "point:action:value[:limit]" % entry)
            point, action, value = fields[0], fields[1], fields[2]
            if action not in FaultInjector.ACTIONS:
                raise ValueError("bad fault action %r in %r (want one of "
                                 "%s)" % (action, entry,
                                          "/".join(FaultInjector.ACTIONS)))
            limit = int(fields[3]) if len(fields) == 4 else 0
            rules.append(_Rule(point, action, float(value), limit))
        return rules

    def fire(self, point):
        """Run every rule registered for ``point`` — may sleep or raise.
        ``corrupt`` rules are payload transforms, not call faults: they
        fire only through maybe_corrupt()."""
        for rule in self.rules:
            if rule.point != point or rule.action == "corrupt":
                continue
            with self._lock:
                if rule.limit and rule.fired >= rule.limit:
                    continue
                if rule.action == "delay":
                    hit = True
                else:
                    hit = self._rng.random() < rule.value
                if not hit:
                    continue
                rule.fired += 1
                self.stats[point] = self.stats.get(point, 0) + 1
            _M_FAULTS.inc()
            # flight-recorder breadcrumb: with FLAGS_telemetry_dump_dir
            # set, the first firing per point leaves a dump artifact
            # (tools/fault_matrix.py asserts it per injected-fault run)
            try:
                from paddle_tpu.observability import flight
                flight.note_fault(point)
            except Exception:
                pass
            if rule.action == "delay":
                time.sleep(rule.value)
            elif rule.action == "drop":
                raise InjectedFault(point, "drop", retryable=True)
            else:
                raise InjectedFault(point, "error", retryable=False)

    def maybe_corrupt(self, point, round_, arr):
        """Return ``arr``, NaN-poisoned when a ``corrupt`` rule for
        ``point`` names sync round ``round_`` (and has firings left).
        The poison is written into a COPY — the caller's buffer (which
        the round-replay cache may alias) is never mutated in place by
        the injector itself; the copy is what gets cached and shipped,
        so retries/replays of the poisoned round stay bit-identical."""
        import numpy as np

        for rule in self.rules:
            if rule.point != point or rule.action != "corrupt":
                continue
            with self._lock:
                if rule.limit and rule.fired >= rule.limit:
                    continue
                if int(rule.value) != int(round_):
                    continue
                if not np.issubdtype(
                        np.asarray(arr).dtype, np.floating):
                    continue
                rule.fired += 1
                self.stats[point] = self.stats.get(point, 0) + 1
            _M_FAULTS.inc()
            try:
                from paddle_tpu.observability import flight
                flight.note_fault("%s:corrupt" % point)
            except Exception:
                pass
            poisoned = np.array(np.asarray(arr), copy=True)
            poisoned.reshape(-1)[:1] = np.nan
            return poisoned
        return arr


def maybe_corrupt(point, round_, arr):
    """Module-level hook mirroring fault_point(): a no-op unless a
    corrupt rule is installed."""
    inj = get_injector()
    if inj.rules:
        return inj.maybe_corrupt(point, round_, arr)
    return arr


_injector = None
_injector_lock = threading.Lock()  # rawlock: ok - module singleton wiring, set up before any mode flip


def get_injector():
    global _injector
    if _injector is None:
        with _injector_lock:
            if _injector is None:
                _injector = FaultInjector.from_env()
    return _injector


def install_faults(spec, seed=None):
    """Replace the process-wide injector (tests).  Returns it."""
    global _injector
    with _injector_lock:
        _injector = FaultInjector(spec, seed=seed)
    return _injector


def fault_point(name):
    """Injection hook — a no-op unless FLAGS_fault_spec names ``name``."""
    inj = get_injector()
    if inj.rules:
        inj.fire(name)


class EndpointResolver:
    """Map a logical pserver endpoint to its current physical endpoint.

    A restarted pserver re-registers in discovery.EndpointRegistry under
    the same shard id (PADDLE_PSERVER_ID, default: its endpoint string),
    possibly on a new port; the stale entry ages out by TTL.  The
    resolver snapshots logical-endpoint -> shard at construction and
    re-reads the registry per resolve."""

    def __init__(self, registry, kind="pserver", logical_eps=None):
        self.registry = registry
        self.kind = kind
        self._shard_of = {}
        for ep, meta in registry.list_meta(kind):
            self._shard_of[ep] = (meta or {}).get("shard", ep)
        for ep in logical_eps or []:
            self._shard_of.setdefault(ep, ep)

    def resolve(self, logical_ep):
        """Current endpoint serving logical_ep's shard, or None when the
        shard has no live registration right now."""
        shard = self._shard_of.get(logical_ep, logical_ep)
        for ep, meta in self.registry.list_meta(self.kind):
            if (meta or {}).get("shard", ep) == shard:
                return ep
        return None


def watchdog_error(op_name, endpoints, status_fn, cause=None):
    """Build a WatchdogTimeout naming what each pserver is waiting on.

    ``status_fn(ep)`` -> the server's BarrierStatus dict (best-effort;
    an unreachable server is reported as such rather than masking the
    timeout)."""
    details = []
    for ep in endpoints:
        try:
            st = status_fn(ep)
            missing = st.get("waiting_for") or []
            unseen = st["alive"] - len(st.get("known", [])) \
                if "alive" in st else 0
            part = ("%s: round=%s barriers=%s/%s"
                    % (ep, st.get("applied_round"), st.get("barriers"),
                       st.get("alive")))
            if missing:
                part += " waiting on %s" % missing
            if unseen > 0:
                part += " (+%d trainer(s) never connected)" % unseen
            details.append(part)
        except Exception as e:
            details.append("%s: unreachable (%s)" % (ep, e))
    msg = ("%s watchdog: distributed %s exceeded its deadline instead of "
           "hanging; per-pserver barrier state: %s"
           % (op_name, op_name, "; ".join(details) or "<none>"))
    if cause is not None:
        msg += " | cause: %s" % cause
    # flight recorder: the who-was-waiting-on-whom artifact — blocked
    # op + per-pserver barrier state + every thread's open span stack
    # (observability/flight.py); its path rides the error message so
    # the dump is findable from the raising process's log alone
    flight_path = None
    try:
        from paddle_tpu.observability import flight
        from paddle_tpu.observability.trace import TRACER
        # dump only when observability is opted into (a dump dir is
        # configured, or tracing is on — then flight.py falls back to
        # the temp dir so a real hang's artifact is never lost).
        # Ordinary test runs constructing WatchdogTimeouts with neither
        # must not litter /tmp (same guard rationale as note_fault).
        if FLAGS.telemetry_dump_dir or TRACER.on:
            flight_path = flight.dump(
                "watchdog:%s" % op_name,
                blocked={"op": op_name, "endpoints": list(endpoints),
                         "details": details})
    except Exception:
        flight_path = None
    if flight_path:
        msg += " | flight recorder: %s" % flight_path
    err = WatchdogTimeout(msg)
    err.details = details
    err.flight_path = flight_path
    return err
