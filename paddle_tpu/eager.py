"""Eager (define-by-run) execution with a gradient tape.

Parity: reference paddle/contrib/tape/ (tape.h:41 Tape, variable.h,
function.h) — the reference's experimental imperative mode that records
ops while executing them and pops the tape for backward.

TPU-native redesign: eager ops execute the SAME registered lowerings as
the graph executor, immediately, on concrete jax arrays; the tape
records (op_type, inputs, attrs, outputs).  ``Tape.backward`` replays
the recording as a pure function of the watched leaves and gets every
gradient from one jax.vjp — so eager mode needs no per-op grad
definitions, and a replayed tape can even be jitted.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.lowering import Ins, LoweringContext
from paddle_tpu.core.registry import get_op_info

__all__ = ["Variable", "Tape", "default_tape",
           "reset_default_tape", "op", "fc_like"]


class Variable:
    """Eager value wrapper (reference contrib/tape/variable.h)."""

    __slots__ = ("value", "name", "trainable", "grad")

    _counter = [0]

    def __init__(self, value, name=None, trainable=False):
        self.value = jnp.asarray(value)
        Variable._counter[0] += 1
        self.name = name or ("var_%d" % Variable._counter[0])
        self.trainable = trainable
        self.grad = None

    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    def numpy(self):
        return np.asarray(self.value)

    def __repr__(self):
        return "eager.Variable(%s, shape=%s)" % (self.name, self.shape)


class _Record:
    __slots__ = ("op_type", "ins", "attrs", "outs")

    def __init__(self, op_type, ins, attrs, outs):
        self.op_type = op_type    # str
        self.ins = ins            # slot -> [Variable|None]
        self.attrs = attrs
        self.outs = outs          # slot -> [Variable]


class Tape:
    """Records eager ops; backward() differentiates the whole recording
    (reference tape.h pops the tape op-by-op; one vjp subsumes that)."""

    def __init__(self, seed=0):
        self.records = []
        self._stopped = False
        self._seed = seed
        self._live_counter = None  # advances across run_op calls

    # -- recording --
    def _ctx(self, env=None, counter=None):
        from paddle_tpu.core.desc import ProgramDesc
        from paddle_tpu.core.lowering import _Counter

        ctx = LoweringContext(ProgramDesc(), 0, env or {},
                              jax.random.PRNGKey(self._seed), "train",
                              counter=counter or _Counter())
        return ctx

    def stop_recording(self):
        """Context manager: ops inside execute but are not taped
        (the no_grad analog)."""
        import contextlib

        @contextlib.contextmanager
        def guard():
            prev = self._stopped
            self._stopped = True
            try:
                yield
            finally:
                self._stopped = prev

        return guard()

    def run_op(self, op_type, ins, attrs=None):
        """Execute one registered op eagerly; ins: slot -> Variable or
        [Variable].  Returns slot -> Variable (or [Variable])."""
        from paddle_tpu.core.lowering import _Counter

        info = get_op_info(op_type)
        norm = {}
        for slot, vs in ins.items():
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            norm[slot] = [v for v in vs]
        raw = {s: [None if v is None else v.value for v in vs]
               for s, vs in norm.items()}
        # one counter for the tape's whole life: stochastic ops (dropout,
        # uniform_random) get a fresh key per call, and replay (which
        # restarts the counter from 0) reproduces the same key sequence
        if self._live_counter is None:
            self._live_counter = _Counter()
        outs = info.lower(self._ctx(counter=self._live_counter),
                          Ins(raw), dict(attrs or {}), None)
        out_vars = {}
        for slot, vals in (outs or {}).items():
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            out_vars[slot] = [None if v is None else Variable(v)
                              for v in vals]
        if not self._stopped:
            self.records.append(_Record(op_type, norm, dict(attrs or {}),
                                        out_vars))
        return {s: (vs[0] if len(vs) == 1 else vs)
                for s, vs in out_vars.items()}

    # -- autodiff --
    def backward(self, loss):
        """Populate .grad of every trainable Variable reachable from the
        recording, d loss / d leaf."""
        leaves = []
        seen = set()
        for rec in self.records:
            for vs in rec.ins.values():
                for v in vs:
                    if v is not None and v.trainable and \
                            id(v) not in seen:
                        seen.add(id(v))
                        leaves.append(v)
        if not leaves:
            return []
        recorded_out_ids = {id(v) for rec in self.records
                            for vs in rec.outs.values()
                            for v in vs if v is not None}
        if id(loss) not in recorded_out_ids:
            raise ValueError(
                "loss %r is not an output of any op recorded on this "
                "tape (was it computed under stop_recording(), on a "
                "different tape, or is it a leaf?)" % loss.name)

        def replay(leaf_vals):
            from paddle_tpu.core.lowering import _Counter

            # one counter across the replay: stochastic ops reproduce
            # the recording's key sequence (NB: ops executed under
            # stop_recording consume live keys but are not replayed, so
            # mixing stochastic ops with stop_recording shifts keys)
            counter = _Counter()
            val_of = {id(v): x for v, x in zip(leaves, leaf_vals)}

            def get(v):
                return val_of.get(id(v), v.value)

            for rec in self.records:
                raw = {s: [None if v is None else get(v) for v in vs]
                       for s, vs in rec.ins.items()}
                outs = get_op_info(rec.op_type).lower(
                    self._ctx(counter=counter), Ins(raw),
                    dict(rec.attrs), None)
                for slot, vals in (outs or {}).items():
                    vals = (vals if isinstance(vals, (list, tuple))
                            else [vals])
                    for ov, x in zip(rec.outs[slot], vals):
                        if ov is not None:
                            val_of[id(ov)] = x
            return val_of[id(loss)].sum()

        grads = jax.grad(replay)([v.value for v in leaves])
        for v, g in zip(leaves, grads):
            v.grad = g
        return list(zip(leaves, grads))

    def reset(self):
        self.records = []
        # restart the key counter with the records: replay always counts
        # from 0, so a live counter that kept running would desync
        # stochastic ops recorded after the reset
        self._live_counter = None


_default = Tape()


def default_tape():
    return _default


def reset_default_tape():
    """Drop the default tape's history (it grows without bound
    otherwise: records pin their arrays and backward() replays the
    whole history).  Training loops should prefer one fresh Tape per
    step, like the reference tape's pop-on-backward."""
    _default.reset()


def op(op_type, ins, attrs=None, tape=None):
    """Module-level eager op call on the default tape.  NB: the default
    tape records forever — call reset_default_tape() between steps, or
    pass a per-step Tape."""
    return (tape or _default).run_op(op_type, ins, attrs)


def fc_like(x, w, b=None, tape=None):
    """Convenience: mul (+ bias) on the tape — the contrib/tape demo's
    Linear function (function.h)."""
    t = tape or _default
    out = t.run_op("mul", {"X": x, "Y": w},
                   {"x_num_col_dims": 1, "y_num_col_dims": 1})["Out"]
    if b is not None:
        out = t.run_op("elementwise_add", {"X": out, "Y": b})["Out"]
    return out
