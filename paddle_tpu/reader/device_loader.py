"""Device-side data loading: batching + double-buffered host->device
staging.

Role parity: reference operators/reader/ (BatchReader,
create_double_buffer_reader_op.cc, blocking_queue.h) — the C++ decorated
-reader chain that overlaps input copy with compute.  TPU-native design:
a background thread calls ``jax.device_put`` (async on TPU) on upcoming
batches so transfers ride the interconnect while XLA executes the
current step; the bounded queue is the blocking-queue analog.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["batch", "DeviceLoader"]


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists of ``batch_size`` (reference
    python/paddle/batch.py; drop_last=True is the reference default —
    and the right one here, where a ragged tail batch would trigger an
    XLA recompile).  Samples may be tuples (fields stay parallel)."""

    def batched():
        b = []
        for s in reader():
            b.append(s)
            if len(b) >= batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched


class DeviceLoader:
    """Iterate device-resident feed dicts, ``capacity`` batches ahead.

    feed_list: var names (or Variables) matching each sample field.
    Samples are field tuples; batches (lists of samples) are stacked
    per-field with np.stack before staging.
    """

    def __init__(self, reader, feed_list, place, capacity=2):
        self.reader = reader
        self.names = [getattr(v, "name", v) for v in feed_list]
        self.place = place
        self.capacity = max(1, int(capacity))

    def _stack(self, samples):
        fields = list(zip(*samples))
        if len(fields) != len(self.names):
            raise ValueError(
                "sample has %d fields but feed_list names %d" %
                (len(fields), len(self.names)))
        return {n: np.stack([np.asarray(x) for x in f])
                for n, f in zip(self.names, fields)}

    def __iter__(self):
        import jax

        dev = self.place.jax_device()
        end = object()
        q = queue.Queue(maxsize=self.capacity)
        stop = threading.Event()

        def put(item):
            # bounded put that gives up when the consumer went away, so
            # an abandoned iterator doesn't pin a thread + `capacity`
            # device-staged batches forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for samples in self.reader():
                    host = self._stack(samples)
                    # async H2D: on TPU device_put returns immediately
                    # and the copy overlaps the running step
                    if not put({k: jax.device_put(v, dev)
                                for k, v in host.items()}):
                        return
            finally:
                put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is end:
                    return
                yield item
        finally:
            stop.set()
            while True:  # drop staged batches so buffers free promptly
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
