"""Device-side data loading: batching + double-buffered host->device
staging, plus an HBM dataset cache for datasets that fit on device.

Role parity: reference operators/reader/ (BatchReader,
create_double_buffer_reader_op.cc, blocking_queue.h) — the C++ decorated
-reader chain that overlaps input copy with compute.  TPU-native design:
a background thread calls ``jax.device_put`` (async on TPU) on upcoming
batches so transfers ride the interconnect while XLA executes the
current step; the bounded queue is the blocking-queue analog.

``DeviceDatasetCache`` is the small-dataset fast path: the whole dataset
is staged to device HBM once, and every epoch is served as device-side
gathers under a jitted per-epoch random permutation — zero per-step
host->device traffic (the tf.data ``cache()``-on-accelerator idiom).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["batch", "DeviceLoader", "DeviceDatasetCache",
           "DatasetExceedsBudget"]


class DatasetExceedsBudget(ValueError):
    """Dataset won't fit the DeviceDatasetCache byte budget — stream it
    through DeviceLoader instead."""


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists of ``batch_size`` (reference
    python/paddle/batch.py; drop_last=True is the reference default —
    and the right one here, where a ragged tail batch would trigger an
    XLA recompile).  Samples may be tuples (fields stay parallel)."""

    def batched():
        b = []
        for s in reader():
            b.append(s)
            if len(b) >= batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched


class DeviceLoader:
    """Iterate device-resident feed dicts, ``capacity`` batches ahead.

    feed_list: var names (or Variables) matching each sample field.
    Samples are field tuples; batches (lists of samples) are stacked
    per-field with np.stack before staging.
    """

    def __init__(self, reader, feed_list, place, capacity=2):
        self.reader = reader
        self.names = [getattr(v, "name", v) for v in feed_list]
        self.place = place
        self.capacity = max(1, int(capacity))

    def _stack(self, samples):
        fields = list(zip(*samples))
        if len(fields) != len(self.names):
            raise ValueError(
                "sample has %d fields but feed_list names %d" %
                (len(fields), len(self.names)))
        return {n: np.stack([np.asarray(x) for x in f])
                for n, f in zip(self.names, fields)}

    def __iter__(self):
        import jax

        dev = self.place.jax_device()
        end = object()
        q = queue.Queue(maxsize=self.capacity)
        stop = threading.Event()

        def put(item):
            # bounded put that gives up when the consumer went away, so
            # an abandoned iterator doesn't pin a thread + `capacity`
            # device-staged batches forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for samples in self.reader():
                    host = self._stack(samples)
                    # async H2D: on TPU device_put returns immediately
                    # and the copy overlaps the running step
                    if not put({k: jax.device_put(v, dev)
                                for k, v in host.items()}):
                        return
            finally:
                put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        empty = queue.Empty  # bind now: module globals go away first at
        try:                 # interpreter shutdown
            while True:
                item = q.get()
                if item is end:
                    return
                yield item
        finally:
            stop.set()
            while True:  # drop staged batches so buffers free promptly
                try:
                    q.get_nowait()
                except empty:
                    break


class DeviceDatasetCache:
    """Serve device-resident shuffled batches from an HBM-cached dataset.

    For datasets that fit in device memory, streaming every batch over
    the host link each epoch is pure waste — the whole dataset is staged
    once, and each epoch is a device-side gather under a fresh
    ``jax.random.permutation`` keyed by (seed, epoch): zero per-step
    host->device traffic and reshuffling identical in distribution to a
    full-buffer host shuffle.  Iteration yields {name: device_array}
    feed dicts, batch-major, ``floor(n / batch_size)`` per epoch
    (drop_last, matching the reference BatchReader default here).

    ``max_bytes`` guards the HBM budget: building the cache raises
    ``DatasetExceedsBudget`` as soon as the running sample-byte total
    crosses it — before the dataset is fully materialized on the host —
    so callers can fall back to the streaming ``DeviceLoader``.
    """

    def __init__(self, reader, feed_list, place, batch_size, seed=0,
                 max_bytes=4 << 30):
        import jax

        self.names = [getattr(v, "name", v) for v in feed_list]
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        samples = []
        total = 0
        for s in reader():
            samples.append(s)
            total += sum(np.asarray(x).nbytes for x in s)
            if total > max_bytes:
                raise DatasetExceedsBudget(
                    "dataset exceeds max_bytes=%d after %d samples — use "
                    "the streaming DeviceLoader" % (max_bytes,
                                                    len(samples)))
        if not samples:
            raise ValueError("reader yielded no samples")
        fields = list(zip(*samples))
        if len(fields) != len(self.names):
            raise ValueError(
                "sample has %d fields but feed_list names %d" %
                (len(fields), len(self.names)))
        host = [np.stack([np.asarray(x) for x in f]) for f in fields]
        self.n = host[0].shape[0]
        if self.n < self.batch_size:
            raise ValueError("dataset smaller than one batch (%d < %d)"
                             % (self.n, self.batch_size))
        dev = place.jax_device()
        self._cache = [jax.device_put(a, dev) for a in host]
        for a in self._cache:
            a.block_until_ready()
        n, bs = self.n, self.batch_size

        def gather(cache, epoch, k):
            key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
            perm = jax.random.permutation(key, n)
            idx = jax.lax.dynamic_slice_in_dim(perm, k * bs, bs)
            return [jax.numpy.take(c, idx, axis=0) for c in cache]

        # epoch/k ride in as traced scalars — one compile serves every
        # (epoch, batch) pair; outputs land on dev via the committed cache
        self._gather = jax.jit(gather)
        self._epoch = 0

    def __iter__(self):
        epoch = self._epoch
        self._epoch += 1
        for k in range(self.n // self.batch_size):
            out = self._gather(self._cache, epoch, k)
            yield dict(zip(self.names, out))
