"""Reader decorators.

Parity: reference python/paddle/reader/decorator.py:29-330 (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers).  Fresh
implementations on queues/threads; same composition semantics.
"""
from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "cache", "ComposeNotAligned"]


def map_readers(func, *readers):
    """Zip several readers and map ``func`` over the sample tuples
    (reference decorator.py:29)."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Pool ``buf_size`` samples, yield them in random order
    (reference decorator.py:51)."""

    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return shuffled


def chain(*readers):
    """Concatenate readers back to back (reference decorator.py:86)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, (b, c)) -> (a, b, c)
    (reference decorator.py:118).  check_alignment=True raises
    ComposeNotAligned when one reader is exhausted early."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError("unexpected kwargs %r" % list(kwargs))

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        sentinel = object()
        for outputs in itertools.zip_longest(*rs, fillvalue=sentinel):
            if sentinel in outputs:
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Background thread keeps up to ``size`` samples ready (reference
    decorator.py:165) — decouples producer and consumer speed."""

    end = object()

    def readers():
        q = queue.Queue(maxsize=size)

        def produce():
            try:
                for s in reader():
                    q.put(s)
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                return
            yield s

    return readers


def firstn(reader, n):
    """Limit to the first ``n`` samples (reference decorator.py:208)."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    """Materialize once, replay from memory on later epochs."""
    all_data = []
    filled = []

    def cached():
        if not filled:
            for s in reader():
                all_data.append(s)
                yield s
            filled.append(True)
        else:
            for s in all_data:
                yield s

    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map ``mapper`` over samples with ``process_num`` worker threads
    (reference decorator.py:236).  order=True preserves input order."""

    end = object()

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            for i, s in enumerate(reader()):
                in_q.put((i, s))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                out_q.put((i, mapper(s)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
            return
        pending = {}
        next_i = 0
        while finished < process_num or pending:
            if next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
                continue
            if finished == process_num:
                # producers done and the next index never arrived
                break
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            i, mapped = item
            pending[i] = mapped

    return xreader


class PipeReader:
    """Stream records from a shell command's stdout (reference
    reader/decorator.py:337 — the HDFS/S3/curl ingestion path).  Plain
    or gzip streams; ``get_line`` yields decoded lines."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import subprocess
        import zlib

        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type %s is not allowed" % file_type)
        if file_type == "gzip":
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.file_type = file_type
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE)

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs

        # incremental decode: a multi-byte utf-8 char may straddle a
        # read-chunk boundary (the reference decodes per chunk and
        # crashes on that)
        decoder = codecs.getincrementaldecoder("utf-8")()
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if not buff:
                tail = b""
                if self.file_type == "gzip":
                    # drain the decompressor: bytes still buffered in
                    # zlib (or a trailing partial member) would be
                    # silently dropped otherwise
                    tail = self.dec.flush()
                decomp_buff = decoder.decode(tail, final=True)
            elif self.file_type == "gzip":
                decomp_buff = decoder.decode(self.dec.decompress(buff))
            else:
                decomp_buff = decoder.decode(buff)
            if decomp_buff:
                if not cut_lines:
                    yield decomp_buff
                else:
                    lines = (remained + decomp_buff).split(line_break)
                    remained = lines.pop(-1)
                    for line in lines:
                        yield line
            if not buff:
                break
        if remained:
            yield remained
        # reap the child and surface failures: a dead `hadoop fs -cat`
        # must not masquerade as an empty dataset
        rc = self.process.wait()
        if rc != 0:
            raise RuntimeError(
                "PipeReader command exited with status %d" % rc)
