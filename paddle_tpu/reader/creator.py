"""Reader creators (parity: python/paddle/reader/creator.py — np_array,
text_file, recordio)."""
from __future__ import annotations

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Reader over the first axis of a numpy array."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    """Reader yielding stripped lines of a text file."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, deserializer=None):
    """Reader over recordio file(s) (reference creator.py:60 uses the
    recordio scanner; ours is paddle_tpu.recordio).  ``deserializer``
    maps raw record bytes to a sample (default: raw bytes)."""
    from paddle_tpu import recordio as rio

    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        for p in paths:
            for rec in rio.read_records(p):
                yield deserializer(rec) if deserializer else rec

    return reader
