"""Data readers: a reader is a zero-arg callable returning an iterable of
samples (parity: python/paddle/reader/__init__.py docs).  Decorators
compose readers; creators build them from arrays/files."""
from .decorator import (map_readers, shuffle, chain, compose, buffered,
                        firstn, xmap_readers, cache,
                        ComposeNotAligned, PipeReader)  # noqa: F401
from . import creator  # noqa: F401
from .device_loader import (DatasetExceedsBudget,  # noqa: F401
                            DeviceDatasetCache, DeviceLoader, batch)

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "cache", "ComposeNotAligned", "PipeReader",
           "creator", "DeviceLoader", "DeviceDatasetCache",
           "DatasetExceedsBudget", "batch"]
