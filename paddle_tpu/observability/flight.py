"""Flight recorder: turn a hang or a kill into a JSON artifact.

The last N spans live in the tracer's ring and metrics are always on —
this module is the DUMP path: on a watchdog timeout, a bench
wall-budget expiry, an injected fault, or SIGTERM/SIGALRM, write one
JSON file naming

- the blocked operation and the peers it was waiting on (the caller
  passes the watchdog's per-pserver barrier state),
- every thread's currently-open span stack (who is blocked where),
- the recent completed spans and the full metrics snapshot.

So the next dead-tunnel hang produces a who-was-waiting-on-whom report
instead of the r05 bench's bare ``rc:124`` (ROADMAP "Evidence state").

Dumps land in ``FLAGS_telemetry_dump_dir`` when set, else the system
temp dir; the writer never raises (a diagnostic must not sink the
operation it is diagnosing).
"""
from __future__ import annotations

import json
import os
import signal as _signal
import tempfile
import threading
import time

from paddle_tpu.core.flags import FLAGS

from .trace import TRACER

__all__ = ["dump", "note_fault", "install_signal_handlers",
           "SCHEMA_VERSION"]

# Envelope version (ISSUE 13 satellite): the artifact is parsed by
# tools/fault_matrix.py, tools/watchtower.py, tools/trace_report.py
# and the scale/slo preset asserts — PR 12 embedded the ledger with no
# versioning and downstream parsers would break silently on shape
# changes.  Bump this WITH a tests/test_flight_schema.py golden update
# whenever a top-level key is added/removed/renamed.
SCHEMA_VERSION = 1

# keep the artifact bounded even with a huge ring configured
MAX_RECENT_SPANS = 1024
MAX_LEDGER_SAMPLES = 256

# RLock, same reasoning as metrics.py: a signal-handler dump (SIGTERM
# arriving during a SIGALRM dump, both on the main thread) must not
# self-deadlock inside its own hang diagnostic.  Sanitizer-adopted
# (ISSUE 14): make_lock(signal_safe=True) records — and under
# FLAGS_sanitizer=locks enforces — exactly that invariant.
from paddle_tpu.core.sanitizer import make_lock

_seq_lock = make_lock("flight.seq", reentrant=True, signal_safe=True)
_seq = 0
_noted_faults = set()


def _next_seq():
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def dump(reason, blocked=None, directory=None, sections=None):
    """Write the flight-recorder artifact; returns its path, or None if
    the write failed (best-effort by design).  ``blocked`` is a
    JSON-able dict describing what the process was stuck on — e.g.
    {"op": "recv", "details": [per-pserver barrier state...]}.
    ``sections`` lets the caller enrich/override a top-level envelope
    section (the SLO engine embeds the offending series under "slo");
    envelope keys are pinned by tests/test_flight_schema.py."""
    try:
        directory = (directory or FLAGS.telemetry_dump_dir
                     or tempfile.gettempdir())
        os.makedirs(directory, exist_ok=True)
        from . import metrics
        spans = TRACER.completed(limit=MAX_RECENT_SPANS)
        # resource-ledger snapshot (ISSUE 12): current per-subsystem
        # values + the newest time-series slice, so a collapse
        # artifact shows the resource curve INTO the failure.  Best
        # effort like everything else here.
        try:
            from . import ledger as _ledger
            ledger_snap = _ledger.snapshot(limit=MAX_LEDGER_SAMPLES)
        except Exception:
            ledger_snap = None
        # SLO status (ISSUE 13): spec table + active burn-rate alerts
        # when an evaluator is installed; the key is present either
        # way so parsers never branch on existence
        try:
            from . import slo as _slo
            slo_snap = _slo.snapshot_for_flight()
        except Exception:
            slo_snap = None
        rec = {
            "kind": "flight_recorder",
            "schema_version": SCHEMA_VERSION,
            "reason": str(reason),
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "label": TRACER.label or "",
            "telemetry_on": TRACER.on,
            "blocked": blocked,
            "open_spans": TRACER.open_spans(),
            "recent_spans": spans,
            "metrics": metrics.snapshot(),
            "ledger": ledger_snap,
            "slo": slo_snap,
        }
        if sections:
            rec.update(sections)
        path = os.path.join(
            directory, "flight_%d_%d.json" % (os.getpid(), _next_seq()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def note_fault(point):
    """Injected-fault hook (resilience.FaultInjector.fire): dump once
    per fault point per process, and ONLY when a dump dir is explicitly
    configured — tools/fault_matrix.py asserts the artifact exists
    after each injected-fault run, while ordinary fault tests don't
    litter the temp dir."""
    if not FLAGS.telemetry_dump_dir or point in _noted_faults:
        return None
    _noted_faults.add(point)
    return dump("fault:%s" % point, blocked={"fault_point": point})


def install_signal_handlers(signals=("SIGTERM", "SIGALRM")):
    """Chain a flight dump onto the named signals' existing handlers
    (previous handler still runs; SIG_DFL is re-raised so the process
    still dies).  Main-thread only; returns the installed signal names.
    """
    installed = []
    for name in signals:
        signum = getattr(_signal, name, None)
        if signum is None:
            continue
        try:
            prev = _signal.getsignal(signum)

            def _handler(sn, frame, _prev=prev, _name=name):
                dump("signal:%s" % _name)
                if callable(_prev):
                    _prev(sn, frame)
                elif _prev != _signal.SIG_IGN:
                    # SIG_DFL, or None (handler installed outside
                    # Python, uncallable from here): restore the
                    # default action and re-deliver so the process
                    # still dies — swallowing a fatal signal would
                    # reproduce the hang class this module diagnoses
                    _signal.signal(sn, _signal.SIG_DFL)
                    os.kill(os.getpid(), sn)

            _signal.signal(signum, _handler)
            installed.append(name)
        except (ValueError, OSError):
            pass  # non-main thread or unsupported signal
    return installed
