"""Per-subsystem resource ledgers (ISSUE 12): the "how big is it
RIGHT NOW" half of the observability layer.

Metrics answer "how many / how long"; traces answer "where did the
time go".  Neither answers the question a 256-trainer collapse poses:
*which bounded-in-theory data structure was growing when the protocol
fell over* — the pserver's per-(round, sender) pending grads, the
reply/replay caches, the live-sender barrier quorum, the apply
worker's backlog, a hier leader's fan-in buffers, the fastwire socket
population.  This module is that answer:

- **Probes.**  A subsystem registers a cheap callable returning
  ``{resource_name: number}`` — O(1) reads of byte/entry counters the
  subsystem maintains incrementally on its own hot path (rpc.py,
  hierarchy.py, fastwire.py).  Probes may be tied to an ``owner``
  object via weakref so a dead server/client drops out of the ledger
  without an explicit unregister.
- **Collector.**  One daemon thread samples every probe at
  ``FLAGS_ledger_sample_ms`` (0 disables), sums same-named resources
  across probes, mirrors each value into an always-on ``ledger_<name>``
  gauge (so every metrics snapshot — trace dumps, flight dumps,
  Prometheus text — carries the latest ledger row), and appends the
  sample to a bounded time-series ring (``FLAGS_ledger_ring``).
- **Forensics.**  Every flight-recorder dump embeds
  :func:`snapshot` — current values plus the newest ring slice — so a
  collapse artifact shows the resource *curve into* the failure, not
  just the final state.  ``FLAGS_ledger_watch`` ("resource>value"
  terms) turns the collector into a tripwire: the first sample past a
  threshold writes one flight dump per resource (reason
  ``ledger:<resource>``), which is how tools/scale_bench.py pins each
  driven collapse mode to evidence.

Cost: the collector touches the ledger a few times a second; nothing
here runs on a training/serving hot path (the incremental counters
the probes read are maintained by their subsystems at per-event
cadence, same budget class as the always-on metrics).  Gated < 2% by
tools/telemetry_overhead.py like the trace/metrics/numerics gates.
"""
from __future__ import annotations

import threading
import time
import weakref

from paddle_tpu.core.flags import FLAGS

from . import metrics as _metrics

__all__ = ["register", "unregister", "collect", "sample_now",
           "snapshot", "peaks", "series", "reset", "value_nbytes",
           "has_probes"]

from paddle_tpu.core.sanitizer import make_lock

# reentrant + signal-safe: flight.dump embeds ledger.snapshot() from
# signal handlers (sanitizer-adopted, ISSUE 14)
_lock = make_lock("ledger.registry", reentrant=True, signal_safe=True)
_probes = {}          # handle -> (subsystem, fn, owner_ref or None)
_last_rows = {}       # handle -> last successful probe row
_next_handle = 0
_ring = None          # deque of {"t", "values"}; built lazily
_gauges = {}          # resource -> Gauge (registry-backed)
_tripped = set()      # ledger-watch resources already dumped
_collector = None     # the sampling thread, started lazily


def value_nbytes(v):
    """Byte footprint of one wire/pending value: dense ndarray,
    SelectedRows (rows + values), or a post-codec Compressed frame
    (whose own ``.nbytes`` property sums its codec arrays).  The ONE
    definition the incremental byte ledgers in rpc.py / hierarchy.py
    share."""
    rows = getattr(v, "rows", None)
    if rows is not None and hasattr(v, "values"):   # SelectedRows
        return (int(getattr(rows, "nbytes", 0))
                + int(getattr(v.values, "nbytes", 0)))
    return int(getattr(v, "nbytes", 0))


def register(subsystem, probe, owner=None):
    """Register ``probe`` (callable -> {resource: number}).  With
    ``owner``, the registration lives exactly as long as the owner
    object (weakref) and ``probe`` is called as ``probe(owner)`` — the
    natural form for a per-instance method (``Cls._ledger_probe``).
    Returns an opaque handle for :func:`unregister`."""
    global _next_handle
    with _lock:
        _next_handle += 1
        handle = _next_handle
        ref = weakref.ref(owner) if owner is not None else None
        _probes[handle] = (str(subsystem), probe, ref)
    _ensure_collector()
    return handle


def unregister(handle):
    with _lock:
        _probes.pop(handle, None)
        _last_rows.pop(handle, None)


def has_probes():
    """True when any probe is registered — the cheap predicate
    callers (tsdb.sample_registry) use to decide whether a ledger
    refresh would do anything."""
    with _lock:
        return bool(_probes)


def collect():
    """One ledger row: every live probe read, same-named resources
    SUMMED across probes (two servers in one test process report their
    combined pending bytes).  A probe that RAISES serves its last
    successful row instead — the lock-free probes can lose a race
    with a dict resize exactly when the subsystem is busiest, and a
    zeroed sample at that moment would make a collapse look idle.
    Only a dead owner (weakref cleared) truly drops out."""
    with _lock:
        entries = list(_probes.items())
    values = {}
    dead = []
    for handle, (_sub, fn, ref) in entries:
        try:
            if ref is not None:
                obj = ref()
                if obj is None:
                    dead.append(handle)
                    continue
                row = fn(obj)
            else:
                row = fn()
            _last_rows[handle] = dict(row or {})
        except Exception:
            row = _last_rows.get(handle)
        for name, v in (row or {}).items():
            values[name] = values.get(name, 0) + v
    if dead:
        with _lock:
            for h in dead:
                _probes.pop(h, None)
                _last_rows.pop(h, None)
    return values


def _get_ring():
    global _ring
    if _ring is None:
        from collections import deque
        with _lock:
            if _ring is None:
                _ring = deque(maxlen=max(1, int(FLAGS.ledger_ring)))
    return _ring


def sample_now():
    """Force one collector iteration: collect, mirror into gauges,
    append to the ring, and fire any ledger-watch tripwires.  Returns
    the sampled values (the collector thread calls this on cadence;
    tests and dump paths call it directly)."""
    values = collect()
    for name, v in values.items():
        g = _gauges.get(name)
        if g is None:
            g = _gauges[name] = _metrics.gauge(
                "ledger_" + name, "resource ledger: " + name)
        g.set(v)
    # a resource whose probe died (server stopped, client collected)
    # must read 0, not freeze at its last value — a later flight dump
    # would otherwise attribute a collapse to a subsystem that no
    # longer exists
    for name, g in _gauges.items():
        if name not in values:
            g.set(0)
    _get_ring().append({"t": round(time.time(), 3),
                        "values": values})
    _check_watch(values)
    return values


def _parse_watch():
    out = []
    for term in str(FLAGS.ledger_watch or "").split(","):
        term = term.strip()
        if ">" not in term:
            continue
        name, thr = term.split(">", 1)
        try:
            out.append((name.strip(), float(thr)))
        except ValueError:
            continue
    return out


def _check_watch(values):
    watches = _parse_watch()
    if not watches:
        return
    for name, thr in watches:
        if name in _tripped or values.get(name, 0) <= thr:
            continue
        _tripped.add(name)
        try:
            from . import flight
            flight.dump("ledger:%s" % name,
                        blocked={"resource": name,
                                 "value": values.get(name, 0),
                                 "threshold": thr})
        except Exception:
            pass


def snapshot(limit=256):
    """The flight-recorder payload: fresh probe values plus the newest
    ``limit`` ring samples (the curve INTO the failure)."""
    try:
        values = sample_now()
    except Exception:
        values = {}
    ring = list(_get_ring())
    if limit is not None and len(ring) > int(limit):
        ring = ring[-int(limit):]
    return {"resources": values, "series": ring}


def series():
    """The full retained time-series (newest last)."""
    return list(_get_ring())


def peaks():
    """Max per resource over the retained series (+ the current
    values) — the per-sweep-point resource curve tools/scale_bench.py
    charts against trainer count."""
    out = {}
    for row in list(_get_ring()):
        for name, v in row["values"].items():
            if v > out.get(name, float("-inf")):
                out[name] = v
    return out


def _ensure_collector():
    global _collector
    if _collector is not None or int(FLAGS.ledger_sample_ms) <= 0:
        return
    with _lock:
        if _collector is not None:
            return
        t = threading.Thread(target=_collect_loop, daemon=True,
                             name="ledger-collector")
        _collector = t
        t.start()


def _collect_loop():
    while True:
        ms = int(FLAGS.ledger_sample_ms)
        if ms <= 0:
            time.sleep(0.25)
            continue
        time.sleep(ms / 1000.0)
        with _lock:
            empty = not _probes
        if empty:
            continue
        try:
            sample_now()
        except Exception:
            pass


def reset():
    """Drop probes, ring, and tripwire state (tests).  The collector
    thread, once started, survives — it idles on an empty registry."""
    global _ring
    with _lock:
        _probes.clear()
        _last_rows.clear()
        _tripped.clear()
        _ring = None
