"""Watchtower time-series store (ISSUE 13 tentpole a): durable metric
history, dependency-free.

Everything before this was point-in-time: the always-on registry
(metrics.py) answers "how many / how long *right now*", the ledger
collector answers "how big is it right now", and both evaporate with
the process.  This module is the durable half — an on-disk store a
sampler appends fixed-interval snapshots of every counter, gauge and
histogram-percentile into, so an SLO can be evaluated over a window
(slo.py), an overhead gate's history survives the tool run, and a
collapse can be read back hours later.

On-disk format (an internal contract — MIGRATION.md "Watchtower"):

- one directory per writer process (two processes never share a
  segment file; ``default_store()`` keys the subdirectory by
  label + pid the way flight dumps are keyed),
- ``tsdb_meta.json``: the series name -> integer id map plus the
  sealed-segment index (t0/t1/records per segment), rewritten
  atomically via core/fsutil only when it changes (new series, seal),
- ``seg_NNNNNN.bin``: append-only fixed-width binary frames, 20 bytes
  each — ``<u4 series_id | f8 unix_time | f8 value>`` little-endian —
  chosen so a whole segment reads as ONE numpy structured array
  (mmap-friendly, no parsing): a torn tail (crash mid-frame) truncates
  to the last whole record,
- rotation: the active segment seals at ``FLAGS_tsdb_segment_bytes``
  and a new one opens; retention drops the OLDEST sealed segments once
  the directory exceeds ``FLAGS_tsdb_retention_mb``.

Query API: ``scan`` (range read), ``downsample`` (bucketed
mean/min/max for sparklines), ``rate`` (counter rate with reset
handling), ``latest``.  Readers re-stat the files per call, so a
reader process sees a live writer's appends without coordination.

Sampler: ``sample_registry(store)`` appends one row per metric —
counters/gauges as themselves, histograms as ``name.count``,
``name.sum`` and ``name.p50/.p90/.p99`` — after refreshing the ISSUE
12 resource ledger (whose ``ledger_*`` gauges then ride the same row).
``ensure_sampler()`` starts the background thread when
``FLAGS_tsdb_dir`` is set; it is called best-effort from the trainer
loop, the serving server and the RPC plane, so any instrumented
process with the flag set retains its history.  Cost is gated < 2% by
tools/telemetry_overhead.py like every other telemetry site.
"""
from __future__ import annotations

import atexit
import json
import os
import struct
import threading
import time

import numpy as np

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.fsutil import atomic_write

__all__ = ["TSDB", "RECORD", "sample_registry", "default_store",
           "ensure_sampler", "stop_sampler", "open_stores",
           "series_values"]

# one frame: series id, unix time, value.  '<' = packed little-endian
# (no padding), so itemsize is exactly 20 and numpy reads a segment
# zero-copy with the matching structured dtype.
RECORD = struct.Struct("<Idd")
_DTYPE = np.dtype([("sid", "<u4"), ("t", "<f8"), ("v", "<f8")])
META_NAME = "tsdb_meta.json"
META_VERSION = 1


class TSDB:
    """One process's time-series store over one directory.

    Writer methods (``append``/``append_row``) and reader methods
    (``scan``/``rate``/``downsample``) coexist; a read-only open
    (``create=False``) of another process's live directory re-loads
    the meta per query so new series resolve."""

    def __init__(self, directory, segment_bytes=None,
                 retention_bytes=None, create=True):
        self.dir = str(directory)
        self.segment_bytes = int(segment_bytes
                                 or FLAGS.tsdb_segment_bytes)
        self.retention_bytes = int(
            retention_bytes
            if retention_bytes is not None
            else FLAGS.tsdb_retention_mb * (1 << 20))
        from paddle_tpu.core.sanitizer import make_lock
        self._lock = make_lock("tsdb.store", reentrant=True)
        self._series = {}            # name -> sid
        self._segments = []          # sealed: {file, records, t0, t1}
        # parsed-array cache for SEALED segments (immutable once
        # sealed, so (file, size) fully keys the content): bounds
        # repeated window queries — the SLO evaluator re-scans every
        # tick — to one disk read + parse per segment, not per query.
        # Small LRU (newest segments are what window queries hit).
        self._seg_cache = {}         # file -> (size, array)
        self._seg_cache_max = 8
        self._active = None          # {file, t0, t1}
        self._next_seg = 1
        self._fh = None
        self._meta_dirty = False
        self._writable = bool(create)
        meta_path = os.path.join(self.dir, META_NAME)
        if os.path.exists(meta_path):
            self._load_meta()
        elif create:
            os.makedirs(self.dir, exist_ok=True)
            self._open_segment()
            self._write_meta()
        else:
            raise FileNotFoundError("no %s under %r" % (META_NAME,
                                                        self.dir))

    # -- meta ----------------------------------------------------------
    def _load_meta(self):
        with open(os.path.join(self.dir, META_NAME)) as f:
            meta = json.load(f)
        if int(meta.get("version", 0)) != META_VERSION:
            raise ValueError("tsdb meta version %r (want %d) under %r"
                             % (meta.get("version"), META_VERSION,
                                self.dir))
        self._series = {k: int(v) for k, v in meta["series"].items()}
        self._segments = list(meta.get("segments", []))
        self._active = meta.get("active")
        self._next_seg = int(meta.get("next_seg", 1))

    def _write_meta(self):
        meta = {"version": META_VERSION, "record_bytes": RECORD.size,
                "series": self._series, "segments": self._segments,
                "active": self._active, "next_seg": self._next_seg}
        atomic_write(os.path.join(self.dir, META_NAME),
                     json.dumps(meta))
        self._meta_dirty = False

    def _maybe_reload(self):
        """Read-only opens follow a live writer: re-load the meta so
        series/segments added since open() resolve."""
        if not self._writable:
            try:
                self._load_meta()
            except Exception:
                pass

    # -- write path ----------------------------------------------------
    def _open_segment(self):
        name = "seg_%06d.bin" % self._next_seg
        self._next_seg += 1
        self._active = {"file": name, "t0": None, "t1": None}
        if self._fh is not None:
            self._fh.close()
        self._fh = open(os.path.join(self.dir, name), "ab")
        self._meta_dirty = True

    def _sid(self, name):
        sid = self._series.get(name)
        if sid is None:
            sid = self._series[name] = len(self._series)
            self._meta_dirty = True
        return sid

    def append(self, name, value, t=None):
        self.append_row({name: value}, t=t)

    def append_row(self, values, t=None):
        """Append one timestamped row of ``{series: value}`` samples;
        flushes so live readers see it, seals/rotates when the active
        segment crosses the size bound."""
        if not values:
            return
        t = float(time.time() if t is None else t)
        with self._lock:
            if self._fh is None:
                if not self._writable:
                    raise IOError("read-only tsdb %r" % self.dir)
                self._fh = open(os.path.join(self.dir,
                                             self._active["file"]),
                                "ab")
            buf = b"".join(
                RECORD.pack(self._sid(n), t, float(v))
                for n, v in values.items()
                if v is not None and np.isfinite(float(v)))
            if not buf:
                return
            self._fh.write(buf)
            self._fh.flush()
            if self._active["t0"] is None:
                self._active["t0"] = t
            self._active["t1"] = t
            if self._meta_dirty:
                self._write_meta()
            if self._fh.tell() >= self.segment_bytes:
                self._seal_locked()

    def _seal_locked(self):
        self._fh.flush()
        size = self._fh.tell()
        self._segments.append({
            "file": self._active["file"],
            "records": size // RECORD.size,
            "t0": self._active["t0"], "t1": self._active["t1"]})
        self._open_segment()
        self._enforce_retention_locked()
        self._write_meta()

    def _enforce_retention_locked(self):
        """Drop the OLDEST sealed segments until total bytes fit the
        retention bound (the active segment always survives)."""
        if self.retention_bytes <= 0:
            return
        total = sum(s["records"] * RECORD.size for s in self._segments)
        while self._segments and total > self.retention_bytes:
            victim = self._segments.pop(0)
            total -= victim["records"] * RECORD.size
            self._seg_cache.pop(victim["file"], None)
            try:
                os.remove(os.path.join(self.dir, victim["file"]))
            except OSError:
                pass
            self._meta_dirty = True

    def flush(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            if self._meta_dirty:
                self._write_meta()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
            # persist the active segment's final bounds: a reopened
            # store's meta must know how far the last session reached
            # (writers only — a read-only view must never clobber the
            # live writer's meta)
            if self._writable:
                self._write_meta()

    # -- read path -----------------------------------------------------
    def names(self):
        self._maybe_reload()
        with self._lock:
            return sorted(self._series)

    def total_bytes(self):
        with self._lock:
            files = [s["file"] for s in self._segments]
            if self._active:
                files.append(self._active["file"])
        total = 0
        for f in files:
            try:
                total += os.path.getsize(os.path.join(self.dir, f))
            except OSError:
                pass
        return total

    def _segment_array(self, fname, sealed=False):
        """One segment as a structured array; a torn tail truncates to
        the last whole record (crash-mid-frame is data loss of one
        sample, never a parse error).  Sealed segments are served from
        the parsed-array cache — their bytes never change."""
        path = os.path.join(self.dir, fname)
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        if sealed:
            with self._lock:
                hit = self._seg_cache.get(fname)
                if hit is not None and hit[0] == size:
                    return hit[1]
        n = size // RECORD.size
        if n == 0:
            return None
        with open(path, "rb") as f:
            raw = f.read(n * RECORD.size)
        arr = np.frombuffer(raw, dtype=_DTYPE,
                            count=len(raw) // RECORD.size)
        if sealed:
            with self._lock:
                while len(self._seg_cache) >= self._seg_cache_max:
                    self._seg_cache.pop(next(iter(self._seg_cache)))
                self._seg_cache[fname] = (size, arr)
        return arr

    def _iter_arrays(self, t0, t1):
        self._maybe_reload()
        with self._lock:
            sealed = list(self._segments)
            active = dict(self._active) if self._active else None
        for seg in sealed:
            if t0 is not None and seg["t1"] is not None \
                    and seg["t1"] < t0:
                continue
            if t1 is not None and seg["t0"] is not None \
                    and seg["t0"] > t1:
                continue
            arr = self._segment_array(seg["file"], sealed=True)
            if arr is not None:
                yield arr
        if active:
            arr = self._segment_array(active["file"])
            if arr is not None:
                yield arr

    def scan(self, name, t0=None, t1=None):
        """(times, values) float64 arrays for ``name`` over [t0, t1],
        time-ordered.  Unknown series -> empty arrays."""
        self._maybe_reload()
        with self._lock:
            sid = self._series.get(name)
        if sid is None:
            return (np.empty(0), np.empty(0))
        ts, vs = [], []
        for arr in self._iter_arrays(t0, t1):
            mask = arr["sid"] == sid
            if t0 is not None:
                mask &= arr["t"] >= t0
            if t1 is not None:
                mask &= arr["t"] <= t1
            if mask.any():
                ts.append(arr["t"][mask])
                vs.append(arr["v"][mask])
        if not ts:
            return (np.empty(0), np.empty(0))
        t = np.concatenate(ts)
        v = np.concatenate(vs)
        order = np.argsort(t, kind="stable")
        return (t[order], v[order])

    def last_time(self):
        """Newest sample timestamp across ALL series, or None for an
        empty store — the post-hoc anchor watchtower evaluates
        windows at.  Sealed bounds come from the meta; the active
        segment's tail is read from the file itself (its meta bound
        is only as fresh as the last meta rewrite — a crashed or
        still-live writer leaves it stale)."""
        self._maybe_reload()
        with self._lock:
            times = [s["t1"] for s in self._segments
                     if s.get("t1") is not None]
            active = dict(self._active) if self._active else None
        if active:
            arr = self._segment_array(active["file"])
            if arr is not None and len(arr):
                times.append(float(arr["t"].max()))
            elif active.get("t1") is not None:
                times.append(active["t1"])
        return max(times) if times else None

    def latest(self, name):
        """(t, value) of the newest sample, or None."""
        t, v = self.scan(name)
        if len(t) == 0:
            return None
        return (float(t[-1]), float(v[-1]))

    def rate(self, name, t0=None, t1=None):
        """Per-second rate of a cumulative counter over the window:
        sum of POSITIVE deltas / elapsed (a negative delta is a counter
        reset — the decrease is discarded, Prometheus-style)."""
        t, v = self.scan(name, t0, t1)
        if len(t) < 2 or t[-1] <= t[0]:
            return 0.0
        deltas = np.diff(v)
        return float(deltas[deltas > 0].sum() / (t[-1] - t[0]))

    def downsample(self, name, buckets=60, t0=None, t1=None):
        """Bucketed rollup for sparkline rows: [{t, mean, min, max,
        count}] over up to ``buckets`` equal time bins (empty bins are
        skipped)."""
        t, v = self.scan(name, t0, t1)
        if len(t) == 0:
            return []
        lo = float(t[0]) if t0 is None else float(t0)
        hi = float(t[-1]) if t1 is None else float(t1)
        if hi <= lo:
            return [{"t": lo, "mean": float(v[-1]),
                     "min": float(v.min()), "max": float(v.max()),
                     "count": int(len(v))}]
        edges = np.linspace(lo, hi, int(buckets) + 1)
        idx = np.clip(np.searchsorted(edges, t, side="right") - 1,
                      0, int(buckets) - 1)
        out = []
        for b in range(int(buckets)):
            mask = idx == b
            if not mask.any():
                continue
            vb = v[mask]
            out.append({"t": float((edges[b] + edges[b + 1]) / 2),
                        "mean": float(vb.mean()),
                        "min": float(vb.min()),
                        "max": float(vb.max()),
                        "count": int(mask.sum())})
        return out


# ---------------------------------------------------------------------
# registry sampler
# ---------------------------------------------------------------------

def sample_registry(store, t=None):
    """Append one snapshot row of the whole always-on registry:
    counters/gauges as themselves; histograms decomposed into
    ``.count``/``.sum`` (cumulative — ``rate()`` works on them) and
    the recent-window ``.p50/.p90/.p99``.  The ISSUE 12 ledger is
    refreshed first (when any probe is registered) so its ``ledger_*``
    gauges ride the same row.  Returns the number of series written."""
    from . import ledger as _ledger
    from . import metrics as _metrics

    try:
        if _ledger.has_probes():
            _ledger.sample_now()
    except Exception:
        pass
    row = {}
    snap = _metrics.snapshot()
    for name, m in snap.items():
        kind = m.get("type")
        if kind == "histogram":
            row[name + ".count"] = m.get("count", 0)
            row[name + ".sum"] = m.get("sum", 0.0)
            for p in ("p50", "p90", "p99"):
                row[name + "." + p] = m.get(p, 0.0)
        else:
            row[name] = m.get("value", 0)
    store.append_row(row, t=t)
    return len(row)


def series_values(store, metric, t0=None, t1=None):
    """Resolve an SLO-style metric name against a store: a plain name
    scans the series; ``<counter>.rate`` evaluates the per-interval
    rate between consecutive samples (resets clamp to 0).  Returns
    (times, values)."""
    if metric.endswith(".rate"):
        t, v = store.scan(metric[:-len(".rate")], t0, t1)
        if len(t) < 2:
            return (np.empty(0), np.empty(0))
        dt = np.diff(t)
        dv = np.diff(v)
        good = dt > 0
        rates = np.where(dv > 0, dv, 0.0)[good] / dt[good]
        return (t[1:][good], rates)
    return store.scan(metric, t0, t1)


# ---------------------------------------------------------------------
# per-process default store + background sampler
# ---------------------------------------------------------------------

_default = None
from paddle_tpu.core.sanitizer import make_lock as _make_lock
_default_lock = _make_lock("tsdb.default")
_sampler = None
_sampler_stop = None


def _safe_label():
    from .trace import TRACER, _default_label
    label = TRACER.label or _default_label()
    return "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in label)


def default_store(create=True):
    """The process's own store under FLAGS_tsdb_dir — one
    subdirectory per (label, pid), because segment files are
    single-writer (flight dumps are keyed the same way).  None when
    the flag is unset."""
    global _default
    root = FLAGS.tsdb_dir
    if not root:
        return None
    root_abs = os.path.abspath(root)
    with _default_lock:
        if _default is not None \
                and os.path.dirname(_default.dir) != root_abs:
            # the root moved (tests, reconfiguration): close the old
            # store cleanly and build a fresh one under the new root
            try:
                _default.close()
            except Exception:
                pass
            _default = None
        if _default is None:
            d = os.path.join(root_abs,
                             "%s_%d" % (_safe_label(), os.getpid()))
            _default = TSDB(d, create=create)
            atexit.register(_default.close)
        return _default


def open_stores(root):
    """Read-only open of every per-process store under ``root`` (or of
    ``root`` itself when it is a single store).  Returns
    {label_dirname: TSDB} — the query side of the per-process layout."""
    root = str(root)
    if os.path.exists(os.path.join(root, META_NAME)):
        return {os.path.basename(root.rstrip("/")) or root:
                TSDB(root, create=False)}
    out = {}
    try:
        children = sorted(os.listdir(root))
    except OSError:
        return out
    for child in children:
        d = os.path.join(root, child)
        if os.path.exists(os.path.join(d, META_NAME)):
            try:
                out[child] = TSDB(d, create=False)
            except Exception:
                continue
    return out


def ensure_sampler():
    """Start the background registry sampler once per process when
    FLAGS_tsdb_dir is set (interval FLAGS_tsdb_sample_ms; 0 disables).
    Best-effort and idempotent — instrumented subsystems (trainer
    loop, serving server, RPC plane) call this at init so any process
    with the flag set retains its metric history.  Also arms the SLO
    evaluator (slo.ensure_evaluator) — the two run as one plane."""
    global _sampler, _sampler_stop
    if not FLAGS.tsdb_dir or int(FLAGS.tsdb_sample_ms) <= 0:
        return None
    with _default_lock:
        if _sampler is not None:
            return _sampler
    store = default_store()
    if store is None:
        return None
    with _default_lock:
        if _sampler is not None:
            return _sampler
        _sampler_stop = threading.Event()
        t = threading.Thread(target=_sample_loop,
                             args=(store, _sampler_stop),
                             daemon=True, name="tsdb-sampler")
        _sampler = t
        t.start()
    # one FINAL sample at interpreter exit (runs before the store's
    # own atexit close — LIFO): a short-lived worker's last counter
    # increments land in the store even when the process exits inside
    # a sampling interval
    atexit.register(_final_sample, store)
    try:
        from . import slo as _slo
        _slo.ensure_evaluator()
    except Exception:
        pass
    return _sampler


def _final_sample(store):
    try:
        if store._fh is not None:   # not already closed
            sample_registry(store)
    except Exception:
        pass


def _sample_loop(store, stop):
    while not stop.is_set():
        ms = int(FLAGS.tsdb_sample_ms)
        if stop.wait(max(ms, 10) / 1000.0):
            break
        try:
            sample_registry(store)
        except Exception:
            pass


def stop_sampler():
    """Stop the background sampler and forget the default store
    (tests)."""
    global _sampler, _sampler_stop, _default
    with _default_lock:
        stop, _sampler, _sampler_stop = _sampler_stop, None, None
        store, _default = _default, None
    if stop is not None:
        stop.set()
    if store is not None:
        try:
            store.close()
        except Exception:
            pass
