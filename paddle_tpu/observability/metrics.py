"""Always-on process metrics: counters, gauges, histograms.

Unlike tracing (gated by ``FLAGS_telemetry``), metrics stay on in
production: an update is one short lock + a few arithmetic ops, paid at
per-step / per-round cadence — never per byte.  Hot paths cache the
metric object at module level (registry lookup happens once, at
import).

Exports:
- ``prometheus_text()``: the Prometheus text exposition format (scrape
  it from a debug endpoint or dump it to a file);
- ``snapshot()``: one JSON-able dict of every metric — rides the trace
  dumps and the flight recorder, and bench.py sources its
  ``step_ms_p50/p90/p99`` fields from histogram snapshots.

Histogram percentiles are computed over a bounded reservoir of the most
recent observations (default 4096) — exact for short benches, a
recent-window estimate for long runs; the cumulative bucket counts are
exact forever.

Per-metric locks are REENTRANT (threading.RLock): the flight recorder
(observability/flight.py) snapshots every metric from SIGNAL handlers
(SIGTERM, the bench's SIGALRM wall budget), and a signal landing on the
very thread that is mid-``observe`` must read through the held lock
instead of deadlocking on it — a torn in-flight update in a crash dump
is acceptable; a diagnostic that hangs the process is not.
"""
from __future__ import annotations

import bisect
import re
import threading
from collections import deque

# lock-sanitizer adoption (ISSUE 14): every metric lock is created
# through make_lock — a plain threading lock in production, an
# instrumented acquisition-order-recording lock under
# FLAGS_sanitizer=locks|all.  signal_safe documents (and, under the
# sanitizer, enforces) the REENTRANT invariant explained above.
from paddle_tpu.core.sanitizer import make_lock

__all__ = ["counter", "gauge", "histogram", "snapshot",
           "prometheus_text", "zero_all", "Counter", "Gauge",
           "Histogram", "nearest_rank"]


def nearest_rank(sorted_vals, p):
    """Nearest-rank percentile (p in [0, 100]) over an already-sorted
    list; 0.0 when empty.  The ONE percentile definition shared by
    Histogram.percentile/.snapshot and export.phase_rows — keep them
    answering the same number for the same data."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]

_REGISTRY = {}
# reentrant: see the signal note above
_REG_LOCK = make_lock("metrics.registry", reentrant=True,
                      signal_safe=True)

# latency-oriented default bounds, in ms (also fine for counts/bytes
# at small scale; pass explicit bounds otherwise)
DEFAULT_BOUNDS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                  100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)

RESERVOIR = 4096


class Counter:
    __slots__ = ("name", "help", "_v", "_lock")

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._v = 0
        self._lock = make_lock("metrics.counter.%s" % name,
                               reentrant=True, signal_safe=True)

    def inc(self, v=1):
        with self._lock:
            self._v += v

    @property
    def value(self):
        return self._v

    def zero(self):
        with self._lock:
            self._v = 0

    def snapshot(self):
        return {"type": "counter", "value": self._v}


class Gauge:
    __slots__ = ("name", "help", "_v", "_lock")

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._v = 0.0
        self._lock = make_lock("metrics.gauge.%s" % name,
                               reentrant=True, signal_safe=True)

    def set(self, v):
        with self._lock:
            self._v = v

    def inc(self, v=1):
        with self._lock:
            self._v += v

    @property
    def value(self):
        return self._v

    def zero(self):
        with self._lock:
            self._v = 0.0

    def snapshot(self):
        return {"type": "gauge", "value": self._v}


class Histogram:
    __slots__ = ("name", "help", "bounds", "_counts", "_sum", "_n",
                 "_recent", "_lock")

    kind = "histogram"

    def __init__(self, name, help="", bounds=None):
        self.name = name
        self.help = help
        self.bounds = tuple(bounds or DEFAULT_BOUNDS)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = +Inf
        self._sum = 0.0
        self._n = 0
        self._recent = deque(maxlen=RESERVOIR)
        self._lock = make_lock("metrics.histogram.%s" % name,
                               reentrant=True, signal_safe=True)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._sum += v
            self._n += 1
            self._recent.append(v)

    @property
    def count(self):
        return self._n

    @property
    def sum(self):
        return self._sum

    def percentile(self, p):
        """p in [0, 100], over the recent-observation reservoir (exact
        when fewer than RESERVOIR observations were made)."""
        with self._lock:
            vals = sorted(self._recent)
        return nearest_rank(vals, p)

    def zero(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._n = 0
            self._recent.clear()

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._n
            vals = sorted(self._recent)

        return {"type": "histogram", "count": n, "sum": round(s, 6),
                "mean": round(s / n, 6) if n else 0.0,
                "p50": nearest_rank(vals, 50),
                "p90": nearest_rank(vals, 90),
                "p99": nearest_rank(vals, 99),
                "buckets": {("%g" % b): c
                            for b, c in zip(self.bounds, counts)},
                "inf": counts[-1]}


def _get(name, cls, help, **kw):
    with _REG_LOCK:
        m = _REGISTRY.get(name)
        if m is None:
            m = _REGISTRY[name] = cls(name, help, **kw)
        elif not isinstance(m, cls):
            raise TypeError("metric %r already registered as %s"
                            % (name, type(m).__name__))
        return m


def counter(name, help=""):
    return _get(name, Counter, help)


def gauge(name, help=""):
    return _get(name, Gauge, help)


def histogram(name, help="", bounds=None):
    return _get(name, Histogram, help, bounds=bounds)


def snapshot():
    """{name: metric snapshot} over every registered metric."""
    with _REG_LOCK:
        items = sorted(_REGISTRY.items())
    return {name: m.snapshot() for name, m in items}


def zero_all():
    """Reset every metric's VALUE in place (tests; registered objects —
    and the module-level references hot paths cache — stay valid)."""
    with _REG_LOCK:
        items = list(_REGISTRY.values())
    for m in items:
        m.zero()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _pname(name):
    return _NAME_RE.sub("_", name)


def _pnum(v):
    """Full-precision exposition value: '%g' would silently round to 6
    significant digits — the byte counters cross 1e6 within seconds and
    a monotonic counter must never appear frozen between scrapes."""
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text():
    """Prometheus text exposition format over every metric."""
    with _REG_LOCK:
        items = sorted(_REGISTRY.items())
    out = []
    for name, m in items:
        pn = _pname(name)
        if m.help:
            out.append("# HELP %s %s" % (pn, m.help))
        out.append("# TYPE %s %s" % (pn, m.kind))
        if isinstance(m, Histogram):
            snap = m.snapshot()
            acc = 0
            for b in m.bounds:
                acc += snap["buckets"]["%g" % b]
                out.append('%s_bucket{le="%g"} %d' % (pn, b, acc))
            acc += snap["inf"]
            out.append('%s_bucket{le="+Inf"} %d' % (pn, acc))
            out.append("%s_sum %s" % (pn, _pnum(snap["sum"])))
            out.append("%s_count %d" % (pn, snap["count"]))
        else:
            out.append("%s %s" % (pn, _pnum(m.value)))
    return "\n".join(out) + "\n"
