"""Step-scoped span tracing: the host half of the telemetry layer.

Role parity: reference platform/profiler.h RAII host events +
tools/timeline.py chrome-trace export, rebuilt as one process-wide
tracer the executor, RPC, fastwire and kernel layers emit into (the
reference scattered RecordEvent through operator.cc and the gRPC
client; here the instrumented sites are named in ISSUE 6).

Design constraints:

- **Disabled cost is one attribute read.**  Hot paths guard every
  begin/end behind ``TRACER.on`` (a plain bool), so with
  ``FLAGS_telemetry`` off the executor step allocates nothing and never
  reads a clock — tools/telemetry_overhead.py gates this at < 2% of the
  prepared hot path.
- **Completed spans land in a bounded ring** (``collections.deque`` with
  maxlen — append is GIL-atomic, so the record path takes no lock),
  sized by ``FLAGS_telemetry_ring_size``.  The same ring is the flight
  recorder's history (observability/flight.py).
- **Open spans are visible.**  Per-thread stacks register in a process
  map so a hang dump can name the span every thread is blocked in —
  the who-was-waiting-on-whom report a dead-tunnel rc:124 never gave.
- **Cross-process correlation.**  Distributed spans carry a correlation
  id built from the wire's (round, sender, seq) identity
  (``round_cid``); a merged trace (observability/export.py) lines
  trainer and pserver timelines up by it.
- **Mergeable clocks.**  Timestamps are monotonic perf_counter_ns with
  a wall-clock anchor captured at tracer init; dumps convert to
  absolute microseconds, so traces from different processes share one
  timeline (chrome://tracing renders them side by side).
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from collections import deque

from paddle_tpu.core.flags import FLAGS

__all__ = ["TRACER", "Tracer", "Span", "round_cid", "traced",
           "disabled_step_probe"]


def round_cid(round_):
    """Correlation id for one sync round: every span of that round —
    trainer send/barrier/get AND pserver scatter/apply — carries the
    same id, so a merged trace correlates them across processes.  The
    finer (sender, seq) identity rides the span's args."""
    return "round:%d" % int(round_)


class Span:
    """One host event.  ``t1 == 0`` means still open (the flight
    recorder reports such spans as where a thread is blocked)."""

    __slots__ = ("name", "t0", "t1", "tid", "cid", "args", "depth")

    def __init__(self, name, t0, tid, cid, args, depth):
        self.name = name
        self.t0 = t0
        self.t1 = 0
        self.tid = tid
        self.cid = cid
        self.args = args
        self.depth = depth


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopCtx()


class _SpanCtx:
    __slots__ = ("_tr", "_span")

    def __init__(self, tr, span):
        self._tr = tr
        self._span = span

    def __enter__(self):
        return self._span

    def __exit__(self, *exc):
        self._tr.end(self._span)
        return False


class Tracer:
    """Thread-safe span recorder.  One process-wide instance (TRACER);
    private instances exist only for tests."""

    def __init__(self, ring_size=None, enabled=None):
        self.on = bool(FLAGS.telemetry) if enabled is None else enabled
        self.label = None
        self._ring = deque(maxlen=int(ring_size
                                      or FLAGS.telemetry_ring_size))
        self._stacks = {}   # tid -> list of open spans (own-thread only)
        # wall anchor: dumps convert monotonic stamps to absolute µs so
        # per-process traces merge onto one timeline
        self._anchor_wall_ns = time.time_ns()
        self._anchor_perf_ns = time.perf_counter_ns()

    # -- lifecycle ----------------------------------------------------
    def enable(self):
        self.on = True

    def disable(self):
        self.on = False

    def configure(self, ring_size):
        """Resize the ring (keeps the newest spans)."""
        self._ring = deque(self._ring, maxlen=int(ring_size))

    def set_label(self, label, force=False):
        """Process label for dumps/merges (e.g. 'trainer0@host:123',
        'pserver@127.0.0.1:6174').  First writer wins unless forced."""
        if force or not self.label:
            self.label = str(label)

    def clear(self):
        """Drop completed spans.  Open-span stacks are NOT touched:
        they are owned by live threads (a profiler-session reset must
        not blank the flight recorder's who-is-blocked report, and a
        still-open span's end() pops its own stack).  Only stacks left
        empty by finished threads are pruned."""
        self._ring.clear()
        for tid, stack in list(self._stacks.items()):
            if not stack:
                self._stacks.pop(tid, None)

    # -- record path --------------------------------------------------
    def begin(self, name, cid=None, args=None):
        """Open a span.  ENABLED-path only: callers guard on ``.on`` so
        the disabled path never reaches here."""
        tid = threading.get_ident()
        stack = self._stacks.get(tid)
        if stack is None:
            stack = self._stacks[tid] = []
        span = Span(name, time.perf_counter_ns(), tid, cid, args,
                    len(stack))
        stack.append(span)
        return span

    def end(self, span, cid=None, args=None):
        """Close ``span`` and commit it to the ring.  Tolerates
        unbalanced nesting (an exception that unwound past un-ended
        children): the stack pops back to this span."""
        span.t1 = time.perf_counter_ns()
        if cid is not None:
            span.cid = cid
        if args:
            span.args = dict(span.args or (), **args)
        stack = self._stacks.get(span.tid)
        if stack:
            while stack:
                if stack.pop() is span:
                    break
        self._ring.append(span)

    def span(self, name, cid=None, args=None):
        """Context-manager form for non-hot paths (RPC rounds, kernel
        lowering).  Returns a shared no-op when tracing is off."""
        if not self.on:
            return _NOOP
        return _SpanCtx(self, self.begin(name, cid, args))

    # -- introspection ------------------------------------------------
    def wall_us(self, t_ns):
        return (self._anchor_wall_ns + (t_ns - self._anchor_perf_ns)) \
            / 1e3

    def _span_dict(self, s, now_ns=None):
        d = {"name": s.name, "ts_us": round(self.wall_us(s.t0), 3),
             "tid": s.tid, "depth": s.depth}
        if s.t1:
            d["dur_us"] = round((s.t1 - s.t0) / 1e3, 3)
        else:
            now_ns = now_ns or time.perf_counter_ns()
            d["open"] = True
            d["elapsed_us"] = round((now_ns - s.t0) / 1e3, 3)
        if s.cid is not None:
            d["cid"] = s.cid
        if s.args:
            d["args"] = dict(s.args)
        return d

    def completed(self, limit=None):
        """Snapshot of the ring, oldest first, as dicts.  ``limit``
        keeps only the newest N BEFORE dict conversion — the flight
        recorder dumps from signal handlers, where converting a
        100k-span ring to keep 1k would delay the very hang artifact
        it exists to produce."""
        spans = list(self._ring)
        if limit is not None and len(spans) > int(limit):
            spans = spans[-int(limit):]
        return [self._span_dict(s) for s in spans]

    def open_spans(self):
        """Every thread's currently-open span stack — the hang report:
        the deepest open span per thread is where it is blocked."""
        now = time.perf_counter_ns()
        out = []
        for stack in list(self._stacks.values()):
            for s in list(stack):
                if s.t1 == 0:
                    out.append(self._span_dict(s, now))
        out.sort(key=lambda d: d["ts_us"])
        return out

    # -- dumps --------------------------------------------------------
    def dump_dict(self):
        """The per-process trace artifact: identity + completed + open
        spans + an always-on metrics snapshot."""
        from . import metrics
        return {
            "label": self.label or _default_label(),
            "pid": os.getpid(),
            "spans": self.completed(),
            "open_spans": self.open_spans(),
            "metrics": metrics.snapshot(),
        }

    def dump(self, path):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.dump_dict(), f)
        return path

    def dump_if_configured(self):
        """Write <FLAGS_telemetry_dump_dir>/trace_<label>_<pid>.json
        when tracing is on and a dump dir is set; returns the path or
        None.  Registered atexit, and called explicitly by the dist
        worker helpers (multiprocessing fork children skip atexit)."""
        if not (self.on and FLAGS.telemetry_dump_dir):
            return None
        label = (self.label or _default_label())
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in label)
        path = os.path.join(FLAGS.telemetry_dump_dir,
                            "trace_%s_%d.json" % (safe, os.getpid()))
        try:
            return self.dump(path)
        except Exception:
            return None


def _default_label():
    role = os.environ.get("PADDLE_TRAINING_ROLE", "").lower()
    if role == "trainer":
        return "trainer%s" % os.environ.get("PADDLE_TRAINER_ID", "")
    if role == "pserver":
        return "pserver"
    return "proc"


def traced(name, args_fn=None):
    """Decorator form: span the whole call when tracing is on, a plain
    passthrough (one attribute read) when off.  ``args_fn(*a, **kw)``
    may build the span args lazily — it only runs when tracing is on,
    so the disabled path pays nothing.  Used at Pallas kernel launch
    sites: the span records the trace/lowering-time cost (inside jit,
    the launch itself happens on device, which utils/xplane.py covers).
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not TRACER.on:
                return fn(*a, **kw)
            span = TRACER.begin(
                name, None, args_fn(*a, **kw) if args_fn else None)
            try:
                return fn(*a, **kw)
            finally:
                TRACER.end(span)
        return wrapper
    return deco


TRACER = Tracer()


def _sync_on(v):
    TRACER.on = bool(v)


def _sync_ring(v):
    if TRACER._ring.maxlen != int(v):
        TRACER.configure(v)


# FLAGS.telemetry / telemetry_ring_size assigned at runtime propagate
# into the tracer (the hot-path check stays one attribute read; the
# watcher keeps a programmatic `FLAGS.telemetry = True` from being
# silently ignored).  enable()/disable() still work directly — the
# profiler session uses them without touching the flag.
FLAGS.watch("telemetry", _sync_on)
FLAGS.watch("telemetry_ring_size", _sync_ring)


def disabled_step_probe(n, _counter=None):
    """Replicate the per-step work the instrumented-but-DISABLED
    executor hot path adds — one guard read plus one always-on step
    counter increment per iteration — ``n`` times.  The overhead gate
    (tools/telemetry_overhead.py) times this loop, and
    tests/test_telemetry.py asserts it allocates nothing."""
    trc = TRACER
    if _counter is None:
        from . import metrics
        _counter = metrics.counter(
            "telemetry_probe_total",
            "iterations of the disabled-path overhead probe")
    inc = _counter.inc
    for _ in range(n):
        if trc.on:
            trc.end(trc.begin("probe"))
        inc()


atexit.register(TRACER.dump_if_configured)
