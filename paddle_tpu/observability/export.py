"""Trace export: merge per-process telemetry dumps (and optional
xplane device traces) into ONE chrome://tracing JSON, and reduce a
trace to a per-phase breakdown table.

The per-process dump (trace.Tracer.dump) stamps spans in absolute
wall-clock microseconds, so merging is pure concatenation: each process
becomes a chrome pid with its label as the process name, and spans of
the same sync round share a ``cid`` arg (trace.round_cid) — select one
in the viewer to see the trainer's send/barrier/get next to the
pserver's scatter/apply for that round.

Device traces: ``jax.profiler.trace`` captures convert through
utils/xplane.device_trace_events (XLine.timestamp_ns is unix-epoch
based, so device ops land on the same absolute timeline).
"""
from __future__ import annotations

import json
import os

from . import metrics

__all__ = ["load_dump", "chrome_trace", "merge_files", "phase_rows",
           "format_phase_table", "kernel_rows", "format_kernel_table",
           "numerics_rows", "format_numerics_table", "serve_rows",
           "format_serve_table", "scale_rows", "format_scale_table",
           "slo_rows", "format_slo_table", "weaver_rows",
           "format_weaver_table"]


def load_dump(path):
    with open(path) as f:
        data = json.load(f)
    if "traceEvents" in data and "spans" not in data:
        # already a chrome trace (e.g. a previous merge): adapt
        spans = []
        for ev in data["traceEvents"]:
            if ev.get("ph") != "X":
                continue
            # open-span markers carry elapsed-at-dump-time as their
            # duration; re-ingesting them as completed spans would let
            # a hung run's open barriers dominate the phase table.
            # Device events (an --xplane merge) are likewise excluded:
            # the original dumps never contained them, so the re-loaded
            # phase table must not be device-op-dominated either.
            if ev.get("cat") in ("open", "device") \
                    or (ev.get("args") or {}).get("open"):
                continue
            s = {"name": ev.get("name", "?"), "ts_us": ev.get("ts", 0),
                 "dur_us": ev.get("dur", 0), "tid": ev.get("tid", 0)}
            cid = (ev.get("args") or {}).get("cid")
            if cid:
                s["cid"] = cid
            spans.append(s)
        return {"label": os.path.basename(path), "pid": 0,
                "spans": spans, "open_spans": [], "metrics": {}}
    return data


def chrome_trace(dumps, device_events=None):
    """[per-process dump dicts] -> chrome trace dict.  ``device_events``
    is an optional pre-built list of chrome events (see
    utils/xplane.device_trace_events)."""
    events = []
    used_pids = set()
    for i, d in enumerate(dumps):
        # fallback pids sit above kernel.pid_max (4194304) so they
        # can't collide with another dump's real OS pid; an explicit
        # pid 0 (the profiler's single-process export) is honored
        pid = d["pid"] if d.get("pid") is not None else (9_000_000 + i)
        # multi-host merges can present the SAME os pid from different
        # machines — remap the later dump so each keeps its own chrome
        # track (and its own process_name label)
        while pid in used_pids:
            pid = 9_000_000 + i if pid < 9_000_000 else pid + 1
        used_pids.add(pid)
        label = d.get("label") or ("proc%d" % i)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        for s in d.get("spans", []):
            ev = {"name": s["name"], "ph": "X", "pid": pid,
                  "tid": s.get("tid", 0), "ts": s.get("ts_us", 0),
                  "dur": s.get("dur_us", 0), "cat": "host"}
            args = dict(s.get("args") or {})
            if s.get("cid"):
                args["cid"] = s["cid"]
            if args:
                ev["args"] = args
            events.append(ev)
        for s in d.get("open_spans", []):
            ev = {"name": s["name"] + " (open)", "ph": "X", "pid": pid,
                  "tid": s.get("tid", 0), "ts": s.get("ts_us", 0),
                  "dur": s.get("elapsed_us", 0), "cat": "open"}
            args = dict(s.get("args") or {})
            if s.get("cid"):
                args["cid"] = s["cid"]
            args["open"] = True
            ev["args"] = args
            events.append(ev)
    if device_events:
        events.extend(device_events)
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_files(paths, out_path=None, xplane=None):
    """Merge per-process dump files (+ an optional xplane capture dir)
    into one chrome trace; write it to ``out_path`` when given.
    Returns (trace_dict, dumps)."""
    dumps = [load_dump(p) for p in paths]
    device_events = None
    if xplane:
        from paddle_tpu.utils.xplane import device_trace_events
        device_events = device_trace_events(xplane)
    trace = chrome_trace(dumps, device_events)
    if out_path:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(trace, f)
    return trace, dumps


def phase_rows(dumps):
    """Aggregate span durations by name over per-process dumps:
    [{name, count, total_ms, mean_ms, p50_ms, p99_ms, share}] sorted by
    total time — the per-phase step-time breakdown."""
    groups = {}
    for d in dumps:
        for s in d.get("spans", []):
            dur = s.get("dur_us")
            if dur is None:
                continue
            groups.setdefault(s["name"], []).append(dur / 1e3)
    total = sum(sum(v) for v in groups.values()) or 1e-12
    rows = []
    for name, vals in groups.items():
        vals.sort()
        n = len(vals)
        rows.append({
            "name": name, "count": n,
            "total_ms": round(sum(vals), 3),
            "mean_ms": round(sum(vals) / n, 3),
            "p50_ms": round(metrics.nearest_rank(vals, 50), 3),
            "p99_ms": round(metrics.nearest_rank(vals, 99), 3),
            "share": round(sum(vals) / total, 4),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _kernel_group(name):
    """Normalize a device-op / launch-site name to its kernel family:
    'pallas.flash_attention' -> 'flash_attention',
    '%fusion.123' / 'fusion.4' -> 'fusion',
    'jit__matmul_kernel.12' -> 'jit__matmul_kernel' — so one row per
    kernel, not one per compiled instance."""
    import re

    if name.startswith("pallas."):
        name = name[len("pallas."):]
    name = name.lstrip("%")
    # strip compiled-instance suffixes ('.123') only — a bare trailing
    # digit is part of the op name ('exp2', 'atan2')
    name = re.sub(r"(\.\d+)+$", "", name)
    return name or "?"


def kernel_rows(dumps, trace=None):
    """Per-kernel rollup (ISSUE 7 satellite): Pallas launch-site spans
    (the ``pallas.*`` spans the kernels emit under FLAGS_telemetry)
    grouped by kernel name, merged with device-side events from an
    --xplane capture (cat 'device' in the merged chrome trace), so a
    fusion win is readable straight from a telemetry dump.  Returns
    [{kernel, side, count, total_ms, mean_ms, share}] sorted by total
    time; host and device entries stay separate rows ('side')."""
    groups = {}
    for d in dumps:
        for s in d.get("spans", []):
            if not s.get("name", "").startswith("pallas."):
                continue
            dur = s.get("dur_us")
            if dur is None:     # open span: no duration to roll up
                continue
            key = (_kernel_group(s["name"]), "host")
            groups.setdefault(key, []).append(dur / 1e3)
    for ev in (trace or {}).get("traceEvents", []):
        if ev.get("cat") != "device" or ev.get("ph") != "X":
            continue
        key = (_kernel_group(ev.get("name", "?")), "device")
        groups.setdefault(key, []).append((ev.get("dur") or 0) / 1e3)
    total = {side: sum(sum(v) for (k, s), v in groups.items()
                       if s == side) or 1e-12
             for side in ("host", "device")}
    rows = []
    for (kernel, side), vals in groups.items():
        rows.append({
            "kernel": kernel, "side": side, "count": len(vals),
            "total_ms": round(sum(vals), 3),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "share": round(sum(vals) / total[side], 4),
        })
    rows.sort(key=lambda r: (r["side"], -r["total_ms"]))
    return rows


def format_kernel_table(rows):
    out = ["%-40s %-7s %7s %10s %9s %7s" % (
        "kernel", "side", "count", "total_ms", "mean_ms", "share")]
    for r in rows:
        out.append("%-40s %-7s %7d %10.3f %9.3f %6.1f%%" % (
            r["kernel"][:40], r["side"], r["count"], r["total_ms"],
            r["mean_ms"], 100.0 * r["share"]))
    return "\n".join(out)


def numerics_rows(dumps):
    """Numerics-observatory rollup (ISSUE 8 satellite): per process
    dump, the training-health metrics the always-on registry carried —
    the gradient-norm distribution (trend over the run's recent
    window), parameter abs-max, nonfinite sightings and guard trips.
    Works on any trace dump (the metrics snapshot rides every one);
    processes that never observed a health read-back report zeros."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})
        gh = m.get("grad_global_norm", {})
        rows.append({
            "label": d.get("label", "?"),
            "checks": m.get("numerics_checks_total", {}).get("value", 0),
            "grad_norm_mean": round(gh.get("mean", 0.0), 6),
            "grad_norm_p50": round(gh.get("p50", 0.0), 6),
            "grad_norm_p90": round(gh.get("p90", 0.0), 6),
            "grad_norm_p99": round(gh.get("p99", 0.0), 6),
            "param_absmax": round(
                m.get("param_absmax", {}).get("value", 0.0), 6),
            "nonfinite": m.get("numerics_nonfinite_total",
                               {}).get("value", 0),
            "trips": m.get("numerics_trips_total", {}).get("value", 0),
            "pserver_nonfinite_grads": m.get(
                "pserver_nonfinite_grads_total", {}).get("value", 0),
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_numerics_table(rows):
    out = ["%-24s %7s %13s %12s %12s %12s %10s %6s" % (
        "process", "checks", "grad_norm_p50", "p90", "p99",
        "param_absmax", "nonfinite", "trips")]
    for r in rows:
        out.append("%-24s %7d %13.4g %12.4g %12.4g %12.4g %10d %6d" % (
            r["label"][:24], r["checks"], r["grad_norm_p50"],
            r["grad_norm_p90"], r["grad_norm_p99"], r["param_absmax"],
            r["nonfinite"], r["trips"]))
    return "\n".join(out)


def wire_rows(dumps):
    """Pserver wire/compression rollup (ISSUE 10 satellite): per
    process dump, the outbound grad bytes before/after the negotiated
    codec (equal when compression is off), the codec's encode-time
    distribution, fastwire socket traffic, and the bounded-staleness
    barrier spread.  Works on any trace dump — the always-on metrics
    snapshot rides every one."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        raw = val("wire_bytes_raw_total")
        comp = val("wire_bytes_compressed_total")
        ch = m.get("compress_ms", {})
        rows.append({
            "label": d.get("label", "?"),
            "grad_bytes_raw": raw,
            "grad_bytes_compressed": comp,
            "compression_ratio": round(raw / comp, 2) if comp else 1.0,
            "compress_ms_p50": round(ch.get("p50", 0.0), 3),
            "compress_ms_p99": round(ch.get("p99", 0.0), 3),
            "compress_count": ch.get("count", 0),
            "fastwire_tx": val("fastwire_bytes_sent_total"),
            "fastwire_rx": val("fastwire_bytes_recv_total"),
            "staleness_gap": val("pserver_staleness_gap"),
            "replays": val("rpc_round_replays_total"),
            "dedup_drops": val("pserver_dedup_drops_total"),
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_wire_table(rows):
    out = ["%-24s %12s %12s %6s %9s %9s %12s %12s %6s" % (
        "process", "grad_raw_B", "grad_wire_B", "ratio", "czip_p50",
        "czip_p99", "fastwire_tx", "fastwire_rx", "stale")]
    for r in rows:
        out.append("%-24s %12d %12d %6.2f %9.3f %9.3f %12d %12d %6d"
                   % (r["label"][:24], r["grad_bytes_raw"],
                      r["grad_bytes_compressed"],
                      r["compression_ratio"], r["compress_ms_p50"],
                      r["compress_ms_p99"], r["fastwire_tx"],
                      r["fastwire_rx"], r["staleness_gap"]))
    return "\n".join(out)


def serve_rows(dumps):
    """Serving-tier rollup (ISSUE 11 satellite): per process dump, the
    request/token plane — predict batches and occupancy, and the
    generative decode loop's tokens/TTFT/inter-token distributions with
    the paged KV cache pressure (blocks used/total, allocation
    failures, preemptions).  Works on any trace dump — the always-on
    metrics snapshot rides every one."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        def hist(name, field, default=0.0):
            return (m.get(name) or {}).get(field, default)

        slots = val("serve_decode_slots_total")
        pfx_tok = val("serve_prefix_tokens_total")
        pfx_cached = val("serve_prefix_tokens_cached_total")
        proposed = val("serve_spec_proposed_total")
        draft_us = val("serve_spec_draft_us_total")
        verify_us = val("serve_spec_verify_us_total")
        rows.append({
            "label": d.get("label", "?"),
            "requests": val("serve_requests_total"),
            "batches": val("serve_batches_total"),
            "gen_requests": val("serve_gen_requests_total"),
            "tokens": val("serve_tokens_total"),
            "prefills": val("serve_prefills_total"),
            "decode_steps": val("serve_decode_steps_total"),
            "decode_occupancy_pct": round(
                100.0 * val("serve_decode_rows_total") / slots, 1)
            if slots else 0.0,
            "ttft_p50_ms": round(hist("serve_ttft_ms", "p50"), 3),
            "ttft_p99_ms": round(hist("serve_ttft_ms", "p99"), 3),
            "itl_p50_ms": round(hist("serve_itl_ms", "p50"), 3),
            "itl_p99_ms": round(hist("serve_itl_ms", "p99"), 3),
            "kv_blocks_used": val("serve_kv_blocks_used"),
            "kv_blocks_total": val("serve_kv_blocks_total"),
            "kv_alloc_failures": val("serve_kv_alloc_failures_total"),
            "preemptions": val("serve_kv_preemptions_total"),
            # prefix cache + speculative decode (ISSUE 19)
            "prefix_hit_rate_pct": round(100.0 * pfx_cached / pfx_tok,
                                         1) if pfx_tok else 0.0,
            "blocks_shared": val("serve_kv_blocks_shared"),
            "cow_copies": val("serve_kv_cow_copies_total"),
            "spec_accept_rate": round(
                val("serve_spec_accepted_total") / proposed, 3)
            if proposed else 0.0,
            "draft_overhead_pct": round(
                100.0 * draft_us / (draft_us + verify_us), 1)
            if draft_us + verify_us else 0.0,
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_serve_table(rows):
    out = ["%-20s %7s %8s %8s %6s %9s %9s %8s %8s %9s %7s %8s "
           "%7s %6s %6s %7s" % (
               "process", "reqs", "tokens", "steps", "occ%", "ttft_p50",
               "ttft_p99", "itl_p50", "itl_p99", "kv_used", "allocF",
               "preempt", "pfxHit%", "shared", "accept", "draft%")]
    for r in rows:
        out.append("%-20s %7d %8d %8d %6.1f %9.3f %9.3f %8.3f %8.3f "
                   "%5d/%-3d %7d %8d %7.1f %6d %6.3f %7.1f" % (
                       r["label"][:20],
                       r["requests"] + r["gen_requests"], r["tokens"],
                       r["decode_steps"], r["decode_occupancy_pct"],
                       r["ttft_p50_ms"], r["ttft_p99_ms"],
                       r["itl_p50_ms"], r["itl_p99_ms"],
                       r["kv_blocks_used"], r["kv_blocks_total"],
                       r["kv_alloc_failures"], r["preemptions"],
                       r.get("prefix_hit_rate_pct", 0.0),
                       r.get("blocks_shared", 0),
                       r.get("spec_accept_rate", 0.0),
                       r.get("draft_overhead_pct", 0.0)))
    return "\n".join(out)


def scale_rows(dumps):
    """Scale-observatory rollup (ISSUE 12): per process dump, the
    resource-ledger gauges the collector mirrors into the always-on
    registry — pending-grad footprint, reply/replay cache bytes and
    their metered evictions, the live barrier set, apply backlog and
    oldest-pending age, hier fan-in buffers, fastwire socket
    population, and the quorum-bookkeeping work counter.  Works on any
    trace OR flight dump (the metrics snapshot rides both); flight
    dumps additionally carry the full ledger time series under their
    'ledger' key."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        rows.append({
            "label": d.get("label", "?"),
            "pending_bytes": val("ledger_pserver_pending_grad_bytes"),
            "pending_entries": val(
                "ledger_pserver_pending_grad_entries"),
            "reply_cache_bytes": val(
                "ledger_pserver_reply_cache_bytes"),
            "reply_evictions": val(
                "pserver_reply_cache_evictions_total"),
            "replay_cache_bytes": val("ledger_rpc_replay_cache_bytes"),
            "replay_evictions": val("rpc_replay_cache_evictions_total"),
            "barrier_set": val("ledger_pserver_barrier_set"),
            "apply_backlog_rounds": val(
                "ledger_pserver_apply_backlog_rounds"),
            "oldest_pending_age_s": val(
                "ledger_pserver_oldest_pending_age_s"),
            "hier_fanin_bytes": val("ledger_hier_fanin_bytes"),
            "fastwire_conns": val("ledger_fastwire_server_conns"),
            "quorum_scan_ops": val("pserver_quorum_scan_ops_total"),
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_scale_table(rows):
    out = ["%-22s %12s %8s %12s %7s %12s %7s %8s %8s %8s %10s" % (
        "process", "pending_B", "entries", "reply_B", "replyEv",
        "replay_B", "rplyEv", "barrier", "backlog", "oldest_s",
        "scan_ops")]
    for r in rows:
        out.append(
            "%-22s %12d %8d %12d %7d %12d %7d %8d %8d %8.2f %10d" % (
                r["label"][:22], r["pending_bytes"],
                r["pending_entries"], r["reply_cache_bytes"],
                r["reply_evictions"], r["replay_cache_bytes"],
                r["replay_evictions"], r["barrier_set"],
                r["apply_backlog_rounds"], r["oldest_pending_age_s"],
                r["quorum_scan_ops"]))
    return "\n".join(out)


def slo_rows(dumps):
    """Watchtower SLO rollup (ISSUE 13): per process dump, the
    per-spec burn-rate gauges the evaluator mirrors into the always-on
    registry (``slo_burn_fast_<name>`` / ``slo_burn_slow_<name>`` /
    ``slo_budget_remaining_<name>``) plus the alert counters.  Works
    on any trace OR flight dump — the metrics snapshot rides both;
    flight dumps written by a firing alert additionally carry the
    offending series under their top-level 'slo' key."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        prefix = "slo_burn_fast_"
        names = sorted(k[len(prefix):] for k in m
                       if k.startswith(prefix))
        alerts = val("slo_alerts_total")
        active = val("slo_alerts_active")
        if not names:
            if alerts or active:
                rows.append({"label": d.get("label", "?"), "slo": "",
                             "burn_fast": 0.0, "burn_slow": 0.0,
                             "budget_remaining": 1.0,
                             "alerts_total": alerts,
                             "alerts_active": active})
            continue
        for n in names:
            rows.append({
                "label": d.get("label", "?"), "slo": n,
                "burn_fast": round(val("slo_burn_fast_" + n, 0.0), 4),
                "burn_slow": round(val("slo_burn_slow_" + n, 0.0), 4),
                "budget_remaining": round(
                    val("slo_budget_remaining_" + n, 1.0), 4),
                "alerts_total": alerts,
                "alerts_active": active,
            })
    rows.sort(key=lambda r: (r["label"], r["slo"]))
    return rows


def format_slo_table(rows):
    out = ["%-22s %-28s %10s %10s %10s %7s %7s" % (
        "process", "slo", "burn_fast", "burn_slow", "budget_rem",
        "alerts", "active")]
    for r in rows:
        out.append("%-22s %-28s %10.2f %10.2f %10.2f %7d %7d" % (
            r["label"][:22], r["slo"][:28], r["burn_fast"],
            r["burn_slow"], r["budget_remaining"], r["alerts_total"],
            r["alerts_active"]))
    return "\n".join(out)


def moe_rows(dumps):
    """MoE routing rollup (ISSUE 15 rider): per process dump, the
    capacity-factor stats the moe_ffn routing shard feeds the
    always-on registry — routed steps/tokens, per-expert load
    distribution (balance), dropped-token fraction and router entropy.
    Works on any trace or flight dump (the metrics snapshot rides
    both)."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        def hist(name, field, default=0.0):
            return (m.get(name) or {}).get(field, default)

        steps = val("moe_router_steps_total")
        tokens = val("moe_tokens_total")
        if not steps and not tokens:
            continue
        rows.append({
            "label": d.get("label", "?"),
            "steps": steps,
            "tokens": tokens,
            "dropped_tokens": val("moe_dropped_tokens_total"),
            "dropped_frac": round(val("moe_dropped_token_frac", 0.0),
                                  4),
            "router_entropy": round(val("moe_router_entropy", 0.0), 4),
            "expert_load_p50": hist("moe_expert_load_tokens", "p50"),
            "expert_load_p99": hist("moe_expert_load_tokens", "p99"),
            "expert_load_mean": round(
                hist("moe_expert_load_tokens", "mean"), 2),
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_moe_table(rows):
    out = ["%-22s %7s %9s %9s %9s %9s %9s %9s %9s" % (
        "process", "steps", "tokens", "dropped", "drop_frac",
        "entropy", "load_p50", "load_p99", "load_mean")]
    for r in rows:
        out.append("%-22s %7d %9d %9d %9.4f %9.4f %9.4g %9.4g %9.4g"
                   % (r["label"][:22], r["steps"], r["tokens"],
                      r["dropped_tokens"], r["dropped_frac"],
                      r["router_entropy"], r["expert_load_p50"],
                      r["expert_load_p99"], r["expert_load_mean"]))
    return "\n".join(out)


def weaver_rows(dumps):
    """Weaver schedule-exploration rollup (ISSUE 18 satellite): per
    process dump, how much of the interleaving space the explorer
    covered — schedules executed, sibling branches the sleep-set
    pruning skipped, failing schedules found, and the decision length
    of the last minimized repro.  tools/weaver.py leaves a dump when
    FLAGS_telemetry_dump_dir is set, so CI runs roll up here."""
    rows = []
    for d in dumps:
        m = d.get("metrics", {})

        def val(name, default=0):
            return (m.get(name) or {}).get("value", default)

        explored = val("weaver_schedules_explored_total")
        pruned = val("weaver_schedules_pruned_total")
        if not explored and not pruned:
            continue
        rows.append({
            "label": d.get("label", "?"),
            "explored": explored,
            "pruned": pruned,
            "pruned_pct": round(
                100.0 * pruned / (explored + pruned), 1)
            if (explored + pruned) else 0.0,
            "failures": val("weaver_failures_total"),
            "minimized_len": val("weaver_minimized_trace_len"),
        })
    rows.sort(key=lambda r: r["label"])
    return rows


def format_weaver_table(rows):
    out = ["%-24s %9s %9s %8s %9s %8s" % (
        "process", "explored", "pruned", "pruned%", "failures",
        "min_len")]
    for r in rows:
        out.append("%-24s %9d %9d %8.1f %9d %8d" % (
            r["label"][:24], r["explored"], r["pruned"],
            r["pruned_pct"], r["failures"], r["minimized_len"]))
    return "\n".join(out)


def format_phase_table(rows, top=0):
    out = ["%-32s %7s %10s %9s %9s %9s %7s" % (
        "phase", "count", "total_ms", "mean_ms", "p50_ms", "p99_ms",
        "share")]
    for r in (rows[:top] if top else rows):
        out.append("%-32s %7d %10.3f %9.3f %9.3f %9.3f %6.1f%%" % (
            r["name"][:32], r["count"], r["total_ms"], r["mean_ms"],
            r["p50_ms"], r["p99_ms"], 100.0 * r["share"]))
    return "\n".join(out)
