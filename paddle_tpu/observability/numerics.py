"""Numerics observatory: on-device tensor-health guards, gradient
telemetry, and first-bad-op forensics (ISSUE 8 tentpole).

The reference framework's only numerics debugger is
``FLAGS_check_nan_inf`` (operator.cc:590) — a serial per-op host check
that forfeits whole-block compilation.  Here the default instrument is
a **fused on-device health reduction**: for every watched tensor of a
compiled block (gradients, written persistables, AMP-cast activations,
fetches) the lowering appends a tiny stats vector

    [finite_bit, nan_count, inf_count, absmax, l2sq]

and packs ALL of them into ONE small f32 array emitted as an extra
output of the jitted step — the step stays a single dispatch, XLA fuses
the reductions into the existing pipeline, and the host reads back a
few hundred bytes every ``FLAGS_check_numerics_every`` steps.

``FLAGS_check_numerics`` drives escalation:

  off      nothing (the default; zero trace or runtime cost)
  metrics  feed the always-on registry: grad_global_norm histogram,
           param_absmax gauge, numerics_nonfinite_total counter
  guard    additionally raise NumericsError and write a
           ``numerics_<pid>_<n>.json`` flight dump (trip site, step or
           round cid, stats snapshot, recent loss history) the moment
           any watched tensor's finite bit trips
  bisect   guard, plus automatically re-run the tripped step through
           the op-by-op path with per-op output checks to name the
           FIRST offending op, its input stats and program location
           (the prepared path snapshots pre-step state each step so
           the forensic re-run starts from the exact same values —
           the expensive debug tier, see PROFILE_r08.md)

The legacy ``FLAGS_check_nan_inf`` now maps onto this machinery on the
prepared path (guard+bisect semantics) instead of being refused — see
MIGRATION.md "check_nan_inf on the prepared path".
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque

import numpy as np

from paddle_tpu.core.flags import FLAGS, define_flag

from . import metrics as _metrics
from .trace import TRACER

__all__ = [
    "NumericsError", "effective_mode", "trace_enabled", "select_watched",
    "pack_health", "decode_health", "np_stats", "HealthMonitor",
    "dump_numerics", "check_op_outputs", "server_check_grad",
    "note_loss", "recent_losses", "reset",
]

define_flag("check_numerics", "off",
            "numerics observatory mode: 'off' (default) | 'metrics' "
            "(fused on-device health stats per watched tensor feed the "
            "always-on registry: grad_global_norm / param_absmax / "
            "numerics_nonfinite_total) | 'guard' (metrics + raise "
            "NumericsError and write numerics_<pid>_<n>.json the "
            "moment a watched tensor goes nonfinite) | 'bisect' "
            "(guard + re-run the tripped step op-by-op to name the "
            "FIRST offending op and its input stats).  The health "
            "reduction rides the compiled step as ONE extra fetch — "
            "the hot path stays a single dispatch "
            "(tools/telemetry_overhead.py gates metrics-mode overhead "
            "at < 2% of the prepared step)")
define_flag("check_numerics_every", 16,
            "host read-back cadence of the on-device health array in "
            "metrics/guard modes (nan/inf in a persistable is sticky, "
            "so a trip within the window is still caught at its edge); "
            "bisect checks every step — its forensic re-run needs the "
            "pre-step snapshot of exactly the tripped step")

MODES = ("off", "metrics", "guard", "bisect")

# per-tensor stats vector layout (f32): finite_bit is 1.0 when the
# tensor contains no nan/inf — the aggregate trip condition is
# ``any finite_bit == 0``
STAT_FIELDS = ("finite", "nan", "inf", "absmax", "l2sq")

# bound the per-tensor table embedded in a dump artifact
MAX_DUMP_STATS = 256
LOSS_HISTORY = 64

_loss_ring = deque(maxlen=LOSS_HISTORY)
_seq_lock = threading.RLock()  # signal-safe, same rationale as flight.py
_seq = 0
_server_trips = set()  # (round, sender) pairs already dumped

_M_NONFINITE = _metrics.counter(
    "numerics_nonfinite_total",
    "nan+inf elements observed across watched tensors")
_M_CHECKS = _metrics.counter(
    "numerics_checks_total", "host read-backs of the health array")
_M_TRIPS = _metrics.counter(
    "numerics_trips_total", "guard/bisect trips (NumericsError raised)")
_M_PS_NONFINITE = _metrics.counter(
    "pserver_nonfinite_grads_total",
    "inbound wire gradients containing nan/inf (per tensor)")
_H_GRAD_NORM = _metrics.histogram(
    "grad_global_norm",
    "global L2 norm over watched gradients per health read-back")
_G_PARAM_ABSMAX = _metrics.gauge(
    "param_absmax", "max |value| over watched persistables")


class NumericsError(FloatingPointError):
    """A numerics guard tripped.  Carries forensics when known:
    ``op_type``/``var``/``location`` (bisect's first bad op),
    ``stats`` (the decoded health snapshot), ``flight_path`` (the
    numerics_*.json artifact, when one was written)."""

    def __init__(self, message, op_type=None, var=None, location=None,
                 stats=None, flight_path=None):
        super().__init__(message)
        self.op_type = op_type
        self.var = var
        self.location = location
        self.stats = stats
        self.flight_path = flight_path


def effective_mode():
    """The active mode, with the legacy FLAGS_check_nan_inf mapped onto
    bisect (reference semantics: training stops at the first bad op,
    named) when check_numerics itself is off."""
    m = str(FLAGS.check_numerics or "off").lower()
    if m not in MODES:
        raise ValueError(
            "FLAGS_check_numerics=%r: want one of %s" % (m, "|".join(MODES)))
    if m == "off" and FLAGS.check_nan_inf:
        return "bisect"
    return m


def trace_enabled():
    """True when compiled blocks must emit the health output (any mode
    but off).  Part of the executor compile-cache key: toggling the
    observatory must never serve an executable without the fetch."""
    return effective_mode() != "off"


def reset():
    """Test hook: clear process-level trip/loss state."""
    _loss_ring.clear()
    _server_trips.clear()


# ---------------------------------------------------------------------------
# watched-tensor selection + the traced health reduction
# ---------------------------------------------------------------------------

def _is_float_desc(vd):
    if vd is None:
        return False
    try:
        from paddle_tpu.core.types import proto_to_np_dtype
        return np.issubdtype(np.dtype(proto_to_np_dtype(vd.dtype)),
                             np.floating)
    except Exception:
        return False


def select_watched(program, block, core_ops, persist_outs, fetch_list):
    """The watch list of one compiled block, fixed before tracing so
    the health rows align with ``entry.watched``:

    - written persistables (params + optimizer state, post-update),
    - PARAMETER gradients (``<persistable>@GRAD`` — what flows into
      the optimizer or onto the pserver wire),
    - the fetch list (losses/metrics — the guard that makes a pure
      inference run trip on a nonfinite output),
    - under AMP, outputs of autocast (MXU-bound) ops — the bf16
      activations whose overflow is mixed precision's expected failure
      mode (Micikevicius et al., 2018).

    ACTIVATION gradients are deliberately NOT watched: fetching a
    temporary forces XLA to materialize it, un-fusing the backward
    chain it would otherwise disappear into (measured ~80% step
    overhead on a small MLP vs <2% for this list) — and any nonfinite
    activation grad lands in a parameter grad within the same step, so
    the guard still trips on the step it happens.

    Only float-declared vars qualify; order is sorted for determinism.
    """
    from paddle_tpu.core.lowering import AMP_AUTOCAST_OPS as amp_ops

    amp = bool(getattr(program, "amp_bf16", False))

    def persistable(name):
        vd = block.find_var_recursive(name)
        return vd is not None and vd.persistable

    names = set()
    names.update(persist_outs)
    for op in core_ops:
        for n in op.output_arg_names():
            if not n:
                continue
            if n.endswith("@GRAD") and persistable(n[: -len("@GRAD")]):
                names.add(n)
            elif amp and op.type in amp_ops:
                names.add(n)
    names.update(n for n in fetch_list if n)
    out = []
    for n in sorted(names):
        if _is_float_desc(block.find_var_recursive(n)):
            out.append(n)
    return tuple(out)


def _traced_value(x):
    """The dense jax value behind an env entry (SelectedRows -> its
    values), or None when there is nothing float to reduce."""
    import jax.numpy as jnp

    if x is None:
        return None
    if hasattr(x, "values") and hasattr(x, "rows"):  # SelectedRows
        x = x.values
    if not hasattr(x, "dtype"):
        return None
    try:
        if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
            return None
    except Exception:
        return None
    return x


def pack_health(env, watched):
    """[n_watched, 5] f32 — the ONE extra output of the compiled step.

    Each tensor's four raw stats (finite count, nan count, abs-max,
    l2²) come out of ONE variadic ``lax.reduce`` — a single fused pass
    reading the tensor's existing buffer in place; the finite bit and
    inf count derive from them for free.  Alternatives measured on a
    128-hidden MLP step (tools/telemetry_overhead.py's metrics-mode
    gate): naive per-stat reductions cost ~40 µs of XLA-CPU kernel
    dispatch per tensor (+34% step), flat segmented reductions lower
    to serial scatters (+29x), and any pad+concat scheme that funnels
    params and their grads through one concatenate makes XLA insert
    defensive copies around the donated (in-place-updated) parameter
    buffers (+40%).  The variadic form measures at noise level."""
    import jax
    import jax.numpy as jnp

    rows = []
    for name in watched:
        x = _traced_value(env.get(name))
        if x is None or getattr(x, "size", 0) == 0:
            rows.append(jnp.array([1.0, 0.0, 0.0, 0.0, 0.0],
                                  jnp.float32))
            continue
        xf = x.astype(jnp.float32).reshape(-1)
        fin, nan, absmax, l2sq = jax.lax.reduce(
            (jnp.isfinite(xf).astype(jnp.float32),
             jnp.isnan(xf).astype(jnp.float32),
             # raw |x| keeps inf visible and nan propagates — the
             # finite bit is the guard, absmax is evidence
             jnp.abs(xf),
             xf * xf),
            (jnp.float32(0), jnp.float32(0), jnp.float32(-np.inf),
             jnp.float32(0)),
            lambda a, b: (a[0] + b[0], a[1] + b[1],
                          jnp.maximum(a[2], b[2]), a[3] + b[3]),
            (0,))
        size = jnp.float32(xf.shape[0])
        rows.append(jnp.stack([
            (fin == size).astype(jnp.float32), nan, size - fin - nan,
            absmax, l2sq]))
    return jnp.stack(rows)


def decode_health(health, watched):
    """Host-side view: {name: {finite, nan, inf, absmax, l2sq}}."""
    h = _to_host(health)
    out = {}
    for i, name in enumerate(watched):
        row = h[i]
        out[name] = {f: float(row[j]) for j, f in enumerate(STAT_FIELDS)}
    return out


def _to_host(v):
    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        return np.asarray(v.addressable_data(0))
    return np.asarray(v)


def np_stats(arr):
    """Host-side stats of one numpy-like value (server inbound checks,
    bisect input forensics): min/max/absmax + nan/inf counts."""
    a = np.asarray(arr)
    if a.size == 0 or not np.issubdtype(a.dtype, np.floating):
        return {"size": int(a.size), "nan": 0, "inf": 0}
    af = a.astype(np.float64, copy=False)
    nan = int(np.isnan(af).sum())
    inf = int(np.isinf(af).sum())
    finite = af[np.isfinite(af)]
    return {
        "size": int(a.size), "nan": nan, "inf": inf,
        "min": float(finite.min()) if finite.size else None,
        "max": float(finite.max()) if finite.size else None,
        "absmax": float(np.abs(finite).max()) if finite.size else None,
    }


# ---------------------------------------------------------------------------
# loss history (rides every dump: the "what was training doing" context)
# ---------------------------------------------------------------------------

def note_loss(value):
    """Record one per-step loss into the recent ring (fluid Trainer
    calls this; a no-op cheap enough to stay unconditional)."""
    try:
        _loss_ring.append(float(np.ravel(np.asarray(value))[0]))
    except Exception:
        pass


def recent_losses():
    return list(_loss_ring)


# ---------------------------------------------------------------------------
# the numerics flight dump
# ---------------------------------------------------------------------------

def _next_seq():
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def dump_numerics(reason, payload, directory=None):
    """Write numerics_<pid>_<n>.json; returns its path or None.

    Policy mirrors resilience.watchdog_error: write only when
    observability is opted into (FLAGS_telemetry_dump_dir configured,
    or tracing on — then fall back to the temp dir), so ordinary runs
    that trip a guard in a test loop don't litter /tmp.  The writer
    never raises — a diagnostic must not sink the error it annotates.
    """
    try:
        directory = directory or FLAGS.telemetry_dump_dir
        if not directory:
            if not TRACER.on:
                return None
            directory = tempfile.gettempdir()
        os.makedirs(directory, exist_ok=True)
        rec = {
            "kind": "numerics",
            "reason": str(reason),
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "label": TRACER.label or "",
            "mode": effective_mode(),
            "losses": recent_losses(),
        }
        rec.update(payload or {})
        path = os.path.join(
            directory, "numerics_%d_%d.json" % (os.getpid(), _next_seq()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------------
# host-side monitor: cadence, metrics, guard/bisect escalation
# ---------------------------------------------------------------------------

class HealthMonitor:
    """Per compiled-entry/prepared-program consumer of the health
    output.  ``observe(health)`` is the per-step hook: it counts the
    cadence, converts the device array only on read-back steps, feeds
    the metrics registry, and escalates per the active mode.  The
    ``rerun`` callable (bisect) re-executes the tripped step op-by-op
    and is expected to raise NumericsError naming the first bad op.

    Cadence contract: health checks happen on the FIRST step (an
    immediately-wrong config surfaces at step 1, not step N) and every
    ``FLAGS_check_numerics_every`` steps after; bisect checks every
    step — its pre-step snapshot must belong to exactly the tripped
    step.  Between checks a trip is still caught at the window edge:
    nan/inf in params/optimizer state is sticky under every optimizer
    update.  The prepared path asks ``want_health()`` BEFORE each step
    and dispatches its health-instrumented twin executable only on
    those steps, so the device-side stats pass (one memory pass over
    the watched bytes) amortizes by 1/every too — that is what keeps
    metrics mode under the 2% gate on bandwidth-bound models."""

    def __init__(self, watched, site):
        self.watched = tuple(watched)
        self.site = str(site)
        self._n = 0

    def _every(self):
        return max(1, int(FLAGS.check_numerics_every))

    def _is_check_step(self, n):
        return n == 1 or n % self._every() == 0

    def want_health(self):
        """True when the NEXT step should run with the health output
        (the prepared path picks its executable off this)."""
        mode = effective_mode()
        if mode == "off" or not self.watched:
            return False
        return mode == "bisect" or self._is_check_step(self._n + 1)

    def observe(self, health, cid=None, rerun=None, checked=None):
        """Record one completed step.  ``health`` is None on steps that
        ran without the health output (cadence-skipped); ``checked``
        forces/suppresses the read-back when the caller already applied
        the cadence at dispatch time."""
        mode = effective_mode()
        self._n += 1
        if mode == "off" or not self.watched or health is None:
            return
        if checked is None:
            checked = mode == "bisect" or self._is_check_step(self._n)
        if not checked:
            return
        stats = decode_health(health, self.watched)
        _M_CHECKS.inc()
        self._feed_metrics(stats)
        if mode == "metrics":
            return
        bad = [n for n, s in stats.items() if s["finite"] == 0.0]
        if not bad:
            return
        self._trip(mode, stats, bad, cid, rerun)

    def _feed_metrics(self, stats):
        grad_l2 = 0.0
        absmax = 0.0
        nonfinite = 0
        saw_grad = False
        for n, s in stats.items():
            nonfinite += int(s["nan"] + s["inf"])
            if n.endswith("@GRAD"):
                saw_grad = True
                if np.isfinite(s["l2sq"]):
                    grad_l2 += s["l2sq"]
            elif np.isfinite(s["absmax"]):
                absmax = max(absmax, s["absmax"])
        if saw_grad:
            _H_GRAD_NORM.observe(float(np.sqrt(grad_l2)))
        _G_PARAM_ABSMAX.set(absmax)
        if nonfinite:
            _M_NONFINITE.inc(nonfinite)

    def _trip(self, mode, stats, bad, cid, rerun):
        _M_TRIPS.inc()
        info = {
            "site": self.site,
            "step": self._n,
            "cid": cid,
            "trip_vars": bad[:32],
            "stats": dict(list(stats.items())[:MAX_DUMP_STATS]),
        }
        if mode == "bisect" and rerun is not None:
            try:
                rerun()
            except NumericsError as e:
                # check_op_outputs already wrote the forensics dump;
                # fold the trip context in only when it did not
                if e.flight_path is None:
                    e.flight_path = dump_numerics(
                        "bisect:%s" % self.site, info)
                e.stats = e.stats or stats
                raise
            # the forensic re-run did not reproduce (a genuinely
            # transient nonfinite, or nondeterminism outside the RNG
            # stream): report the guard trip with that caveat
            info["bisect"] = "rerun did not reproduce"
            path = dump_numerics("guard:%s" % self.site, info)
            raise NumericsError(
                "numerics guard tripped at %s (nonfinite in %s) but the "
                "op-by-op re-run did not reproduce it%s"
                % (self.site, bad[:8],
                   " | flight: %s" % path if path else ""),
                stats=stats, flight_path=path)
        path = dump_numerics("guard:%s" % self.site, info)
        raise NumericsError(
            "numerics guard tripped at %s: nonfinite values in %s "
            "(FLAGS_check_numerics=bisect re-runs the step op-by-op to "
            "name the first offending op)%s"
            % (self.site, bad[:8], " | flight: %s" % path if path else ""),
            stats=stats, flight_path=path)


# ---------------------------------------------------------------------------
# first-bad-op forensics (bisect re-run + the legacy op-by-op path)
# ---------------------------------------------------------------------------

def check_op_outputs(op, env, block_idx=0, op_idx=None):
    """Validate every float output of one eagerly-run op; on the first
    nan/inf, dump forensics (op type, program location, per-input
    stats) and raise NumericsError naming the op (reference
    FLAGS_check_nan_inf, operator.cc:590 — message kept compatible)."""
    import jax.numpy as jnp

    for name in op.output_arg_names():
        if not name:
            continue
        val = env.get(name)
        if val is None or not hasattr(val, "dtype"):
            continue
        if not jnp.issubdtype(jnp.result_type(val), jnp.floating):
            continue
        if bool(jnp.isfinite(val).all()):
            continue
        in_stats = {}
        for n in op.input_arg_names():
            if not n:
                continue
            v = env.get(n)
            if v is not None and hasattr(v, "dtype"):
                try:
                    in_stats[n] = np_stats(_to_host(v))
                except Exception:
                    pass
        location = {"block": int(block_idx),
                    "op_idx": None if op_idx is None else int(op_idx)}
        path = dump_numerics(
            "first_bad_op:%s" % op.type,
            {"first_bad_op": {"type": op.type, "output": name,
                              "output_stats": np_stats(_to_host(val)),
                              "inputs": in_stats, **location}})
        raise NumericsError(
            "operator %r produced nan/inf in output %r (block %d, op %s; "
            "input stats: %s)%s"
            % (op.type, name, location["block"], location["op_idx"],
               {k: (v.get("nan"), v.get("inf"), v.get("absmax"))
                for k, v in in_stats.items()},
               " | flight: %s" % path if path else ""),
            op_type=op.type, var=name, location=location,
            flight_path=path)


# ---------------------------------------------------------------------------
# pserver inbound attribution: a poisoned round names its trainer
# ---------------------------------------------------------------------------

def server_check_grad(name, arr, round_, sender):
    """Health-check one inbound wire gradient (rpc.VariableServer
    scatter handlers, outside the server lock).  Counts nonfinite
    arrivals always; dumps ONE numerics artifact per (round, sender)
    naming the round cid — so a poisoned round is attributable to the
    trainer that sent it (the fault_matrix 'numerics' preset asserts
    exactly this artifact)."""
    if effective_mode() == "off":
        return
    values = arr.values if hasattr(arr, "values") and \
        hasattr(arr, "rows") else arr
    a = np.asarray(values)
    if a.size == 0 or not np.issubdtype(a.dtype, np.floating):
        return
    if np.isfinite(a).all():
        return
    _M_PS_NONFINITE.inc()
    key = (int(round_ or 0), int(sender) if sender is not None else -1)
    if key in _server_trips:
        return
    _server_trips.add(key)
    from .trace import round_cid
    dump_numerics(
        "pserver_grad:%s" % name,
        {"cid": round_cid(key[0]), "round": key[0],
         "sender": None if sender is None else "%06x" % sender,
         "var": name, "stats": np_stats(a),
         "site": "pserver.scatter"})
