"""Watchtower SLO engine (ISSUE 13 tentpole b): declarative
objectives evaluated continuously against the time-series store, with
multi-window burn-rate alerting.

An SLO is "this metric, compared this way, against this threshold,
with this error budget": ``serve_request_ms.p99 <= 10`` with budget
0.01 means "at most 1% of sampled windows may show a p99 above
10 ms".  Specs load from a JSON/TOML file or an inline FLAGS string
(``FLAGS_slo_spec``); metrics name tsdb series the registry sampler
writes (tsdb.sample_registry), including the ``.p50/.p90/.p99``
histogram decompositions and a ``<counter>.rate`` suffix for
throughput floors (``pserver_rounds_applied_total.rate >= 1.0``).

Burn-rate alerting (the Google-SRE multi-window shape): per spec, the
fraction of BAD samples in a window divided by the budget is the burn
rate — 1.0 burns the budget exactly at the window's length.  Two
windows fire independently: a FAST window (default 300 s) with a high
threshold (default 14.0 — a sharp regression pages in minutes) and a
SLOW window (default 3600 s) with a low threshold (default 2.0 — a
simmering leak still surfaces).  A firing (slo, window):

- increments ``slo_alerts_total`` and joins ``slo_alerts_active``,
- mirrors its burn/budget into always-on gauges
  (``slo_burn_<window>_<name>``, ``slo_budget_remaining_<name>``) so
  every trace/flight dump and the trace_report --slo rollup carry it,
- writes ONE flight dump per (slo, window) per process (reason
  ``slo:<name>:<window>``) with the offending window's series
  embedded — the forensics artifact tools/fault_matrix.py's ``slo``
  preset asserts,
- is visible in BarrierStatus-style introspection (rpc.py attaches
  ``alerts_brief()`` to the pserver's BarrierStatus reply).

``ensure_evaluator()`` arms a background evaluation thread when
``FLAGS_slo_spec`` is set (cadence ``FLAGS_slo_eval_ms``); evaluation
cost is gated < 2% by tools/telemetry_overhead.py.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

from paddle_tpu.core.flags import FLAGS

from . import metrics as _metrics
from . import tsdb as _tsdb

__all__ = ["SLO", "Evaluator", "load_specs", "parse_objective",
           "install", "ensure_evaluator", "evaluate_once", "status",
           "active_alerts", "alerts_brief", "snapshot_for_flight",
           "reset"]

_M_ALERTS = _metrics.counter(
    "slo_alerts_total", "burn-rate alerts fired (one per slo x window "
    "transition into firing)")
_G_ACTIVE = _metrics.gauge(
    "slo_alerts_active", "slo x window pairs currently firing")

_OPS = {
    "<=": lambda v, th: v <= th,
    "<": lambda v, th: v < th,
    ">=": lambda v, th: v >= th,
    ">": lambda v, th: v > th,
    "==": lambda v, th: v == th,
    "!=": lambda v, th: v != th,
}
_OBJ_RE = re.compile(r"^\s*([A-Za-z0-9_.:-]+)\s*"
                     r"(<=|>=|==|!=|<|>)\s*([-+0-9.eE]+)\s*$")

DEFAULT_BUDGET = 0.01
DEFAULT_FAST_S = 300.0
DEFAULT_SLOW_S = 3600.0
DEFAULT_BURN_FAST = 14.0
DEFAULT_BURN_SLOW = 2.0
MIN_SAMPLES = 3


def _safe(name):
    return re.sub(r"[^A-Za-z0-9_]", "_", str(name))


class SLO:
    """One declarative objective.  ``metric`` names a tsdb series
    (with the optional ``.rate`` suffix); a sample is BAD when
    ``op(value, threshold)`` is False."""

    __slots__ = ("name", "metric", "op", "threshold", "budget",
                 "fast_s", "slow_s", "burn_fast", "burn_slow",
                 "min_samples")

    def __init__(self, metric, op, threshold, name=None,
                 budget=DEFAULT_BUDGET, fast_s=DEFAULT_FAST_S,
                 slow_s=DEFAULT_SLOW_S, burn_fast=DEFAULT_BURN_FAST,
                 burn_slow=DEFAULT_BURN_SLOW,
                 min_samples=MIN_SAMPLES):
        if op not in _OPS:
            raise ValueError("bad SLO op %r (want one of %s)"
                             % (op, "/".join(sorted(_OPS))))
        if not (0 < float(budget) <= 1):
            raise ValueError("SLO budget must be in (0, 1], got %r"
                             % (budget,))
        self.metric = str(metric)
        self.op = op
        self.threshold = float(threshold)
        self.name = _safe(name or self.metric)
        self.budget = float(budget)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn_fast = float(burn_fast)
        self.burn_slow = float(burn_slow)
        self.min_samples = int(min_samples)

    @property
    def objective(self):
        return "%s %s %g" % (self.metric, self.op, self.threshold)

    def good(self, value):
        return bool(_OPS[self.op](float(value), self.threshold))

    def to_dict(self):
        return {"name": self.name, "metric": self.metric,
                "objective": self.objective, "budget": self.budget,
                "fast_s": self.fast_s, "slow_s": self.slow_s,
                "burn_fast": self.burn_fast,
                "burn_slow": self.burn_slow}


def parse_objective(text):
    """'metric <= 10' -> (metric, op, threshold)."""
    m = _OBJ_RE.match(str(text))
    if not m:
        raise ValueError("bad SLO objective %r (want 'metric OP "
                         "number', OP in %s)"
                         % (text, "/".join(sorted(_OPS))))
    return m.group(1), m.group(2), float(m.group(3))


def _spec_from_dict(d):
    d = dict(d)
    if "objective" in d:
        metric, op, th = parse_objective(d.pop("objective"))
        d.setdefault("metric", metric)
        d.setdefault("op", op)
        d.setdefault("threshold", th)
    return SLO(d.pop("metric"), d.pop("op"), d.pop("threshold"), **d)


def _load_toml_slo(path):
    """TOML spec files: stdlib tomllib when available (3.11+), else a
    dependency-free subset parser — ``[[slo]]`` table arrays of
    ``key = value`` lines (quoted strings, numbers, booleans,
    ``#`` comments), which is exactly the shape an SLO file uses.
    Anything fancier should just use JSON."""
    try:
        import tomllib
    except ImportError:
        tomllib = None
    if tomllib is not None:
        with open(path, "rb") as f:
            return tomllib.load(f).get("slo", [])
    items = []
    current = None
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if line == "[[slo]]":
                current = {}
                items.append(current)
                continue
            if line.startswith("["):
                current = None      # some other table: not ours
                continue
            if "=" not in line or current is None:
                if current is None:
                    continue
                raise ValueError("bad TOML line %d in %r: %r"
                                 % (lineno, path, raw.rstrip()))
            key, val = (s.strip() for s in line.split("=", 1))
            if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
                current[key] = val[1:-1]
            elif val in ("true", "false"):
                current[key] = val == "true"
            else:
                try:
                    current[key] = int(val)
                except ValueError:
                    current[key] = float(val)
    return items


def load_specs(source):
    """SLO list from: a ``.json`` / ``.toml`` file path ({"slo":
    [...]} or a bare list, each entry an objective string or a dict),
    an inline comma-separated objective string
    (``serve_request_ms.p99<=10,pserver_rounds_applied_total.rate>=1``),
    or an already-built list/dict."""
    if isinstance(source, (list, tuple)):
        items = list(source)
    elif isinstance(source, dict):
        items = list(source.get("slo", []))
    else:
        text = str(source).strip()
        if not text:
            return []
        if text.endswith((".toml", ".json")):
            # a spec that LOOKS like a file path must be one: a typo'd
            # path silently re-parsed as inline objectives would
            # disable monitoring with no diagnostic
            if not os.path.exists(text):
                raise FileNotFoundError(
                    "SLO spec file %r does not exist" % text)
            if text.endswith(".toml"):
                items = _load_toml_slo(text)
            else:
                with open(text) as f:
                    data = json.load(f)
                items = data.get("slo", []) if isinstance(data, dict) \
                    else list(data)
        else:
            items = [t for t in text.split(",") if t.strip()]
    out = []
    for item in items:
        if isinstance(item, SLO):
            out.append(item)
        elif isinstance(item, dict):
            out.append(_spec_from_dict(item))
        else:
            metric, op, th = parse_objective(item)
            out.append(SLO(metric, op, th))
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError("duplicate SLO names: %r" % names)
    return out


class Evaluator:
    """Evaluate specs against a store; fire burn-rate alerts.

    ``dump_alerts=False`` turns the side effects off (watchtower's
    one-shot report evaluates somebody else's store and must not
    write flight dumps into it)."""

    def __init__(self, store, specs, dump_alerts=True):
        self.store = store
        self.specs = list(specs)
        self.dump_alerts = bool(dump_alerts)
        # REENTRANT, same invariant as metrics.py/ledger.py: a
        # signal-handler flight dump (snapshot_for_flight -> status())
        # landing on the very thread that is mid-evaluate must read
        # through the held lock instead of deadlocking inside its own
        # crash artifact
        from paddle_tpu.core.sanitizer import make_lock
        self._lock = make_lock("slo.evaluator", reentrant=True,
                               signal_safe=True)
        self._dumped = set()     # (name, window) ever dumped
        self._active = {}        # (name, window) -> since (unix time)
        self._status = []

    # -- math ----------------------------------------------------------
    def _window_eval(self, spec, t, v, burn_threshold, now):
        """Burn over the window already SLICED into (t, v).  The store
        is scanned once per spec — the fast window is a numpy mask of
        the slow window's arrays, never a second disk read."""
        import numpy as np

        n = int(len(v))
        if n == 0:
            return {"samples": 0, "bad": 0, "bad_frac": 0.0,
                    "burn": 0.0, "firing": False, "_t": t, "_v": v}
        # vectorized goodness: the comparison ops broadcast over the
        # whole window (the evaluator runs on cadence — gated < 2% of
        # FLAGS_slo_eval_ms by tools/telemetry_overhead.py)
        bad = n - int(np.count_nonzero(
            _OPS[spec.op](v, spec.threshold)))
        bad_frac = bad / n
        burn = bad_frac / spec.budget
        firing = n >= spec.min_samples and burn >= burn_threshold
        return {"samples": n, "bad": bad,
                "bad_frac": round(bad_frac, 6),
                "burn": round(burn, 4), "firing": firing,
                "_t": t, "_v": v}

    def evaluate(self, now=None):
        """One evaluation pass over every spec; returns (and caches)
        the status rows.  Alert side effects (counter, gauges, ONE
        flight dump per (slo, window)) fire AFTER the status rows are
        committed, so a first-evaluation alert's flight dump carries
        this pass's full status table, not the previous (possibly
        empty) one."""
        now = float(time.time() if now is None else now)
        rows = []
        pending = []     # (spec, window name, window dict + arrays)
        for spec in self.specs:
            # ONE store scan per spec (the slow window); the fast
            # window is a mask over the same arrays
            st, sv = _tsdb.series_values(self.store, spec.metric,
                                         now - spec.slow_s, now)
            mask = st >= now - spec.fast_s
            slow = self._window_eval(spec, st, sv, spec.burn_slow,
                                     now)
            fast = self._window_eval(spec, st[mask], sv[mask],
                                     spec.burn_fast, now)
            # last observed value straight from the slow window's
            # already-fetched array — never an unbounded store scan
            sv = slow.get("_v")
            last_v = float(sv[-1]) if sv is not None and len(sv) \
                else None
            # budget remaining over the SLOW window: the long-horizon
            # "how much error budget is left" number watchtower charts
            remaining = max(0.0, 1.0 - slow["bad_frac"] / spec.budget)
            row = {
                "name": spec.name, "metric": spec.metric,
                "objective": spec.objective, "budget": spec.budget,
                "last_value": (round(last_v, 6)
                               if last_v is not None else None),
                "budget_remaining": round(remaining, 4),
                "windows": {
                    "fast": dict(fast, window_s=spec.fast_s,
                                 burn_threshold=spec.burn_fast),
                    "slow": dict(slow, window_s=spec.slow_s,
                                 burn_threshold=spec.burn_slow),
                },
            }
            for wname in ("fast", "slow"):
                w = row["windows"][wname]
                _metrics.gauge(
                    "slo_burn_%s_%s" % (wname, spec.name),
                    "burn rate over the %s window" % wname
                ).set(w["burn"])
                # keep the window arrays for the deferred alert pass
                pending.append((spec, wname, dict(w)))
            _metrics.gauge(
                "slo_budget_remaining_%s" % spec.name,
                "error budget remaining (slow window)"
            ).set(row["budget_remaining"])
            # drop the raw window arrays from the cached status: the
            # offending series is materialized only into an alert's
            # flight dump (watchtower re-scans the store when it
            # wants the curve)
            for w in row["windows"].values():
                w.pop("_t", None)
                w.pop("_v", None)
            rows.append(row)
        with self._lock:
            self._status = rows
        for spec, wname, w in pending:
            self._alert(spec, wname, w, now)
        with self._lock:
            _G_ACTIVE.set(len(self._active))
        return rows

    # -- alerts --------------------------------------------------------
    def _alert(self, spec, window, w, now):
        key = (spec.name, window)
        with self._lock:
            was_active = key in self._active
            if w["firing"] and not was_active:
                self._active[key] = now
            elif not w["firing"] and was_active:
                self._active.pop(key, None)
            newly = w["firing"] and not was_active
            need_dump = newly and self.dump_alerts \
                and key not in self._dumped
            if need_dump:
                self._dumped.add(key)
        if not newly:
            return
        _M_ALERTS.inc()
        if not need_dump:
            return
        # ONE flight dump per (slo, window) per process, carrying the
        # offending window's series — the alert's forensics artifact
        series = [[round(float(a), 3), float(b)]
                  for a, b in zip(w.get("_t", ()), w.get("_v", ()))]
        try:
            from . import flight
            flight.dump(
                "slo:%s:%s" % (spec.name, window),
                blocked={"slo": spec.name, "window": window,
                         "burn": w["burn"],
                         "objective": spec.objective},
                sections={"slo": {
                    "alert": {"slo": spec.name, "window": window,
                              "burn": w["burn"],
                              "burn_threshold": w["burn_threshold"],
                              "bad_frac": w["bad_frac"],
                              "samples": w["samples"],
                              "objective": spec.objective,
                              "budget": spec.budget,
                              "series": series},
                    "status": self.status(),
                    "alerts": self.active_alerts(),
                }})
        except Exception:
            pass

    # -- introspection -------------------------------------------------
    def status(self):
        """The cached status rows (already array-free — the raw
        window series never enter the cache)."""
        with self._lock:
            return [dict(r) for r in self._status]

    def active_alerts(self):
        with self._lock:
            return [{"slo": name, "window": win,
                     "since": round(since, 3)}
                    for (name, win), since in sorted(
                        self._active.items())]


# ---------------------------------------------------------------------
# process-wide evaluator
# ---------------------------------------------------------------------

_EVAL = None
# reentrant: install() is callable both directly and from inside
# ensure_evaluator's locked section
from paddle_tpu.core.sanitizer import make_lock as _make_lock
_eval_lock = _make_lock("slo.install", reentrant=True)
_eval_thread = None
_eval_stop = None


def install(store=None, specs=None, dump_alerts=True):
    """Build (and adopt as the process evaluator) an Evaluator over
    ``store`` (default: the FLAGS_tsdb_dir default store) and
    ``specs`` (default: FLAGS_slo_spec).  The background loop (if
    armed) re-reads the process evaluator each tick, so a later
    install() genuinely replaces what runs AND what introspection
    reports."""
    global _EVAL
    store = store or _tsdb.default_store()
    if store is None:
        raise ValueError("no tsdb store (set FLAGS_tsdb_dir or pass "
                         "store=)")
    if specs is None:
        specs = load_specs(FLAGS.slo_spec)
    elif not isinstance(specs, (list, tuple)):
        specs = load_specs(specs)
    with _eval_lock:
        _EVAL = Evaluator(store, specs, dump_alerts=dump_alerts)
        return _EVAL


def ensure_evaluator():
    """Arm the background evaluation thread once per process when
    FLAGS_slo_spec names specs (cadence FLAGS_slo_eval_ms; 0
    disables).  Idempotent — called from tsdb.ensure_sampler so the
    sampler and the evaluator arm as one plane.  A broken spec is a
    loud warning, never a silent no-monitoring state."""
    global _eval_thread, _eval_stop
    if not FLAGS.slo_spec or int(FLAGS.slo_eval_ms) <= 0:
        return None
    with _eval_lock:
        if _EVAL is None:
            try:
                install()
            except Exception as e:
                import warnings
                warnings.warn(
                    "FLAGS_slo_spec=%r did not arm the SLO "
                    "evaluator: %s — burn-rate alerting is OFF"
                    % (FLAGS.slo_spec, e))
                return None
        if _eval_thread is not None:
            return _eval_thread
        _eval_stop = threading.Event()
        t = threading.Thread(target=_eval_loop, args=(_eval_stop,),
                             daemon=True, name="slo-evaluator")
        _eval_thread = t
        t.start()
        return t


def _eval_loop(stop):
    while not stop.is_set():
        ms = int(FLAGS.slo_eval_ms)
        if stop.wait(max(ms, 10) / 1000.0):
            break
        # re-read each tick: a later install() swaps what runs, so
        # the loop and the introspection surface never split
        ev = _EVAL
        if ev is None:
            continue
        try:
            ev.evaluate()
        except Exception:
            pass


def evaluate_once():
    """One synchronous evaluation of the process evaluator (tests,
    tools); None when none is installed."""
    ev = _EVAL
    if ev is None:
        return None
    return ev.evaluate()


def status():
    ev = _EVAL
    return ev.status() if ev is not None else []


def active_alerts():
    ev = _EVAL
    return ev.active_alerts() if ev is not None else []


def alerts_brief():
    """['name:window', ...] of currently-firing alerts — the
    BarrierStatus-sized summary rpc.py attaches to its introspection
    reply."""
    return ["%s:%s" % (a["slo"], a["window"])
            for a in active_alerts()]


def snapshot_for_flight():
    """The flight-recorder payload: spec status + active alerts, or
    None when no evaluator is installed (the envelope still carries
    the key — tests/test_flight_schema.py pins that)."""
    ev = _EVAL
    if ev is None:
        return None
    return {"status": ev.status(), "alerts": ev.active_alerts()}


def reset():
    """Drop the process evaluator and its thread (tests)."""
    global _EVAL, _eval_thread, _eval_stop
    with _eval_lock:
        stop, _eval_thread, _eval_stop = _eval_stop, None, None
        _EVAL = None
    if stop is not None:
        stop.set()
