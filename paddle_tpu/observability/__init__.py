"""Unified telemetry layer (ISSUE 6): step-scoped tracing, always-on
metrics, chrome-trace export with distributed round correlation, and a
hang flight recorder.

  trace      span API + process tracer (FLAGS_telemetry gates; the
             disabled hot path is one attribute read)
  metrics    counters/gauges/histograms, always on; Prometheus text +
             JSON snapshot exports
  export     merge per-process dumps (+ xplane device traces) into one
             chrome://tracing JSON; per-phase breakdown rows
  flight     dump the ring + open spans + metrics + resource ledgers
             on watchdog timeout, wall-budget expiry, injected
             faults, SIGTERM/SIGALRM
  ledger     (ISSUE 12) per-subsystem resource ledgers: pserver
             pending grads / reply cache / barrier quorum / apply
             backlog, client replay cache, hier fan-in buffers,
             fastwire sockets — incremental byte/entry counters
             sampled by a low-rate collector into ledger_* gauges +
             a bounded time-series ring; FLAGS_ledger_watch turns a
             crossed threshold into a flight dump (collapse
             forensics for tools/scale_bench.py)
  numerics   (ISSUE 8) on-device tensor-health guards: fused per-step
             health reduction over watched tensors, four-mode
             escalation (FLAGS_check_numerics =
             off|metrics|guard|bisect), numerics_*.json forensics
             incl. first-bad-op bisection; imported lazily by its
             consumers (executor, rpc, trainer)
  tsdb       (ISSUE 13) Watchtower time-series store: a background
             sampler appends every counter/gauge/histogram-percentile
             (and the refreshed ledger) to size-bounded append-only
             binary segments under FLAGS_tsdb_dir, with range-scan /
             downsample / rate() queries and byte-bounded retention —
             the durable history slo.py, tools/watchtower.py and
             tools/perf_sentinel.py read
  slo        (ISSUE 13) declarative SLOs (FLAGS_slo_spec: JSON/TOML
             file or inline objectives) evaluated continuously
             against the tsdb with multi-window burn-rate alerting:
             a firing (slo, window) bumps slo_alerts_total, writes
             ONE flight dump embedding the offending series, and is
             visible in BarrierStatus introspection; both imported
             lazily by their consumers

Instrumented sites: core/executor_impl (step/feed/dispatch/sync spans,
compile-cache + step counters), distributed/rpc (send/gather/barrier/
apply spans carrying the (round, sender, seq) wire identity as a
correlation id, dedup/replay counters), distributed/fastwire (wire
byte counters), kernels (Pallas launch-site spans), fluid/trainer and
fluid/profiler (RecordEvent is now a telemetry span).

See README "Observability" and tools/trace_report.py.
"""
from . import metrics  # noqa: F401
from . import trace  # noqa: F401
from .trace import TRACER, round_cid  # noqa: F401

__all__ = ["trace", "metrics", "TRACER", "round_cid"]
