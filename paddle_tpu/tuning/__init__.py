"""Persistent shape-keyed autotune cache (ISSUE 7 tentpole, part 2).

Every tile-size sweep this repo has run (`tools/conv_tune.py`,
`tools/flash_tune.py`, `tools/matmul_tune.py`) used to evaporate after
the run: the numbers went into a PROFILE_*.md table and a human carried
the winners back into kernel defaults by hand.  This module makes
tuning persistent and self-applying:

- Sweep tools ``record()`` their best configuration per
  (kernel, shape, dtype, backend) into ONE JSON file under
  ``FLAGS_autotune_cache_dir``.
- Kernel lowerings ``lookup()`` the cache at compile time (trace time —
  compile-cache-miss cadence, zero per-step cost) and shape their
  Pallas grid/block specs from the hit; a miss falls back to the
  built-in defaults, so the cache is purely an accelerant.
- ``fingerprint()`` rides the executor compile-cache key: a re-tuned
  cache can never serve a stale executable.

The cache file is human-readable JSON (inspect/edit/commit it per rig);
a corrupt or missing file degrades to defaults without error — tuning
state must never be able to sink a training run.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["lookup", "record", "fingerprint", "cache_path", "entries",
           "make_key", "default_backend", "invalidate"]

CACHE_FILE = "autotune_cache.json"

_lock = threading.RLock()
# (path, mtime_ns) -> parsed entries; in-process writes bump _version so
# the executor compile-cache key changes even before the file mtime is
# re-read
_loaded = {"path": None, "mtime": None, "entries": {}}
_version = 0


def _dir():
    from paddle_tpu.core.flags import FLAGS

    return getattr(FLAGS, "autotune_cache_dir", "") or ""


def cache_path():
    """Path of the cache file, or None when the flag is unset."""
    d = _dir()
    return os.path.join(d, CACHE_FILE) if d else None


def default_backend():
    """Platform the computation will run on ('tpu'/'cpu'/...), matching
    the kernels' own platform pick (flash_attention.target_platform)."""
    try:
        from paddle_tpu.kernels.flash_attention import target_platform
        return target_platform()
    except Exception:
        return "cpu"


def make_key(kernel, shape, dtype, backend):
    """'kernel|128x64x256|float32|tpu' — the one canonical key form."""
    if isinstance(shape, (list, tuple)):
        shape = "x".join(str(int(s)) for s in shape)
    return "|".join((str(kernel), str(shape), str(dtype), str(backend)))


def _load():
    """Parsed entries of the current cache file, mtime-memoized.
    Missing or corrupt file -> {} (and the bad state is remembered so a
    broken file is not re-parsed on every lookup)."""
    path = cache_path()
    if path is None:
        return {}
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = -1
    with _lock:
        if _loaded["path"] == path and _loaded["mtime"] == mtime:
            return _loaded["entries"]
        entries_ = {}
        if mtime != -1:
            try:
                with open(path) as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    raw = data.get("entries", data)
                    if isinstance(raw, dict):
                        entries_ = {k: v for k, v in raw.items()
                                    if isinstance(v, dict)}
            except Exception:
                entries_ = {}   # corrupt -> defaults, never an error
        _loaded.update(path=path, mtime=mtime, entries=entries_)
        return entries_


def invalidate():
    """Forget the memoized file state (tests flip FLAGS mid-process)."""
    with _lock:
        _loaded.update(path=None, mtime=None, entries={})


def entries():
    """All cached entries ({key: entry dict}); {} when disabled."""
    return dict(_load())


def lookup(kernel, shape, dtype, backend=None):
    """The tuned config dict for (kernel, shape, dtype, backend), or
    None.  Called at trace time by kernel lowerings; a miss means
    'use the built-in defaults'."""
    if not _dir():
        return None
    if backend is None:
        backend = default_backend()
    e = _load().get(make_key(kernel, shape, dtype, backend))
    if not e:
        return None
    cfg = e.get("config")
    return dict(cfg) if isinstance(cfg, dict) else None


def record(kernel, shape, dtype, config, ms=None, backend=None,
           source=None):
    """Persist a sweep winner.  Read-modify-write under the module lock
    with a crash-safe atomic replace; no-op (returns False) when
    FLAGS_autotune_cache_dir is unset."""
    global _version

    path = cache_path()
    if path is None:
        return False
    if backend is None:
        backend = default_backend()
    key = make_key(kernel, shape, dtype, backend)
    entry = {"config": dict(config)}
    if ms is not None:
        entry["ms"] = round(float(ms), 4)
    if source:
        entry["source"] = str(source)
    entry["recorded_unix"] = int(time.time())
    with _lock:
        from paddle_tpu.core.fsutil import atomic_write

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        cur = dict(_load())
        cur[key] = entry
        atomic_write(path, json.dumps(
            {"version": 1, "entries": cur}, indent=1, sort_keys=True))
        _version += 1
        invalidate()
    return True


def fingerprint():
    """Token for the executor compile-cache key: changes whenever the
    cache directory, the file on disk, or an in-process record() does —
    so lowerings that consulted the cache are recompiled, never reused
    stale.  Cheap: one stat when enabled, a constant when not."""
    d = _dir()
    if not d:
        return ("", 0, 0)
    path = os.path.join(d, CACHE_FILE)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = -1
    return (d, mtime, _version)
