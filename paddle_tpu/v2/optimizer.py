"""v2 optimizers (reference python/paddle/v2/optimizer.py wrapping the
swig ParameterUpdater).  Each maps onto the fluid optimizer family —
one jitted update fused into the training step, not a per-parameter
updater loop."""
from __future__ import annotations

import paddle_tpu.fluid as fluid

__all__ = ["Optimizer", "Momentum", "Adam", "Adamax", "AdaGrad",
           "DecayedAdaGrad", "AdaDelta", "RMSProp",
           "L1Regularization", "L2Regularization", "ModelAverage"]


class L2Regularization:
    def __init__(self, rate):
        self.rate = float(rate)

    def to_fluid(self):
        return fluid.regularizer.L2DecayRegularizer(self.rate)


class L1Regularization:
    def __init__(self, rate):
        self.rate = float(rate)

    def to_fluid(self):
        return fluid.regularizer.L1DecayRegularizer(self.rate)


class ModelAverage:
    """Accepted for signature parity; the fluid ModelAverage wrapper is
    the supported route (fluid/average.py)."""

    def __init__(self, average_window, max_average_window=None,
                 do_average_in_cpu=False):
        self.average_window = average_window


class Optimizer:
    def __init__(self, learning_rate=1e-3, regularization=None,
                 model_average=None, gradient_clipping_threshold=None,
                 learning_rate_decay_a=0.0, learning_rate_decay_b=0.0,
                 learning_rate_schedule=None, **kwargs):
        self.learning_rate = learning_rate
        self.regularization = regularization
        self.model_average = model_average
        self.gradient_clipping_threshold = gradient_clipping_threshold
        # decaying schedules rode the v1 trainer's sample counter; the
        # fluid lr-scheduler layers are the supported route — fail loud
        # rather than silently train at a constant lr
        if learning_rate_schedule not in (None, "constant"):
            raise NotImplementedError(
                "learning_rate_schedule=%r: use "
                "fluid.layers.learning_rate_scheduler (exponential/"
                "polynomial/piecewise decay) with fluid.optimizer"
                % (learning_rate_schedule,))

    def _reg(self):
        return self.regularization.to_fluid() \
            if self.regularization is not None else None

    def _apply_clip(self, topo):
        """Install the v1 per-parameter L2-norm clip before minimize
        (reference gradient_clipping_threshold semantics)."""
        if not self.gradient_clipping_threshold:
            return
        import paddle_tpu.fluid.clip as fclip
        fclip.set_gradient_clip(
            fclip.GradientClipByNorm(self.gradient_clipping_threshold),
            program=topo.main_program)

    def to_fluid(self):
        raise NotImplementedError

    def enable_types(self):  # reference-API shim
        return []


class Momentum(Optimizer):
    def __init__(self, momentum=None, sparse=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum or 0.0
        self.sparse = sparse

    def to_fluid(self):
        if not self.momentum:
            return fluid.optimizer.SGD(
                learning_rate=self.learning_rate,
                regularization=self._reg())
        return fluid.optimizer.Momentum(
            learning_rate=self.learning_rate, momentum=self.momentum,
            regularization=self._reg())


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_fluid(self):
        return fluid.optimizer.Adam(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon,
            regularization=self._reg())


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def to_fluid(self):
        return fluid.optimizer.Adamax(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, regularization=self._reg())


class AdaGrad(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Adagrad(
            learning_rate=self.learning_rate, regularization=self._reg())


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-06, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return fluid.optimizer.DecayedAdagrad(
            learning_rate=self.learning_rate, decay=self.rho,
            epsilon=self.epsilon, regularization=self._reg())


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-06, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return fluid.optimizer.Adadelta(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon, regularization=self._reg())


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return fluid.optimizer.RMSProp(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon, regularization=self._reg())
