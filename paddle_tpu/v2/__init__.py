"""v2-era API (reference python/paddle/v2).

Round-3 state was a data-utilities shim that *raised* on the graph API;
this package closes the last census row: ``layer`` / ``activation`` /
``pooling`` / ``attr`` / ``data_type`` / ``optimizer`` / ``parameters``
/ ``trainer`` / ``event`` / ``networks`` / ``infer`` are thin builders
over the fluid stack (see each module's docstring for the reference
anchor).  A reference v2 script over the ported layer subset
(``layer.py __all__``: data/fc/embedding/conv/pool/bn/sequence/lstm/
gru/recurrent_group+memory/mixed+projections/beam_search generation/seq_concat/expand/cost layers) — layers declared at import time,
``parameters.create(cost)``, ``trainer.SGD(...).train(reader)`` — runs
with only the import line changed; unported v1 layer names raise with
their fluid equivalent named.

The *mechanics* differ on purpose: layer calls build a deferred DAG
that materializes into ONE fluid Program (a single XLA computation),
not a per-layer gserver config — same API, TPU-native execution.
"""
from __future__ import annotations

from paddle_tpu import batch  # noqa: F401  (paddle.v2.batch == paddle.batch)
from paddle_tpu import dataset  # noqa: F401
from paddle_tpu import reader  # noqa: F401
from paddle_tpu.dataset import image  # noqa: F401  (paddle.v2.image)

from . import minibatch  # noqa: F401

from . import activation  # noqa: F401
from . import attr  # noqa: F401
from . import config_base  # noqa: F401
from . import data_type  # noqa: F401
from . import evaluator  # noqa: F401
from . import event  # noqa: F401
from . import layer  # noqa: F401
from . import networks  # noqa: F401
from . import optimizer  # noqa: F401
from . import parameters  # noqa: F401
from . import pooling  # noqa: F401
from . import topology  # noqa: F401
from . import trainer  # noqa: F401
from .inference import Inference, infer  # noqa: F401

__all__ = ["init", "batch", "reader", "dataset", "infer", "Inference",
           "layer", "activation", "pooling", "attr", "data_type",
           "optimizer", "parameters", "trainer", "event", "networks",
           "topology", "config_base", "image", "minibatch", "evaluator"]

_initialized = False


def init(use_gpu=False, trainer_count=1, **kwargs):
    """v2 bootstrap (reference v2/__init__.py init: parses flags, seeds
    devices).  Device selection happens per-Executor here; this records
    the call and validates the arguments."""
    global _initialized
    if trainer_count < 1:
        raise ValueError("trainer_count must be >= 1")
    _initialized = True
