"""v2 training events (reference python/paddle/v2/event.py).

``metrics`` replaces the reference's swig Evaluator handle: a plain
dict of name -> float for the batch/pass (e.g.
``classification_error_evaluator``)."""
from __future__ import annotations

__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "EndForwardBackward", "TestResult"]


class WithMetric:
    def __init__(self, metrics):
        self.metrics = dict(metrics or {})


class TestResult(WithMetric):
    def __init__(self, cost, metrics=None):
        super().__init__(metrics)
        self.cost = cost


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, metrics=None):
        super().__init__(metrics)
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, metrics=None):
        super().__init__(metrics)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
